"""Adapter registry: small checkpoints on disk, stacked pages on device.

The multi-tenant serving contract is "adding a tenant changes data,
never programs". This module is the data side:

- **disk**: :func:`save_adapter` writes the adapter pytree through the
  crash-safe checkpoint stack (staging dir + fsync + crc32 + atomic
  publish — PR 1 machinery unchanged) with a ``format: "lora_adapter"``
  metadata record carrying rank/alpha/targets/dropout and the BASE-model
  fingerprint. :func:`load_adapter` verifies both: a full checkpoint
  refused as an adapter, an adapter refused onto the wrong base — each a
  hard, named error;
- **device**: :class:`AdapterStore` keeps up to ``max_loaded`` adapters
  resident in ONE preallocated pytree per target layer —
  ``(A_stack [S, in, r], B_stack [S, r, out])`` with ``S = max_loaded +
  1`` and row 0 the reserved zero adapter (= base model). Loading a
  tenant is a row write into the stack (``.at[slot].set``), evicting is
  forgetting a row — buffer updates, never recompiles. The serving
  programs take the whole stack as a plain jit input and gather per-slot
  rows in-program (:func:`~paddle_tpu.lora.layers.adapter_rows`);
- **residency**: LRU over unpinned rows. The engine pins a row for the
  lifetime of every request decoding against it, so eviction can never
  swap an adapter out from under a live stream.
"""
from __future__ import annotations

import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

import jax.numpy as jnp

from .layers import (LoraConfig, applied_config, base_fingerprint,
                     is_lora_param, lora_paths, lora_state)

__all__ = ["ADAPTER_FORMAT", "AdapterError", "AdapterFormatError",
           "AdapterStore", "save_adapter", "load_adapter",
           "adapter_metadata", "normalize_adapter_id"]

ADAPTER_FORMAT = "lora_adapter"

BASE_ADAPTER = "base"   # reserved name for stack row 0 (the zero adapter)


def normalize_adapter_id(adapter_id):
    """Collapse the reserved ``"base"`` alias onto ``None`` (the zero
    adapter). Every boundary that accepts an adapter id (server/router
    submit, engine admit) normalizes through THIS helper, so one tenant
    key can never split into two cache namespaces or metrics rows."""
    return None if adapter_id == BASE_ADAPTER else adapter_id


class AdapterError(RuntimeError):
    """A registry operation failed host-side BEFORE any device dispatch
    (unknown adapter, every slot pinned) — the serving loop fails just
    the offending request, never the engine."""


class AdapterFormatError(ValueError):
    """A checkpoint is not what the caller pointed at: a full model
    checkpoint fed to the adapter loader, an adapter checkpoint fed to a
    full restore, or an adapter whose base fingerprint / LoRA geometry
    does not match the serving model."""


# -------------------------------------------------------------- disk side
def save_adapter(directory: str, model, *, async_: bool = False):
    """Save ``model``'s adapter pytree as a (tiny) crash-safe checkpoint.

    The metadata records ``format: "lora_adapter"``, the LoRA geometry
    and the base-model fingerprint, so :func:`load_adapter` /
    :class:`AdapterStore` can hard-reject mismatched loads. Returns the
    async save handle when ``async_`` (see ``checkpoint.save_state``)."""
    from ..distributed.checkpoint import save_state

    config = applied_config(model)
    if config is None:
        raise ValueError(
            f"{type(model).__name__} has no LoRA injection to save; "
            f"apply_lora(model, config) / Model.fit(lora=...) first")
    extra = {"format": ADAPTER_FORMAT,
             "lora": {**config.to_dict(),
                      "base_fingerprint": base_fingerprint(model),
                      "base_model": type(model).__name__}}
    return save_state(lora_state(model), directory, async_=async_,
                      extra_meta=extra)


def adapter_metadata(directory: str) -> dict:
    """The ``lora`` metadata record of an adapter checkpoint (raises
    :class:`AdapterFormatError` for non-adapter directories)."""
    try:
        with open(os.path.join(directory, "metadata.json")) as f:
            meta = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        raise AdapterFormatError(
            f"{directory}: not a readable checkpoint directory: {e}"
        ) from e
    if meta.get("format") != ADAPTER_FORMAT:
        raise AdapterFormatError(
            f"{directory} is not a LoRA adapter checkpoint (format="
            f"{meta.get('format')!r}); full model checkpoints load via "
            f"checkpoint.load_state / Model.load, not the adapter "
            f"registry")
    return dict(meta.get("lora") or {})


def load_adapter(directory: str, model=None) -> Tuple[Dict, dict]:
    """Load an adapter checkpoint: ``(adapter_state, lora_meta)``.

    With ``model`` (a LoRA-applied network), the checkpoint's recorded
    base fingerprint and LoRA geometry are verified against it —
    mismatch is a hard :class:`AdapterFormatError`, because an adapter
    trained against a different base would load cleanly and serve
    garbage."""
    from ..distributed.checkpoint import load_state

    meta = adapter_metadata(directory)
    if model is not None:
        _check_compatible(directory, meta, model)
    state = load_state(directory)
    bad = sorted(k for k in state if not is_lora_param(k))
    if bad:
        raise AdapterFormatError(
            f"{directory}: adapter checkpoint contains non-adapter "
            f"leaves (e.g. {bad[:3]}) — corrupt metadata?")
    return state, meta


def _check_compatible(directory: str, meta: dict, model) -> None:
    config = applied_config(model)
    if config is None:
        raise AdapterFormatError(
            f"cannot load adapter {directory} into a model without a "
            f"LoRA injection; apply_lora(model, config) first")
    want_fp = base_fingerprint(model)
    got_fp = meta.get("base_fingerprint")
    if got_fp is not None and got_fp != want_fp:
        raise AdapterFormatError(
            f"{directory}: adapter was trained against base model "
            f"{meta.get('base_model')!r} (fingerprint {got_fp}); this "
            f"model's fingerprint is {want_fp} — refusing to serve an "
            f"adapter on the wrong base")
    for field in ("rank", "alpha", "dropout"):
        got = meta.get(field)
        want = getattr(config, field)
        if got is not None and float(got) != float(want):
            raise AdapterFormatError(
                f"{directory}: adapter {field}={got} does not match the "
                f"model's injection {field}={want}; adapters in one "
                f"registry must share the stacked-page geometry")


# ------------------------------------------------------------ device side
class AdapterStore:
    """Device-resident multi-adapter registry for ONE injected model.

    ``register``/``load`` put adapters in the host registry; the first
    request for a tenant stages its pages into a stack row
    (:meth:`acquire`), evicting the least-recently-used unpinned row when
    full. All registry mutation is host-side metadata plus shape-stable
    row writes — the compiled serving programs never change.

    Thread-safe: the serving worker acquires/releases; router threads
    read :meth:`resident`/:meth:`known` for placement affinity.
    """

    def __init__(self, model, config: Optional[LoraConfig] = None,
                 max_loaded: int = 8):
        from .layers import apply_lora

        applied = applied_config(model)
        if applied is None:
            if config is None:
                raise ValueError(
                    "AdapterStore needs a LoRA-applied model or a "
                    "LoraConfig to apply (pass config=)")
            apply_lora(model, config)
            applied = config
        elif config is not None and config != applied:
            raise ValueError(
                f"model is injected with {applied}, store asked for "
                f"{config}; one geometry per model")
        if int(max_loaded) < 1:
            raise ValueError(f"max_loaded must be >= 1, got {max_loaded}")
        self.model = model
        self.config = applied
        self.fingerprint = base_fingerprint(model)
        self.paths = lora_paths(model)
        self.max_loaded = int(max_loaded)
        self.slots = self.max_loaded + 1      # +1: reserved zero row 0
        st = model.__dict__["_lora_applied"]
        # two-lock discipline (tpu_lint R7): `_lock` guards the host
        # metadata maps and is held only for dict/int work — the router's
        # placement probes (resident/known/salt), the metrics collectors
        # (stats) and the engine's release path contend it every request
        # and must never stall behind device work. `_write_lock`
        # serializes page STAGING (the .at[slot].set H2D writes) and is
        # taken only by writers (acquire's miss path, register's
        # refresh); it is always acquired FIRST, `_lock` only inside it
        # — one global order, so R6 stays cycle-free.
        self._lock = threading.Lock()
        self._write_lock = threading.Lock()
        self._tick = 0
        # row bookkeeping: _names[s] is the adapter resident in row s
        self._names: List[Optional[str]] = [BASE_ADAPTER] + \
            [None] * self.max_loaded
        self._by_name: Dict[str, int] = {}
        self._pins = [0] * self.slots
        self._last_use = [0] * self.slots
        self._host: Dict[str, Dict[str, np.ndarray]] = {}
        # bumped on every register() of a name: the prefix-cache digest
        # salt embeds it, so pushing a new adapter VERSION orphans the
        # K/V blocks the old weights computed (they age out via LRU)
        self._versions: Dict[str, int] = {}
        self.loads = 0
        self.evictions = 0
        self.tensors = {}
        for path in self.paths:
            (a_shape, b_shape) = st.shapes[path]
            a_ref = model._get_by_path(f"{path}.lora_A")
            self.tensors[path] = (
                jnp.zeros((self.slots,) + tuple(a_shape), a_ref.dtype),
                jnp.zeros((self.slots,) + tuple(b_shape), a_ref.dtype))
        self.page_bytes = int(sum(
            a.nbytes + b.nbytes for a, b in self.tensors.values()
        ) // self.slots)

    # ------------------------------------------------------------- intake
    def _as_pages(self, state: Dict) -> Dict[str, Tuple[np.ndarray,
                                                        np.ndarray]]:
        """Validate a flat adapter pytree against this store's geometry
        and regroup it per layer path."""
        st = self.model.__dict__["_lora_applied"]
        pages = {}
        seen = set()
        for path in self.paths:
            a_key, b_key = f"{path}.lora_A", f"{path}.lora_B"
            if a_key not in state or b_key not in state:
                raise AdapterFormatError(
                    f"adapter state lacks {a_key!r}/{b_key!r}; it was "
                    f"saved from a different injection "
                    f"(target_modules/model mismatch)")
            a = np.asarray(state[a_key])
            b = np.asarray(state[b_key])
            want_a, want_b = st.shapes[path]
            if a.shape != tuple(want_a) or b.shape != tuple(want_b):
                raise AdapterFormatError(
                    f"adapter leaf shapes {a.shape}/{b.shape} at "
                    f"{path!r} do not match the store geometry "
                    f"{want_a}/{want_b} (rank mismatch?)")
            pages[path] = (a, b)
            seen.update((a_key, b_key))
        extra = sorted(set(state) - seen)
        if extra:
            raise AdapterFormatError(
                f"adapter state carries unexpected leaves (e.g. "
                f"{extra[:3]}) — saved from a wider injection?")
        return pages

    def register(self, name: str, state: Dict) -> None:
        """Host-register an adapter pytree under ``name``. Re-registering
        a name replaces it (and refreshes its device pages if resident —
        the adapter-update path)."""
        if not name or name == BASE_ADAPTER:
            raise ValueError(
                f"adapter name must be a non-empty string != "
                f"{BASE_ADAPTER!r}, got {name!r}")
        pages = self._as_pages(state)
        with self._write_lock:
            with self._lock:
                slot = self._by_name.get(name)
                if slot is not None and self._pins[slot] > 0:
                    slot = None     # live streams: never rewrite in place
            staged = self._stage_pages(slot, pages) \
                if slot is not None else None
            with self._lock:
                self._host[name] = pages
                self._versions[name] = self._versions.get(name, 0) + 1
                cur = self._by_name.get(name)
                if cur is not None and self._pins[cur] > 0:
                    # live streams are mid-decode against the OLD pages
                    # (a pin may have landed while we staged):
                    # publishing now would hand them mixed-version
                    # weights. Orphan the row instead — pinned streams
                    # keep it (it frees once they finish), the name
                    # unmaps so the next acquire() stages the NEW pages
                    # into a fresh row. The staged write is discarded.
                    del self._by_name[name]
                    self._names[cur] = None
                elif staged is not None and cur == slot:
                    # pages + version bump publish under ONE lock hold,
                    # so a concurrent acquire(with_salt=True) can never
                    # pair the new salt with the old pages (or vice
                    # versa) — the PR-9 namespace invariant
                    self.tensors = staged
                    self.loads += 1
                elif cur is not None:
                    del self._by_name[name]
                    self._names[cur] = None

    def load(self, name: str, directory: str) -> None:
        """Load an adapter checkpoint from ``directory`` and register it
        as ``name`` — fingerprint/geometry mismatches are hard errors."""
        state, _ = load_adapter(directory, self.model)
        self.register(name, state)

    # ---------------------------------------------------------- residency
    def _stage_pages(self, slot: int, pages: Dict) -> Dict:
        # a row write per target layer: shape-stable device updates (the
        # stacks stay jit inputs of unchanged aval — no recompile).
        # Builds the WHOLE new stack dict and returns it; the caller
        # publishes `self.tensors = staged` under `_lock` (one atomic
        # assignment, so dispatch-side readers see all-old or all-new).
        # Only `_write_lock` is held here — the metadata lock the
        # router/metrics threads contend is free during the H2D writes.
        staged = {}
        for path, (a_stack, b_stack) in self.tensors.items():
            a = a_stack.at[slot].set(  # tpu-lint: disable=R7(writer-only staging lock; the contended metadata lock is free)
                pages[path][0])
            b = b_stack.at[slot].set(  # tpu-lint: disable=R7(writer-only staging lock; the contended metadata lock is free)
                pages[path][1])
            staged[path] = (a, b)
        return staged

    def acquire(self, name: Optional[str], *, with_salt: bool = False):
        """Resolve ``name`` to a resident stack row and pin it (one pin
        per live request). ``None``/``"base"`` is row 0. Raises
        :class:`AdapterError` (host-side, pre-dispatch) for unknown
        adapters or when every row is pinned by live requests.

        ``with_salt`` returns ``(row, digest_salt)`` captured under ONE
        lock hold — the admission path needs the salt of exactly the
        version whose pages it just pinned; reading :meth:`salt`
        separately would race a concurrent :meth:`register` and stamp
        old-weight K/V into the new version's cache namespace."""
        if name is None or name == BASE_ADAPTER:
            with self._lock:
                self._pins[0] += 1
            return (0, b"") if with_salt else 0
        with self._lock:
            # resident fast path: pin + touch + salt under one hold —
            # no staging, so the write lock is never involved
            self._tick += 1
            slot = self._by_name.get(name)
            if slot is not None:
                self._pins[slot] += 1
                self._touch_locked(slot)
                if not with_salt:
                    return slot
                return slot, self._salt_locked(name)
        # miss: stage the pages with the metadata lock RELEASED (the
        # pre-fix shape held it across the .at[slot].set H2D writes,
        # stalling every placement probe — tpu_lint R7's poster child)
        with self._write_lock:
            with self._lock:
                slot = self._by_name.get(name)
                if slot is not None:        # a register() raced us in
                    self._pins[slot] += 1
                    self._touch_locked(slot)
                    if not with_salt:
                        return slot
                    return slot, self._salt_locked(name)
                pages = self._host.get(name)
                if pages is None:
                    raise AdapterError(
                        f"unknown adapter {name!r}; register() or load() "
                        f"it into the store first")
                slot = self._free_slot_locked()
                if slot is None:
                    raise AdapterError(
                        f"all {self.max_loaded} adapter rows are pinned "
                        f"by live requests; raise max_loaded (>= engine "
                        f"slots is always safe) or shed load")
                # reserve: a PINNED nameless row — _free_slot_locked
                # skips it, so no concurrent writer can steal the slot
                # while we stage outside the lock
                self._pins[slot] += 1
                self._touch_locked(slot)
            try:
                staged = self._stage_pages(slot, pages)
            except BaseException:
                with self._lock:
                    # roll the reservation back — guarded like release():
                    # a crash-recovery release_all() may have zeroed the
                    # pins while we staged outside `_lock`, and an
                    # unguarded decrement would underflow to -1 (making
                    # a later-pinned live row look evictable)
                    if self._pins[slot] > 0:
                        self._pins[slot] -= 1
                raise
            with self._lock:
                self.tensors = staged
                self.loads += 1
                self._names[slot] = name
                self._by_name[name] = slot
                if not with_salt:
                    return slot
                return slot, self._salt_locked(name)

    def _touch_locked(self, slot: int) -> None:
        self._last_use[slot] = self._tick

    def _free_slot_locked(self) -> Optional[int]:
        for s in range(1, self.slots):
            # a nameless row may still be PINNED (orphaned by a
            # re-register while streams decode against it) — not free
            if self._names[s] is None and self._pins[s] == 0:
                return s
        victim = None
        for s in range(1, self.slots):
            if self._pins[s] > 0:
                continue
            if victim is None or self._last_use[s] < self._last_use[victim]:
                victim = s
        if victim is None:
            return None
        old = self._names[victim]
        if old is not None:
            del self._by_name[old]
            self.evictions += 1
        self._names[victim] = None
        return victim

    def release(self, slot: int) -> None:
        """Drop one pin on ``slot`` (the engine calls this when the
        request leaves its engine slot)."""
        with self._lock:
            if 0 <= slot < self.slots and self._pins[slot] > 0:
                self._pins[slot] -= 1

    def release_all(self) -> None:
        """Crash-recovery sweep: the engine reset requeues every live
        request, so every pin it held is void."""
        with self._lock:
            self._pins = [0] * self.slots

    # ------------------------------------------------------------- lookup
    def salt(self, name: Optional[str]) -> bytes:
        """The prefix-cache digest-chain namespace for ``name`` — THE
        single source for both the engine's block identity and the
        router's affinity probe (a byte drift between the two would
        silently zero affinity). Embeds the registration version: a
        re-registered (updated) adapter gets a fresh namespace, so K/V
        blocks its OLD weights computed can never serve the new ones
        (stale blocks age out of the pool via LRU)."""
        if name is None or name == BASE_ADAPTER:
            return b""
        with self._lock:
            return self._salt_locked(name)

    def _salt_locked(self, name: str) -> bytes:
        return b"lora:%s@%d" % (str(name).encode(),
                                self._versions.get(name, 0))

    def known(self, name: Optional[str]) -> bool:
        """Registered (host side) — submit-time validation."""
        if name is None or name == BASE_ADAPTER:
            return True
        with self._lock:
            return name in self._host

    def resident(self, name: Optional[str]) -> bool:
        """Currently holding a device row — the router's adapter-affinity
        signal (placing a tenant where its pages are warm skips a load)."""
        if name is None or name == BASE_ADAPTER:
            return True
        with self._lock:
            return name in self._by_name

    def loaded(self) -> Dict[str, int]:
        """``{adapter_name: stack_row}`` of resident adapters."""
        with self._lock:
            return dict(self._by_name)

    def stats(self) -> dict:
        with self._lock:
            return {
                "max_loaded": self.max_loaded,
                "registered": len(self._host),
                "resident": len(self._by_name),
                "pinned_rows": sum(1 for s in range(1, self.slots)
                                   if self._pins[s] > 0),
                "loads": self.loads,
                "evictions": self.evictions,
                "page_bytes": self.page_bytes,
                "rank": self.config.rank,
            }

    def __repr__(self):
        s = self.stats()
        return (f"AdapterStore(resident={s['resident']}/{s['max_loaded']},"
                f" registered={s['registered']}, rank={s['rank']})")
