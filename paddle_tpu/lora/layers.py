"""LoRA injection: frozen-base low-rank adapters on existing layers.

Low-Rank Adaptation (arXiv:2106.09685) fine-tunes a frozen base model by
learning a rank-``r`` update per target projection: the layer computes
``W x + (alpha/r) * B (A x)`` with ``A [in, r]``, ``B [r, out]`` and only
``A``/``B`` trainable. At production scale this is the per-tenant story —
hundreds of tenants share ONE base model and each owns a pytree a few
thousand floats big.

The injection here deliberately does NOT restructure the model:
:func:`apply_lora` registers ``lora_A``/``lora_B`` as ordinary parameters
ON each target layer and hangs the delta off a forward-post hook, so

- base parameter *paths are unchanged* — base checkpoints load before or
  after injection, and the base-model fingerprint an adapter checkpoint
  pins is computed over exactly the paths a non-LoRA model has;
- every existing execution path (eager, ``functional_call`` under
  jit/grad, the compiled generate/serve programs) picks the delta up for
  free: the hook runs inside the layer's ``__call__``;
- ``B`` initializes to zeros, so an injected model is bit-identical to
  the base until training moves the adapter.

Two application modes, selected at trace time:

- **solo** (default): the hook reads the layer's own ``lora_A``/``lora_B``
  — the single-adapter path used by training and solo ``generate``;
- **batched rows** (:func:`adapter_rows`): the serving engine activates a
  per-batch-row adapter context — each target layer receives gathered
  ``(A, B)`` pages of shape ``[B, in, r]`` / ``[B, r, out]`` and applies a
  per-row contraction, so ONE compiled decode program serves a batch
  mixing arbitrary tenants (row 0 of the page stack is the zero adapter =
  the base model). Both modes share one einsum formulation, so a tenant's
  served stream is token-identical to its solo generate.
"""
from __future__ import annotations

import contextlib
import hashlib
import json
import threading
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from ..nn import functional as F
from ..nn.initializer import Constant, Normal
from ..nn.layer import Layer

__all__ = ["LoraConfig", "apply_lora", "applied_config", "lora_paths",
           "lora_state", "set_adapter", "clear_adapter", "is_lora_param",
           "base_fingerprint", "adapter_rows"]

_LORA_LEAVES = ("lora_A", "lora_B")


@dataclass(frozen=True)
class LoraConfig:
    """Adapter geometry shared by training, the registry and serving.

    - ``rank``: the low-rank bottleneck ``r`` (optimizer state and
      adapter checkpoints scale with it, not with the model);
    - ``alpha``: the delta is scaled by ``alpha / rank`` (the LoRA-paper
      convention, so sweeping ``rank`` keeps the update magnitude);
    - ``target_modules``: leaf-layer names to inject (e.g.
      ``("qkv_proj", "fc_in")``); ``None`` asks the model via its
      ``lora_spec()`` (GPT/Llama families provide attention + MLP
      projections);
    - ``dropout``: input dropout on the adapter branch, training only.
    """

    rank: int = 8
    alpha: float = 16.0
    target_modules: Optional[Tuple[str, ...]] = None
    dropout: float = 0.0

    def __post_init__(self):
        if int(self.rank) < 1:
            raise ValueError(f"rank must be >= 1, got {self.rank}")
        if not 0.0 <= float(self.dropout) < 1.0:
            raise ValueError(
                f"dropout must be in [0, 1), got {self.dropout}")
        if self.target_modules is not None:
            object.__setattr__(self, "target_modules",
                               tuple(self.target_modules))

    @property
    def scaling(self) -> float:
        return float(self.alpha) / float(self.rank)

    def to_dict(self) -> dict:
        return {"rank": int(self.rank), "alpha": float(self.alpha),
                "target_modules": (None if self.target_modules is None
                                   else list(self.target_modules)),
                "dropout": float(self.dropout)}


# ------------------------------------------------- batched adapter context
# Trace-time state: the serving engine pushes a {layer_path: (A_rows,
# B_rows)} dict around its functional_call so every hook reached under the
# trace applies the per-row pages instead of the layer's own adapter.
# thread-local because each engine worker traces on its own thread.
_CTX = threading.local()


def _ctx_stack() -> list:
    stack = getattr(_CTX, "stack", None)
    if stack is None:
        stack = _CTX.stack = []
    return stack


def _current_rows() -> Optional[dict]:
    stack = _ctx_stack()
    return stack[-1] if stack else None


@contextlib.contextmanager
def adapter_rows(pages, rows):
    """Activate per-row adapter pages for every LoRA hook reached under
    this context (trace-time, thread-local).

    ``pages`` maps layer path -> ``(A_stack [S, in, r], B_stack [S, r,
    out])`` — the registry's device-resident stacked buffer; ``rows`` is
    the (possibly traced) ``[B]`` vector of stack rows, one per batch
    row (0 = the zero adapter = base model). The gather happens here, in
    program, so which tenants share the batch is pure DATA — admitting or
    evicting a tenant never retraces."""
    idx = jnp.asarray(rows, jnp.int32)
    if idx.ndim == 0:
        idx = idx[None]
    ctx = {path: (jnp.take(a, idx, axis=0), jnp.take(b, idx, axis=0))
           for path, (a, b) in pages.items()}
    _ctx_stack().append(ctx)
    try:
        yield
    finally:
        _ctx_stack().pop()


def _delta_rows(x, a_rows, b_rows, scaling):
    """The one adapter contraction both modes share: ``x [B, ..., in]``
    against per-row ``a_rows [B, in, r]`` / ``b_rows [B, r, out]``. A
    single formulation (same dot_generals, same reduction order) is what
    makes a tenant's batched served stream bit-identical to its solo
    generate."""
    a_rows = a_rows.astype(x.dtype)
    b_rows = b_rows.astype(x.dtype)
    t = jnp.einsum("b...i,bir->b...r", x, a_rows)
    return jnp.einsum("b...r,bro->b...o", t, b_rows) * jnp.asarray(
        scaling, x.dtype)


class _LoraHook:
    """Forward-post hook carrying one target layer's adapter math."""

    __slots__ = ("path", "config")

    def __init__(self, path: str, config: LoraConfig):
        self.path = path
        self.config = config

    def __call__(self, layer, inputs, output):
        x = inputs[0]
        if self.config.dropout and layer.training:
            x = F.dropout(x, p=self.config.dropout, training=True)
        ctx = _current_rows()
        if ctx is not None:
            try:
                a_rows, b_rows = ctx[self.path]
            except KeyError:
                raise KeyError(
                    f"adapter_rows context active but holds no pages for "
                    f"layer {self.path!r} — the AdapterStore was built "
                    f"for a different injection (target_modules "
                    f"mismatch?)") from None
        else:
            a, b = layer.lora_A, layer.lora_B
            batch = x.shape[0]
            a_rows = jnp.broadcast_to(a[None], (batch,) + a.shape)
            b_rows = jnp.broadcast_to(b[None], (batch,) + b.shape)
        return output + _delta_rows(x, a_rows, b_rows, self.config.scaling)


@dataclass
class _LoraApplied:
    """Bookkeeping :func:`apply_lora` leaves on the model instance."""

    config: LoraConfig
    paths: List[str]
    shapes: Dict[str, Tuple[Tuple[int, ...], Tuple[int, ...]]]
    hooks: dict


def _resolve_targets(model: Layer, config: LoraConfig) -> Tuple[str, ...]:
    if config.target_modules is not None:
        return tuple(config.target_modules)
    spec = getattr(model, "lora_spec", None)
    if spec is None:
        raise ValueError(
            f"{type(model).__name__} has no lora_spec() and the "
            f"LoraConfig names no target_modules; pass target_modules= "
            f"explicitly (leaf layer names, e.g. ('qkv_proj', 'fc_in'))")
    return tuple(spec()["target_modules"])


def applied_config(model: Layer) -> Optional[LoraConfig]:
    """The :class:`LoraConfig` a model was injected with (None = base)."""
    st = model.__dict__.get("_lora_applied")
    return st.config if st is not None else None


def lora_paths(model: Layer) -> List[str]:
    """Paths of the injected target layers, in traversal order."""
    st = model.__dict__.get("_lora_applied")
    if st is None:
        raise ValueError(f"{type(model).__name__} has no LoRA injection; "
                         f"call apply_lora(model, config) first")
    return list(st.paths)


def apply_lora(model: Layer, config: LoraConfig) -> Layer:
    """Inject LoRA branches into ``model``'s target projections, in place.

    Each matched leaf layer (by name, among layers exposing
    ``in_features``/``out_features``) gains parameters ``lora_A``
    ``[in, rank]`` (Normal(0, 0.02)) and ``lora_B`` ``[rank, out]``
    (zeros — injection is a numeric no-op until training) plus the delta
    hook. GSPMD shardings follow the base weight: a column-parallel
    target shards ``lora_B`` over "mp", a row-parallel target shards
    ``lora_A``, so tensor-parallel serving needs no adapter gathers.

    Idempotent under the SAME config; a second call with a different
    config raises (un-inject by rebuilding the model)."""
    existing = model.__dict__.get("_lora_applied")
    if existing is not None:
        if existing.config == config:
            return model
        raise ValueError(
            f"model already carries a LoRA injection with "
            f"{existing.config}; refusing to stack {config} on top — "
            f"rebuild the model to change adapter geometry")
    targets = _resolve_targets(model, config)
    paths: List[str] = []
    shapes: Dict[str, Tuple[Tuple[int, ...], Tuple[int, ...]]] = {}
    hooks = {}
    for path, layer in model.named_sublayers():
        name = path.rsplit(".", 1)[-1]
        if name not in targets:
            continue
        in_f = getattr(layer, "in_features", None)
        out_f = getattr(layer, "out_features", None)
        if in_f is None or out_f is None:
            raise ValueError(
                f"LoRA target {path!r} has no in_features/out_features — "
                f"only linear-style projections can carry an adapter "
                f"(got {type(layer).__name__})")
        layer.add_parameter("lora_A", layer.create_parameter(
            (in_f, config.rank),
            default_initializer=Normal(0.0, 0.02)))
        layer.add_parameter("lora_B", layer.create_parameter(
            (config.rank, out_f), default_initializer=Constant(0.0)))
        base_spec = layer._param_shardings.get("weight")
        if base_spec == (None, "mp"):
            layer.set_param_sharding("lora_B", (None, "mp"))
        elif base_spec == ("mp", None):
            layer.set_param_sharding("lora_A", ("mp", None))
        hook = _LoraHook(path, config)
        hooks[path] = layer.register_forward_post_hook(hook)
        paths.append(path)
        shapes[path] = ((in_f, config.rank), (config.rank, out_f))
    if not paths:
        raise ValueError(
            f"no layer of {type(model).__name__} matched LoRA "
            f"target_modules {targets!r}")
    model.__dict__["_lora_applied"] = _LoraApplied(
        config=config, paths=paths, shapes=shapes, hooks=hooks)
    return model


# --------------------------------------------------------- adapter pytree
def is_lora_param(path: str) -> bool:
    """True for adapter leaves (``...lora_A`` / ``...lora_B``) — the
    trainable-set predicate ``Model.fit(lora=...)`` hands the train
    step."""
    return path.rsplit(".", 1)[-1] in _LORA_LEAVES


def lora_state(model: Layer) -> Dict[str, jnp.ndarray]:
    """The adapter pytree: flat ``{param_path: array}`` over the injected
    ``lora_A``/``lora_B`` leaves only — the thing :func:`AdapterStore
    <paddle_tpu.lora.store.AdapterStore>` saves, loads and stacks."""
    lora_paths(model)  # raises when not injected
    return {k: v for k, v in model.named_parameters() if is_lora_param(k)}


def set_adapter(model: Layer, state: Dict) -> Layer:
    """Write an adapter pytree (from :func:`lora_state` or an adapter
    checkpoint) into the model's injected leaves. Missing or unexpected
    keys are an error — a truncated adapter silently serving the base
    model is exactly the bug this refuses to allow."""
    want = set(lora_state(model))
    got = set(state)
    if want != got:
        missing = sorted(want - got)[:3]
        extra = sorted(got - want)[:3]
        raise ValueError(
            f"adapter state does not match this model's injection: "
            f"{len(want - got)} missing (e.g. {missing}), "
            f"{len(got - want)} unexpected (e.g. {extra})")
    for k, v in state.items():
        cur = model._get_by_path(k)
        arr = jnp.asarray(v)
        if tuple(cur.shape) != tuple(arr.shape):
            raise ValueError(
                f"adapter leaf {k!r} has shape {tuple(arr.shape)}, model "
                f"expects {tuple(cur.shape)} (rank mismatch?)")
        model._set_by_path(k, arr.astype(cur.dtype))
    return model


def clear_adapter(model: Layer) -> Layer:
    """Zero the injected leaves — back to exact base-model behaviour."""
    for k, v in lora_state(model).items():
        model._set_by_path(k, jnp.zeros_like(v))
    return model


def base_fingerprint(model: Layer) -> str:
    """Structural fingerprint of the BASE model an adapter belongs to:
    a digest over the model class plus every non-LoRA parameter's
    ``(path, shape, dtype)``. Cheap (no device readback) and stable
    across injection — an adapter checkpoint records it and the registry
    refuses to load an adapter onto a different architecture. It
    identifies the architecture, not the weight VALUES: pair it with
    base-checkpoint provenance when several same-shaped bases coexist."""
    rows = sorted(
        (k, tuple(int(d) for d in v.shape), str(v.dtype))
        for k, v in model.named_parameters() if not is_lora_param(k))
    raw = json.dumps([type(model).__name__, rows]).encode()
    return hashlib.blake2b(raw, digest_size=16).hexdigest()
