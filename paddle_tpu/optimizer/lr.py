"""LR schedulers (reference: ``python/paddle/optimizer/lr.py``, ~20 schedulers).

Dual API: paddle-style stateful ``step()``/``get_lr()``, plus ``value_at(step)``
which is pure and traceable — the jitted train step computes LR from the
optimizer's step counter so schedules live inside the compiled program.
"""
from __future__ import annotations

import math

import jax.numpy as jnp


class LRScheduler:
    def __init__(self, learning_rate=0.1, last_epoch=-1, verbose=False):
        self.base_lr = learning_rate
        self.last_epoch = last_epoch
        self.verbose = verbose
        self.step()

    # stateful API ---------------------------------------------------------
    def step(self, epoch=None):
        if epoch is None:
            self.last_epoch += 1
        else:
            self.last_epoch = epoch
        self.last_lr = float(self.value_at(self.last_epoch))
        return self.last_lr

    def get_lr(self):
        return self.last_lr

    def state_dict(self):
        return {"last_epoch": self.last_epoch, "last_lr": self.last_lr}

    def set_state_dict(self, state):
        self.last_epoch = state.get("last_epoch", self.last_epoch)
        self.last_lr = state.get("last_lr", self.last_lr)

    # pure API -------------------------------------------------------------
    def value_at(self, step):
        raise NotImplementedError


class ConstantLR(LRScheduler):
    def value_at(self, step):
        return jnp.asarray(self.base_lr, jnp.float32)


class NoamDecay(LRScheduler):
    def __init__(self, d_model, warmup_steps, learning_rate=1.0, last_epoch=-1, verbose=False):
        self.d_model = d_model
        self.warmup_steps = warmup_steps
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        step = jnp.maximum(jnp.asarray(step, jnp.float32), 1.0)
        a = step ** -0.5
        b = step * (self.warmup_steps ** -1.5)
        return self.base_lr * (self.d_model ** -0.5) * jnp.minimum(a, b)


class ExponentialDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr * self.gamma ** jnp.asarray(step, jnp.float32)


class NaturalExpDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr * jnp.exp(-self.gamma * jnp.asarray(step, jnp.float32))


class InverseTimeDecay(LRScheduler):
    def __init__(self, learning_rate, gamma, last_epoch=-1, verbose=False):
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr / (1 + self.gamma * jnp.asarray(step, jnp.float32))


class PolynomialDecay(LRScheduler):
    def __init__(self, learning_rate, decay_steps, end_lr=0.0001, power=1.0,
                 cycle=False, last_epoch=-1, verbose=False):
        self.decay_steps = decay_steps
        self.end_lr = end_lr
        self.power = power
        self.cycle = cycle
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        if self.cycle:
            div = jnp.ceil(jnp.maximum(step, 1.0) / self.decay_steps)
            decay_steps = self.decay_steps * jnp.maximum(div, 1.0)
        else:
            decay_steps = self.decay_steps
            step = jnp.minimum(step, self.decay_steps)
        frac = (1 - step / decay_steps) ** self.power
        return (self.base_lr - self.end_lr) * frac + self.end_lr


class LinearWarmup(LRScheduler):
    def __init__(self, learning_rate, warmup_steps, start_lr, end_lr, last_epoch=-1, verbose=False):
        self.lr_after = learning_rate  # float or LRScheduler
        self.warmup_steps = warmup_steps
        self.start_lr = start_lr
        self.end_lr = end_lr
        super().__init__(end_lr, last_epoch, verbose)

    def value_at(self, step):
        step_f = jnp.asarray(step, jnp.float32)
        warm = self.start_lr + (self.end_lr - self.start_lr) * jnp.minimum(
            step_f / max(self.warmup_steps, 1), 1.0)
        if isinstance(self.lr_after, LRScheduler):
            after = self.lr_after.value_at(jnp.maximum(step_f - self.warmup_steps, 0.0))
        else:
            after = jnp.asarray(self.lr_after, jnp.float32)
        return jnp.where(step_f < self.warmup_steps, warm, after)


class PiecewiseDecay(LRScheduler):
    def __init__(self, boundaries, values, last_epoch=-1, verbose=False):
        self.boundaries = list(boundaries)
        self.values = list(values)
        super().__init__(values[0], last_epoch, verbose)

    def value_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        out = jnp.asarray(self.values[-1], jnp.float32)
        for b, v in zip(reversed(self.boundaries), reversed(self.values[:-1])):
            out = jnp.where(step < b, jnp.asarray(v, jnp.float32), out)
        return out


class CosineAnnealingDecay(LRScheduler):
    def __init__(self, learning_rate, T_max, eta_min=0, last_epoch=-1, verbose=False):
        self.T_max = T_max
        self.eta_min = eta_min
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        cos = jnp.cos(math.pi * jnp.minimum(step, self.T_max) / self.T_max)
        return self.eta_min + (self.base_lr - self.eta_min) * (1 + cos) / 2


class StepDecay(LRScheduler):
    def __init__(self, learning_rate, step_size, gamma=0.1, last_epoch=-1, verbose=False):
        self.step_size = step_size
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        k = jnp.floor(jnp.asarray(step, jnp.float32) / self.step_size)
        return self.base_lr * self.gamma ** k


class MultiStepDecay(LRScheduler):
    def __init__(self, learning_rate, milestones, gamma=0.1, last_epoch=-1, verbose=False):
        self.milestones = list(milestones)
        self.gamma = gamma
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        k = sum(jnp.where(step >= m, 1.0, 0.0) for m in self.milestones)
        return self.base_lr * self.gamma ** k


class LambdaDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        return self.base_lr * self.lr_lambda(step)


class MultiplicativeDecay(LRScheduler):
    def __init__(self, learning_rate, lr_lambda, last_epoch=-1, verbose=False):
        self.lr_lambda = lr_lambda
        super().__init__(learning_rate, last_epoch, verbose)

    def value_at(self, step):
        # product form is inherently sequential; supported for python ints only
        lr = self.base_lr
        for i in range(1, int(step) + 1):
            lr *= self.lr_lambda(i)
        return jnp.asarray(lr, jnp.float32)


class OneCycleLR(LRScheduler):
    def __init__(self, max_learning_rate, total_steps, divide_factor=25.0,
                 end_learning_rate=0.0001, phase_pct=0.3, anneal_strategy="cos",
                 three_phase=False, last_epoch=-1, verbose=False):
        self.max_lr = max_learning_rate
        self.total_steps = total_steps
        self.initial_lr = max_learning_rate / divide_factor
        self.end_lr = end_learning_rate
        self.phase_pct = phase_pct
        super().__init__(self.initial_lr, last_epoch, verbose)

    def value_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        up_steps = self.phase_pct * self.total_steps
        down_steps = self.total_steps - up_steps

        def cos_interp(a, b, frac):
            return b + (a - b) * (1 + jnp.cos(math.pi * frac)) / 2

        frac_up = jnp.clip(step / jnp.maximum(up_steps, 1.0), 0.0, 1.0)
        frac_down = jnp.clip((step - up_steps) / jnp.maximum(down_steps, 1.0), 0.0, 1.0)
        up = cos_interp(self.initial_lr, self.max_lr, 1 - frac_up)
        down = cos_interp(self.max_lr, self.end_lr, 1 - frac_down)
        return jnp.where(step < up_steps, up, down)


class CyclicLR(LRScheduler):
    def __init__(self, base_learning_rate, max_learning_rate, step_size_up,
                 step_size_down=None, mode="triangular", gamma=1.0, last_epoch=-1, verbose=False):
        self.base_lr_c = base_learning_rate
        self.max_lr = max_learning_rate
        self.step_size_up = step_size_up
        self.step_size_down = step_size_down or step_size_up
        self.mode = mode
        self.gamma = gamma
        super().__init__(base_learning_rate, last_epoch, verbose)

    def value_at(self, step):
        step = jnp.asarray(step, jnp.float32)
        cycle_len = self.step_size_up + self.step_size_down
        cycle = jnp.floor(step / cycle_len)
        pos = step - cycle * cycle_len
        up_frac = jnp.clip(pos / self.step_size_up, 0.0, 1.0)
        down_frac = jnp.clip((pos - self.step_size_up) / self.step_size_down, 0.0, 1.0)
        scale = jnp.where(pos < self.step_size_up, up_frac, 1.0 - down_frac)
        amp = self.max_lr - self.base_lr_c
        if self.mode == "triangular2":
            amp = amp / (2.0 ** cycle)
        elif self.mode == "exp_range":
            amp = amp * self.gamma ** step
        return self.base_lr_c + amp * scale


class ReduceOnPlateau(LRScheduler):
    """Metric-driven; inherently host-side (not traceable)."""

    def __init__(self, learning_rate, mode="min", factor=0.1, patience=10,
                 threshold=1e-4, threshold_mode="rel", cooldown=0, min_lr=0,
                 epsilon=1e-8, verbose=False):
        self.mode = mode
        self.factor = factor
        self.patience = patience
        self.threshold = threshold
        self.threshold_mode = threshold_mode
        self.cooldown = cooldown
        self.min_lr = min_lr
        self.best = None
        self.num_bad_epochs = 0
        self.cooldown_counter = 0
        self.base_lr = learning_rate
        self.last_lr = learning_rate
        self.last_epoch = 0

    def value_at(self, step):
        return jnp.asarray(self.last_lr, jnp.float32)

    def step(self, metrics=None, epoch=None):
        if metrics is None:
            return self.last_lr
        current = float(metrics)
        if self.best is None:
            self.best = current
        better = (current < self.best - self._thr()) if self.mode == "min" else (
            current > self.best + self._thr())
        if better:
            self.best = current
            self.num_bad_epochs = 0
        else:
            self.num_bad_epochs += 1
        if self.cooldown_counter > 0:
            self.cooldown_counter -= 1
            self.num_bad_epochs = 0
        if self.num_bad_epochs > self.patience:
            self.last_lr = max(self.last_lr * self.factor, self.min_lr)
            self.cooldown_counter = self.cooldown
            self.num_bad_epochs = 0
        return self.last_lr

    def _thr(self):
        if self.threshold_mode == "rel":
            return abs(self.best) * self.threshold if self.best is not None else 0.0
        return self.threshold
