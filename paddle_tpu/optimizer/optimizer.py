"""Optimizer base.

Reference parity: ``python/paddle/optimizer/optimizer.py`` (param groups, LR
schedulers, grad clip, master weights). TPU-native design: every optimizer is
a pure ``init(params) -> state`` / ``update(grads, state, params) -> (params,
state)`` pair so the whole step jits into one XLA program with donated
buffers; the stateful ``step()``-style API used by the eager/`hapi` path is a
thin shell over it.

Master weights ("multi_precision" in the reference,
``python/paddle/optimizer/optimizer.py`` master-weight path): when params are
bf16, ``init`` keeps an f32 copy and ``update`` applies the step in f32,
casting back — same semantics, expressed functionally.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from .lr import LRScheduler


def _tree_map(fn, *trees, is_leaf=None):
    return jax.tree.map(fn, *trees, is_leaf=is_leaf)


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        self._learning_rate = learning_rate
        self._parameters = parameters
        self.weight_decay = 0.0 if weight_decay is None else weight_decay
        self.grad_clip = grad_clip
        self.multi_precision = multi_precision
        # stateful-API storage (eager/hapi path)
        self._state = None
        self._accumulated_grads = None

    # ------------------------------------------------------------ LR
    def get_lr(self, step=None):
        """Scalar LR; traceable when ``step`` is a tracer."""
        if isinstance(self._learning_rate, LRScheduler):
            if step is None:
                return self._learning_rate.get_lr()
            return self._learning_rate.value_at(step)
        return self._learning_rate

    def set_lr(self, value):
        self._learning_rate = value

    # ------------------------------------------------------------ functional
    def init(self, params) -> Dict[str, Any]:
        state = {"step": jnp.zeros((), jnp.int32)}
        state.update(self._init_slots(params))
        if self.multi_precision:
            state["master_weights"] = _tree_map(
                lambda p: p.astype(jnp.float32) if p.dtype in (jnp.bfloat16, jnp.float16) else p,
                params)
        return state

    def update(self, grads, state, params):
        """Apply one optimization step. Returns (new_params, new_state)."""
        if self.grad_clip is not None:
            grads = self.grad_clip(grads)
        step = state["step"] + 1
        lr = self.get_lr(step)
        work_params = state.get("master_weights", params)
        grads32 = _tree_map(lambda g: g.astype(jnp.float32) if g is not None else None, grads)
        new_work, new_slots = self._apply(grads32, {**state, "step": step}, work_params, lr)
        new_state = {**new_slots, "step": step}
        if self.multi_precision and "master_weights" in state:
            new_state["master_weights"] = new_work
            new_params = _tree_map(lambda p, m: m.astype(p.dtype), params, new_work)
        else:
            new_params = _tree_map(lambda p, w: w.astype(p.dtype), params, new_work)
        return new_params, new_state

    # subclass hooks -------------------------------------------------------
    def _init_slots(self, params) -> Dict[str, Any]:
        return {}

    def _apply(self, grads, state, params, lr):
        raise NotImplementedError

    def _decayed_grad(self, g, p):
        """L2-style decay folded into the gradient (paddle's default
        ``weight_decay`` semantics for non-AdamW optimizers)."""
        if self.weight_decay:
            return g + self.weight_decay * p.astype(g.dtype)
        return g

    # ------------------------------------------------------------ stateful API
    def bind(self, params):
        """Attach parameter pytree for the stateful step() API."""
        self._parameters = params
        self._state = self.init(params)
        return self

    def step(self, params=None, grads=None):
        """Stateful step over bound params (eager path). Returns new params."""
        if params is None:
            params = self._parameters
        if grads is None:
            grads = self._accumulated_grads
        if self._state is None:
            self._state = self.init(params)
        new_params, self._state = self.update(grads, self._state, params)
        self._parameters = new_params
        self._accumulated_grads = None
        return new_params

    def clear_grad(self, set_to_zero=True):
        self._accumulated_grads = None

    # ------------------------------------------------------------ state dict
    def state_dict(self):
        out = {"state": self._state}
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state_dict):
        self._state = state_dict.get("state", self._state)
        if "LR_Scheduler" in state_dict and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state_dict["LR_Scheduler"])


class SGD(Optimizer):
    """reference: ``python/paddle/optimizer/sgd.py``"""

    def _apply(self, grads, state, params, lr):
        new_params = _tree_map(
            lambda p, g: p if g is None else p - lr * self._decayed_grad(g, p),
            params, grads)
        return new_params, {}


class Momentum(Optimizer):
    """reference: ``python/paddle/optimizer/momentum.py``"""

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _init_slots(self, params):
        return {"velocity": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)}

    def _apply(self, grads, state, params, lr):
        def upd(p, g, v):
            if g is None:
                return p, v
            g = self._decayed_grad(g, p)
            v_new = self.momentum * v + g
            if self.use_nesterov:
                step_dir = g + self.momentum * v_new
            else:
                step_dir = v_new
            return p - lr * step_dir, v_new

        flat = _tree_map(upd, params, grads, state["velocity"])
        new_params = _tree_map(lambda pv: pv[0], flat, is_leaf=lambda x: isinstance(x, tuple))
        new_v = _tree_map(lambda pv: pv[1], flat, is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"velocity": new_v}


class Adam(Optimizer):
    """reference: ``python/paddle/optimizer/adam.py`` (incl. the fused
    multi-tensor path — unnecessary here: the whole update is one XLA fusion).
    """

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None,
                 lazy_mode=False, multi_precision=False, name=None,
                 moment_dtype=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip, multi_precision)
        self.beta1 = beta1
        self.beta2 = beta2
        self.epsilon = epsilon
        # storage dtype for the moment1 slot; update math is always f32.
        # bf16 m cuts optimizer-state HBM by 2 bytes/param — part of the
        # lever that fits GPT-1.3B on a 16 GB v5e (bench.py:bench_gpt_1p3b).
        # moment2 deliberately STAYS f32: its beta2=0.999 EMA moves only
        # ~0.1% per step, below bf16's ~0.39% half-ULP, so round-to-nearest
        # would store it unchanged forever (a frozen second moment pins the
        # effective LR at whatever an early spike set it to). moment1's
        # beta1=0.9 moves ~10% per step — far above ULP, safe in bf16.
        self._moment_dtype = jnp.dtype(moment_dtype) if moment_dtype else jnp.float32

    def _init_slots(self, params):
        return {
            "moment1": _tree_map(lambda p: jnp.zeros_like(p, dtype=self._moment_dtype), params),
            "moment2": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
        }

    def _decay_term(self, p, lr):
        # plain Adam: decay folded into grad (L2); AdamW overrides
        return None

    def _apply(self, grads, state, params, lr):
        step = state["step"]
        b1c = 1.0 - self.beta1 ** step.astype(jnp.float32)
        b2c = 1.0 - self.beta2 ** step.astype(jnp.float32)

        def upd(p, g, m, v):
            if g is None:
                return p, m, v
            g = g.astype(jnp.float32)
            if not isinstance(self, AdamW):
                g = self._decayed_grad(g, p)
            m_new = self.beta1 * m.astype(jnp.float32) + (1 - self.beta1) * g
            v_new = self.beta2 * v.astype(jnp.float32) + (1 - self.beta2) * jnp.square(g)
            m_hat = m_new / b1c
            v_hat = v_new / b2c
            delta = lr * m_hat / (jnp.sqrt(v_hat) + self.epsilon)
            if isinstance(self, AdamW) and self.weight_decay:
                delta = delta + lr * self.weight_decay * p.astype(jnp.float32)
            return (p - delta.astype(p.dtype),
                    m_new.astype(self._moment_dtype), v_new)

        triples = _tree_map(upd, params, grads, state["moment1"], state["moment2"])
        is_leaf = lambda x: isinstance(x, tuple)  # noqa: E731
        return (
            _tree_map(lambda t: t[0], triples, is_leaf=is_leaf),
            {
                "moment1": _tree_map(lambda t: t[1], triples, is_leaf=is_leaf),
                "moment2": _tree_map(lambda t: t[2], triples, is_leaf=is_leaf),
            },
        )


class AdamW(Adam):
    """Decoupled weight decay (reference: ``python/paddle/optimizer/adamw.py``).
    Supports ``apply_decay_param_fun`` to exempt bias/norm params."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=0.01, grad_clip=None,
                 apply_decay_param_fun=None, lazy_mode=False, multi_precision=False, name=None,
                 moment_dtype=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision,
                         moment_dtype=moment_dtype)
        self.apply_decay_param_fun = apply_decay_param_fun

    def _apply(self, grads, state, params, lr):
        if self.apply_decay_param_fun is None:
            return super()._apply(grads, state, params, lr)
        # per-name decay masking: params is a flat dict path->array
        decay_mask = {k: self.apply_decay_param_fun(k) for k in params}
        saved = self.weight_decay
        step = state["step"]
        b1c = 1.0 - self.beta1 ** step.astype(jnp.float32)
        b2c = 1.0 - self.beta2 ** step.astype(jnp.float32)
        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            p, g = params[k], grads[k]
            m, v = state["moment1"][k], state["moment2"][k]
            if g is None:
                new_p[k], new_m[k], new_v[k] = p, m, v
                continue
            g = g.astype(jnp.float32)
            m_new = self.beta1 * m.astype(jnp.float32) + (1 - self.beta1) * g
            v_new = self.beta2 * v.astype(jnp.float32) + (1 - self.beta2) * jnp.square(g)
            delta = lr * (m_new / b1c) / (jnp.sqrt(v_new / b2c) + self.epsilon)
            if decay_mask[k] and saved:
                delta = delta + lr * saved * p.astype(jnp.float32)
            new_p[k] = p - delta.astype(p.dtype)
            new_m[k] = m_new.astype(self._moment_dtype)
            new_v[k] = v_new
        return new_p, {"moment1": new_m, "moment2": new_v}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.epsilon = epsilon
        self.initial_accumulator_value = initial_accumulator_value

    def _init_slots(self, params):
        return {"moment": _tree_map(
            lambda p: jnp.full_like(p, self.initial_accumulator_value, dtype=jnp.float32), params)}

    def _apply(self, grads, state, params, lr):
        def upd(p, g, acc):
            if g is None:
                return p, acc
            g = self._decayed_grad(g.astype(jnp.float32), p)
            acc_new = acc + jnp.square(g)
            return p - (lr * g / (jnp.sqrt(acc_new) + self.epsilon)).astype(p.dtype), acc_new

        pairs = _tree_map(upd, params, grads, state["moment"])
        is_leaf = lambda x: isinstance(x, tuple)  # noqa: E731
        return (_tree_map(lambda t: t[0], pairs, is_leaf=is_leaf),
                {"moment": _tree_map(lambda t: t[1], pairs, is_leaf=is_leaf)})


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.rho = rho
        self.epsilon = epsilon
        self.momentum = momentum
        self.centered = centered

    def _init_slots(self, params):
        slots = {
            "mean_square": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
            "momentum_buf": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
        }
        if self.centered:
            slots["mean_grad"] = _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)
        return slots

    def _apply(self, grads, state, params, lr):
        new_ms, new_mom, new_mg, new_p = {}, {}, {}, {}
        for k in params:
            p, g = params[k], grads[k]
            if g is None:
                new_p[k], new_ms[k], new_mom[k] = p, state["mean_square"][k], state["momentum_buf"][k]
                if self.centered:
                    new_mg[k] = state["mean_grad"][k]
                continue
            g = self._decayed_grad(g.astype(jnp.float32), p)
            ms = self.rho * state["mean_square"][k] + (1 - self.rho) * jnp.square(g)
            if self.centered:
                mg = self.rho * state["mean_grad"][k] + (1 - self.rho) * g
                denom = jnp.sqrt(ms - jnp.square(mg) + self.epsilon)
                new_mg[k] = mg
            else:
                denom = jnp.sqrt(ms + self.epsilon)
            mom = self.momentum * state["momentum_buf"][k] + lr * g / denom
            new_p[k] = p - mom.astype(p.dtype)
            new_ms[k], new_mom[k] = ms, mom
        slots = {"mean_square": new_ms, "momentum_buf": new_mom}
        if self.centered:
            slots["mean_grad"] = new_mg
        return new_p, slots


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.epsilon = epsilon
        self.rho = rho

    def _init_slots(self, params):
        return {
            "avg_squared_grad": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
            "avg_squared_update": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
        }

    def _apply(self, grads, state, params, lr):
        new_p, new_g2, new_u2 = {}, {}, {}
        for k in params:
            p, g = params[k], grads[k]
            if g is None:
                new_p[k] = p
                new_g2[k] = state["avg_squared_grad"][k]
                new_u2[k] = state["avg_squared_update"][k]
                continue
            g = self._decayed_grad(g.astype(jnp.float32), p)
            g2 = self.rho * state["avg_squared_grad"][k] + (1 - self.rho) * jnp.square(g)
            u2_prev = state["avg_squared_update"][k]
            update = jnp.sqrt(u2_prev + self.epsilon) / jnp.sqrt(g2 + self.epsilon) * g
            u2 = self.rho * u2_prev + (1 - self.rho) * jnp.square(update)
            new_p[k] = p - (lr * update).astype(p.dtype)
            new_g2[k], new_u2[k] = g2, u2
        return new_p, {"avg_squared_grad": new_g2, "avg_squared_update": new_u2}


class Adamax(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-08,
                 parameters=None, weight_decay=None, grad_clip=None, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _init_slots(self, params):
        return {
            "moment": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
            "inf_norm": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
        }

    def _apply(self, grads, state, params, lr):
        step = state["step"]
        b1c = 1.0 - self.beta1 ** step.astype(jnp.float32)
        new_p, new_m, new_u = {}, {}, {}
        for k in params:
            p, g = params[k], grads[k]
            if g is None:
                new_p[k], new_m[k], new_u[k] = p, state["moment"][k], state["inf_norm"][k]
                continue
            g = self._decayed_grad(g.astype(jnp.float32), p)
            m = self.beta1 * state["moment"][k] + (1 - self.beta1) * g
            u = jnp.maximum(self.beta2 * state["inf_norm"][k], jnp.abs(g))
            new_p[k] = p - (lr / b1c * m / (u + self.epsilon)).astype(p.dtype)
            new_m[k], new_u[k] = m, u
        return new_p, {"moment": new_m, "inf_norm": new_u}


class Lamb(Optimizer):
    """Layer-wise adaptive large-batch optimizer
    (reference: ``python/paddle/optimizer/lamb.py``)."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-06, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, name=None):
        super().__init__(learning_rate, parameters, lamb_weight_decay, grad_clip)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon
        self.exclude_from_weight_decay_fn = exclude_from_weight_decay_fn

    def _init_slots(self, params):
        return {
            "moment1": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
            "moment2": _tree_map(lambda p: jnp.zeros_like(p, dtype=jnp.float32), params),
        }

    def _apply(self, grads, state, params, lr):
        step = state["step"].astype(jnp.float32)
        b1c = 1.0 - self.beta1 ** step
        b2c = 1.0 - self.beta2 ** step
        new_p, new_m, new_v = {}, {}, {}
        for k in params:
            p, g = params[k], grads[k]
            if g is None:
                new_p[k], new_m[k], new_v[k] = p, state["moment1"][k], state["moment2"][k]
                continue
            g = g.astype(jnp.float32)
            m = self.beta1 * state["moment1"][k] + (1 - self.beta1) * g
            v = self.beta2 * state["moment2"][k] + (1 - self.beta2) * jnp.square(g)
            r = (m / b1c) / (jnp.sqrt(v / b2c) + self.epsilon)
            decay = self.weight_decay
            if self.exclude_from_weight_decay_fn is not None and self.exclude_from_weight_decay_fn(k):
                decay = 0.0
            p32 = p.astype(jnp.float32)
            r = r + decay * p32
            w_norm = jnp.linalg.norm(p32)
            r_norm = jnp.linalg.norm(r)
            trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
            new_p[k] = p - (lr * trust * r).astype(p.dtype)
            new_m[k], new_v[k] = m, v
        return new_p, {"moment1": new_m, "moment2": new_v}


class LarsMomentum(Optimizer):
    """LARS: layer-wise adaptive rate scaling over momentum
    (reference ``python/paddle/incubate/optimizer/lars_momentum.py`` and the
    fleet ``lars`` meta-optimizer). Per-parameter trust ratio
    ``lars_coeff * ||p|| / (||g|| + wd * ||p|| + eps)`` rescales the LR —
    the large-batch recipe where Lamb's normalization is Adam-shaped and
    LARS's is momentum-shaped."""

    def __init__(self, learning_rate=0.001, momentum=0.9, lars_coeff=0.001,
                 lars_weight_decay=0.0005, parameters=None, grad_clip=None,
                 epsilon=1e-8, exclude_from_weight_decay=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self.momentum = momentum
        self.lars_coeff = lars_coeff
        self.lars_weight_decay = lars_weight_decay
        self.epsilon = epsilon
        self.exclude_from_weight_decay = exclude_from_weight_decay or []

    def _init_slots(self, params):
        return {"velocity": _tree_map(
            lambda p: jnp.zeros_like(p, dtype=jnp.float32), params)}

    def _excluded(self, name) -> bool:
        return any(frag in str(name) for frag in self.exclude_from_weight_decay)

    def _apply(self, grads, state, params, lr):
        def upd(p, g, v, excluded):
            if g is None:
                return p, v
            p32 = p.astype(jnp.float32)
            g32 = g.astype(jnp.float32)
            p_norm = jnp.sqrt(jnp.sum(p32 * p32))
            g_norm = jnp.sqrt(jnp.sum(g32 * g32))
            wd = 0.0 if excluded else self.lars_weight_decay
            local_lr = jnp.where(
                (p_norm > 0) & (g_norm > 0),
                self.lars_coeff * p_norm / (g_norm + wd * p_norm +
                                            self.epsilon),
                1.0)
            v_new = self.momentum * v + lr * local_lr * (g32 + wd * p32)
            return (p32 - v_new).astype(p.dtype), v_new

        vel = state["velocity"]
        if isinstance(params, dict):
            # params are the framework's flat name->leaf dicts, so the
            # exclude_from_weight_decay name fragments can be honored
            out = {k: upd(params[k], grads.get(k), vel[k], self._excluded(k))
                   for k in params}
            new_params = {k: pv[0] for k, pv in out.items()}
            new_v = {k: pv[1] for k, pv in out.items()}
        else:
            flat = _tree_map(lambda p, g, v: upd(p, g, v, False),
                             params, grads, vel)
            new_params = _tree_map(lambda pv: pv[0], flat,
                                   is_leaf=lambda x: isinstance(x, tuple))
            new_v = _tree_map(lambda pv: pv[1], flat,
                              is_leaf=lambda x: isinstance(x, tuple))
        return new_params, {"velocity": new_v}


class DGCMomentum(Optimizer):
    """Deep Gradient Compression over momentum (reference
    ``fleet/meta_optimizers/dgc_optimizer.py`` / Lin et al.): each step only
    the top-(1-s) fraction of the residual-accumulated gradient is applied;
    the rest keeps accumulating locally with momentum correction and factor
    masking. On TPU the transport saving belongs to XLA, but the ALGORITHM
    (what reaches the weights, and when) is reproduced exactly — the knob
    that matters for convergence when grads cross slow DCN links.

    Before ``rampup_begin_step`` it is plain momentum; sparsity then ramps
    through the ``sparsity`` list over ``rampup_step`` steps.
    """

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 parameters=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip,
                         multi_precision)
        self.momentum = momentum
        self.rampup_begin_step = int(rampup_begin_step)
        self.rampup_step = max(int(rampup_step), 1)
        self.sparsity = tuple(float(s) for s in sparsity)

    def _init_slots(self, params):
        zeros = lambda p: jnp.zeros_like(p, dtype=jnp.float32)  # noqa: E731
        return {"velocity": _tree_map(zeros, params),
                "residual": _tree_map(zeros, params)}

    def _sparsity_at(self, step):
        levels = jnp.asarray(self.sparsity, jnp.float32)
        idx = jnp.clip((step - self.rampup_begin_step)
                       * len(self.sparsity) // self.rampup_step,
                       0, len(self.sparsity) - 1)
        return levels[idx]

    def _apply(self, grads, state, params, lr):
        step = state["step"]
        use_dgc = step > self.rampup_begin_step
        s = self._sparsity_at(step)

        def upd(p, g, u, v):
            if g is None:
                return p, u, v
            # momentum correction: accumulate momentum-corrected grads
            u_new = self.momentum * u + g
            v_new = v + u_new
            flat = jnp.abs(v_new).reshape(-1)
            thr = jnp.quantile(flat, jnp.clip(s, 0.0, 1.0 - 1e-7))
            mask = (jnp.abs(v_new) >= thr).astype(v_new.dtype)
            sparse = v_new * mask
            # factor masking: transmitted coordinates reset their local state
            v_dgc = v_new * (1.0 - mask)
            u_dgc = u_new * (1.0 - mask)
            p_dgc = p - lr * sparse
            # warmup: vanilla momentum, residual stays empty
            p_warm = p - lr * u_new
            return (jnp.where(use_dgc, p_dgc, p_warm),
                    jnp.where(use_dgc, u_dgc, u_new),
                    jnp.where(use_dgc, v_dgc, v))

        out = _tree_map(upd, params, grads, state["velocity"],
                        state["residual"])
        is_triple = lambda t: isinstance(t, tuple)  # noqa: E731
        new_params = _tree_map(lambda t: t[0], out, is_leaf=is_triple)
        return new_params, {
            "velocity": _tree_map(lambda t: t[1], out, is_leaf=is_triple),
            "residual": _tree_map(lambda t: t[2], out, is_leaf=is_triple)}
