"""Functional neural-net ops (reference: ``python/paddle/nn/functional/``).

Each function is a pure jnp/lax composition — the conv/matmul ops hit the MXU
via a single ``lax.conv_general_dilated``/``dot_general``; elementwise
epilogues (bias, activation) are fused by XLA, which is why there is no
``fused_*`` op zoo here (reference keeps 39k LoC of fused CUDA ops under
``paddle/fluid/operators/fused/``).

Layout: paddle defaults to NCHW; ``data_format`` is honored and NHWC is the
TPU-friendly fast path.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import convert_dtype
from .layer import take_rng_key

# ------------------------------------------------------------- activations
relu = jax.nn.relu
relu6 = jax.nn.relu6
sigmoid = jax.nn.sigmoid
softplus_ = jax.nn.softplus
silu = jax.nn.silu
swish = jax.nn.silu
elu = jax.nn.elu
selu = jax.nn.selu
celu = jax.nn.celu
glu = jax.nn.glu


def tanh(x, name=None):
    return jnp.tanh(x)


def gelu(x, approximate=False, name=None):
    return jax.nn.gelu(x, approximate=approximate)


def leaky_relu(x, negative_slope=0.01, name=None):
    return jax.nn.leaky_relu(x, negative_slope=negative_slope)


def prelu(x, weight, data_format="NCHW", name=None):
    x, w = jnp.asarray(x), jnp.asarray(weight)
    if w.size > 1:
        ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
        shape = [1] * x.ndim
        shape[ch_axis] = w.size
        w = w.reshape(shape)
    return jnp.where(x >= 0, x, w * x)


def rrelu(x, lower=1.0 / 8.0, upper=1.0 / 3.0, training=True, name=None):
    x = jnp.asarray(x)
    if training:
        a = jax.random.uniform(take_rng_key("rrelu"), x.shape, dtype=x.dtype,
                               minval=lower, maxval=upper)
    else:
        a = jnp.asarray((lower + upper) / 2.0, x.dtype)
    return jnp.where(x >= 0, x, a * x)


def hardtanh(x, min=-1.0, max=1.0, name=None):  # noqa: A002
    return jnp.clip(x, min, max)


def hardshrink(x, threshold=0.5, name=None):
    x = jnp.asarray(x)
    return jnp.where(jnp.abs(x) > threshold, x, jnp.zeros_like(x))


def softshrink(x, threshold=0.5, name=None):
    x = jnp.asarray(x)
    return jnp.where(x > threshold, x - threshold, jnp.where(x < -threshold, x + threshold, jnp.zeros_like(x)))


def tanhshrink(x, name=None):
    return x - jnp.tanh(x)


def thresholded_relu(x, threshold=1.0, name=None):
    x = jnp.asarray(x)
    return jnp.where(x > threshold, x, jnp.zeros_like(x))


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return jnp.clip(slope * jnp.asarray(x) + offset, 0.0, 1.0)


def hardswish(x, name=None):
    x = jnp.asarray(x)
    return x * jnp.clip(x + 3.0, 0.0, 6.0) / 6.0


def mish(x, name=None):
    return x * jnp.tanh(jax.nn.softplus(x))


def softplus(x, beta=1.0, threshold=20.0, name=None):
    x = jnp.asarray(x)
    return jnp.where(x * beta > threshold, x, jax.nn.softplus(x * beta) / beta)


def softsign(x, name=None):
    return jax.nn.soft_sign(x)


def maxout(x, groups, axis=1, name=None):
    x = jnp.asarray(x)
    axis = axis % x.ndim
    c = x.shape[axis]
    new_shape = x.shape[:axis] + (c // groups, groups) + x.shape[axis + 1 :]
    return jnp.max(x.reshape(new_shape), axis=axis + 1)


def softmax(x, axis=-1, dtype=None, name=None):
    x = jnp.asarray(x)
    if dtype is not None:
        x = x.astype(convert_dtype(dtype))
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1, dtype=None, name=None):
    x = jnp.asarray(x)
    if dtype is not None:
        x = x.astype(convert_dtype(dtype))
    return jax.nn.log_softmax(x, axis=axis)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    g = jax.random.gumbel(take_rng_key("gumbel"), jnp.shape(x), dtype=jnp.asarray(x).dtype)
    y = jax.nn.softmax((x + g) / temperature, axis=axis)
    if hard:
        idx = jnp.argmax(y, axis=axis, keepdims=True)
        onehot = jnp.put_along_axis(
            jnp.zeros_like(y), idx, jnp.ones([], y.dtype), axis=axis, inplace=False)
        y = jax.lax.stop_gradient(onehot - y) + y  # straight-through
    return y


# ------------------------------------------------------------- linear / embedding
def linear(x, weight, bias=None, name=None):
    """paddle weight layout: [in_features, out_features]. Under an O1
    ``amp.auto_cast`` scope the matmul runs in the autocast dtype (the
    white-list contract, reference amp O1)."""
    from ..amp.auto_cast import autocast_call

    x, weight, bias = autocast_call("linear", x, weight, bias)
    out = jnp.matmul(x, weight)
    if bias is not None:
        out = out + bias
    return out


def embedding(x, weight, padding_idx=None, sparse=False, name=None):
    del sparse  # XLA gather handles both densities
    out = jnp.take(jnp.asarray(weight), jnp.asarray(x), axis=0)
    if padding_idx is not None:
        mask = (jnp.asarray(x) == padding_idx)[..., None]
        out = jnp.where(mask, jnp.zeros_like(out), out)
    return out


def one_hot(x, num_classes, name=None):
    return jax.nn.one_hot(jnp.asarray(x), num_classes)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    label = jnp.asarray(label)
    k = label.shape[-1]
    if prior_dist is not None:
        return (1 - epsilon) * label + epsilon * jnp.asarray(prior_dist)
    return (1 - epsilon) * label + epsilon / k


# ------------------------------------------------------------- normalization
def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    x = jnp.asarray(x)
    nrm = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=True) ** (1.0 / p)
    return x / jnp.maximum(nrm, epsilon)


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-05, name=None):
    x = jnp.asarray(x)
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    axes = tuple(range(x.ndim - len(tuple(normalized_shape)), x.ndim))
    # compute stats in f32 for bf16 inputs (TPU norm-stability idiom)
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * jax.lax.rsqrt(var + epsilon)
    out = out.astype(x.dtype)
    if weight is not None:
        out = out * weight
    if bias is not None:
        out = out + bias
    return out


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """Not in the reference (predates RMSNorm adoption); required for the
    Llama family (BASELINE.md)."""
    x = jnp.asarray(x)
    xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    out = (xf * jax.lax.rsqrt(var + epsilon)).astype(x.dtype)
    if weight is not None:
        out = out * weight
    return out


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-05, data_format="NCHW",
               use_global_stats=None, name=None):
    """Returns (out, new_running_mean, new_running_var).

    Unlike the reference's in-place stat mutation (``batch_norm_kernel.cu``),
    updated stats are returned functionally; ``nn.BatchNorm`` layers write
    them back into their buffers.
    """
    x = jnp.asarray(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    shape = [1] * x.ndim
    shape[ch_axis] = x.shape[ch_axis]

    use_batch_stats = training and not use_global_stats
    if use_batch_stats:
        xf = x.astype(jnp.float32) if x.dtype in (jnp.bfloat16, jnp.float16) else x
        mean = jnp.mean(xf, axis=reduce_axes)
        var = jnp.var(xf, axis=reduce_axes)
        n = x.size // x.shape[ch_axis]
        unbiased = var * n / max(n - 1, 1)
        new_mean = momentum * running_mean + (1 - momentum) * mean.astype(running_mean.dtype)
        new_var = momentum * running_var + (1 - momentum) * unbiased.astype(running_var.dtype)
    else:
        mean, var = running_mean, running_var
        new_mean, new_var = running_mean, running_var

    out = (x - mean.reshape(shape).astype(x.dtype)) * jax.lax.rsqrt(
        var.reshape(shape).astype(jnp.float32) + epsilon
    ).astype(x.dtype)
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out, new_mean, new_var


def group_norm(x, num_groups, weight=None, bias=None, epsilon=1e-05, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    if data_format.startswith("NC"):
        N, C = x.shape[0], x.shape[1]
        spatial = x.shape[2:]
        g = x.reshape(N, num_groups, C // num_groups, *spatial)
        axes = tuple(range(2, g.ndim))
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        g = (g - mean) * jax.lax.rsqrt(var + epsilon)
        out = g.reshape(x.shape)
        shape = [1, C] + [1] * len(spatial)
    else:
        N, C = x.shape[0], x.shape[-1]
        spatial = x.shape[1:-1]
        g = x.reshape(N, *spatial, num_groups, C // num_groups)
        axes = tuple(range(1, g.ndim - 2)) + (g.ndim - 1,)
        mean = jnp.mean(g, axis=axes, keepdims=True)
        var = jnp.var(g, axis=axes, keepdims=True)
        g = (g - mean) * jax.lax.rsqrt(var + epsilon)
        out = g.reshape(x.shape)
        shape = [1] * (x.ndim - 1) + [C]
    if weight is not None:
        out = out * weight.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return out


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-05, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(2, x.ndim)) if ch_axis == 1 else tuple(range(1, x.ndim - 1))
    mean = jnp.mean(x, axis=reduce_axes, keepdims=True)
    var = jnp.var(x, axis=reduce_axes, keepdims=True)
    out = (x - mean) * jax.lax.rsqrt(var + eps)
    if weight is not None:
        shape = [1] * x.ndim
        shape[ch_axis] = x.shape[ch_axis]
        out = out * weight.reshape(shape)
        if bias is not None:
            out = out + bias.reshape(shape)
    return out


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    sq = jnp.square(x)
    moved = jnp.moveaxis(sq, ch_axis, -1)
    pad_lo = (size - 1) // 2
    pad_hi = size - 1 - pad_lo
    padded = jnp.pad(moved, [(0, 0)] * (moved.ndim - 1) + [(pad_lo, pad_hi)])
    windows = jnp.stack([jnp.roll(padded, -i, axis=-1)[..., : moved.shape[-1]] for i in range(size)], axis=0)
    summed = jnp.sum(windows, axis=0)
    summed = jnp.moveaxis(summed, -1, ch_axis)
    return x / jnp.power(k + alpha * summed, beta)


# ------------------------------------------------------------- dropout
def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    x = jnp.asarray(x)
    if not training or p == 0.0:
        if mode == "downscale_in_infer" and not training:
            return x * (1.0 - p)
        return x
    if p == 1.0:
        return jnp.zeros_like(x)
    key = take_rng_key("dropout")
    if axis is not None:
        axes = [axis] if isinstance(axis, int) else list(axis)
        mask_shape = tuple(x.shape[i] if i in axes else 1 for i in range(x.ndim))
    else:
        mask_shape = x.shape
    keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
    if mode == "upscale_in_train":
        return jnp.where(keep, x / (1.0 - p), jnp.zeros_like(x))
    return jnp.where(keep, x, jnp.zeros_like(x))


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    axis = [0, 1] if data_format == "NCHW" else [0, 3]
    return dropout(x, p=p, axis=axis, training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    axis = [0, 1] if data_format == "NCDHW" else [0, 4]
    return dropout(x, p=p, axis=axis, training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    x = jnp.asarray(x)
    if not training or p == 0.0:
        return x
    alpha = 1.6732632423543772
    scale = 1.0507009873554805
    alpha_p = -alpha * scale
    key = take_rng_key("dropout")
    keep = jax.random.bernoulli(key, 1.0 - p, x.shape)
    a = (1.0 / math.sqrt((1.0 - p) * (1.0 + p * alpha_p**2)))
    b = -a * alpha_p * p
    return a * jnp.where(keep, x, jnp.full_like(x, alpha_p)) + b


# ------------------------------------------------------------- conv / pool
def _pair(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v,) * n


def _conv_dim_numbers(ndim, channel_last):
    if ndim == 3:
        return ("NCL", "OIL", "NCL") if not channel_last else ("NLC", "OIL", "NLC")
    if ndim == 4:
        return ("NCHW", "OIHW", "NCHW") if not channel_last else ("NHWC", "OIHW", "NHWC")
    return ("NCDHW", "OIDHW", "NCDHW") if not channel_last else ("NDHWC", "OIDHW", "NDHWC")


def _conv_padding(padding, n_spatial, kernel, stride, dilation):
    """paddle padding: int | list | 'SAME' | 'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, int):
        return [(padding, padding)] * n_spatial
    padding = list(padding)
    if len(padding) == n_spatial and all(isinstance(p, int) for p in padding):
        return [(p, p) for p in padding]
    if len(padding) == 2 * n_spatial:
        return [(padding[2 * i], padding[2 * i + 1]) for i in range(n_spatial)]
    return [tuple(p) for p in padding]


def _convnd(x, weight, bias, stride, padding, dilation, groups, n_spatial, channel_last):
    from ..amp.auto_cast import autocast_call

    x, weight, bias = autocast_call("conv", x, weight, bias)
    x, w = jnp.asarray(x), jnp.asarray(weight)
    stride = _pair(stride, n_spatial)
    dilation = _pair(dilation, n_spatial)
    kernel = w.shape[2:]
    pad = _conv_padding(padding, n_spatial, kernel, stride, dilation)
    lhs_spec, rhs_spec, out_spec = _conv_dim_numbers(x.ndim, channel_last)
    dn = jax.lax.conv_dimension_numbers(x.shape, w.shape, (lhs_spec, rhs_spec, out_spec))
    out = jax.lax.conv_general_dilated(
        x, w.astype(x.dtype), window_strides=stride, padding=pad,
        rhs_dilation=dilation, dimension_numbers=dn, feature_group_count=groups,
    )
    if bias is not None:
        b_shape = [1] * out.ndim
        b_shape[out.ndim - 1 if channel_last else 1] = -1
        out = out + jnp.asarray(bias, out.dtype).reshape(b_shape)
    return out


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 1, data_format == "NLC")


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 2, data_format == "NHWC")


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _convnd(x, weight, bias, stride, padding, dilation, groups, 3, data_format == "NDHWC")


def _convnd_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                      groups, n_spatial, channel_last):
    x, w = jnp.asarray(x), jnp.asarray(weight)
    stride = _pair(stride, n_spatial)
    dilation = _pair(dilation, n_spatial)
    kernel = w.shape[2:]
    pad = _conv_padding(padding, n_spatial, kernel, stride, dilation)
    opad = _pair(output_padding, n_spatial)
    # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
    lhs_spec, rhs_spec, out_spec = _conv_dim_numbers(x.ndim, channel_last)
    dn = jax.lax.conv_dimension_numbers(
        x.shape, (w.shape[1] * groups, w.shape[0] // groups) + tuple(kernel),
        (lhs_spec, rhs_spec, out_spec))
    if isinstance(pad, str):
        trans_pad = pad
    else:
        trans_pad = [
            (dilation[i] * (kernel[i] - 1) - pad[i][0],
             dilation[i] * (kernel[i] - 1) - pad[i][1] + opad[i])
            for i in range(n_spatial)
        ]
    # gradient-of-conv formulation: dilate the input by stride
    w_t = jnp.swapaxes(w, 0, 1)  # -> [out_c/groups, in_c, *k]
    if groups > 1:
        # regroup: [g, out_c/g, in_c/g, *k] with flipped spatial
        w_g = w.reshape(groups, w.shape[0] // groups, *w.shape[1:])
        w_g = jnp.swapaxes(w_g, 1, 2)  # g, out/g, in/g, *k
        w_t = w_g.reshape(w.shape[1] * groups, w.shape[0] // groups, *kernel)
    w_t = jnp.flip(w_t, axis=tuple(range(2, w_t.ndim)))
    out = jax.lax.conv_general_dilated(
        x, w_t.astype(x.dtype), window_strides=(1,) * n_spatial, padding=trans_pad,
        lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn,
        feature_group_count=groups,
    )
    if bias is not None:
        b_shape = [1] * out.ndim
        b_shape[out.ndim - 1 if channel_last else 1] = -1
        out = out + jnp.asarray(bias, out.dtype).reshape(b_shape)
    return out


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCL", name=None):
    return _convnd_transpose(x, weight, bias, stride, padding, output_padding,
                             dilation, groups, 1, data_format == "NLC")


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCHW", output_size=None, name=None):
    return _convnd_transpose(x, weight, bias, stride, padding, output_padding,
                             dilation, groups, 2, data_format == "NHWC")


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     dilation=1, groups=1, data_format="NCDHW", output_size=None, name=None):
    return _convnd_transpose(x, weight, bias, stride, padding, output_padding,
                             dilation, groups, 3, data_format == "NDHWC")


def _pool(x, kernel_size, stride, padding, n_spatial, channel_last, reducer, init, ceil_mode=False):
    x = jnp.asarray(x)
    kernel_size = _pair(kernel_size, n_spatial)
    stride = _pair(stride if stride is not None else kernel_size, n_spatial)
    pad = _conv_padding(padding, n_spatial, kernel_size, stride, (1,) * n_spatial)
    if channel_last:
        dims = (1,) + tuple(kernel_size) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = [(0, 0)] + (list(pad) if not isinstance(pad, str) else pad) + [(0, 0)]
    else:
        dims = (1, 1) + tuple(kernel_size)
        strides = (1, 1) + tuple(stride)
        pads = [(0, 0), (0, 0)] + (list(pad) if not isinstance(pad, str) else pad)
    if isinstance(pad, str):
        pads = pad
    elif ceil_mode:
        # extend high padding so the last partial window is included
        spatial_axes = range(1, 1 + n_spatial) if channel_last else range(2, 2 + n_spatial)
        pads = list(pads)
        for i, ax in enumerate(spatial_axes):
            size = x.shape[ax] + pads[ax][0] + pads[ax][1]
            rem = (size - kernel_size[i]) % stride[i]
            if rem != 0:
                pads[ax] = (pads[ax][0], pads[ax][1] + stride[i] - rem)
    return jax.lax.reduce_window(x, init, reducer, dims, strides, pads)


def max_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCHW", name=None):
    if return_mask:
        if data_format != "NCHW" or ceil_mode:
            raise NotImplementedError(
                "return_mask supports NCHW without ceil_mode")
        # explicit-window path: emits the flat H*W argmax indices
        # max_unpool2d consumes (defined below)
        return _max_pool2d_with_mask(jnp.asarray(x), kernel_size, stride,
                                     padding)
    return _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                 jax.lax.max, -jnp.inf if jnp.issubdtype(jnp.asarray(x).dtype, np.floating)
                 else jnp.iinfo(jnp.asarray(x).dtype).min, ceil_mode)


def max_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, name=None):
    x4 = jnp.expand_dims(jnp.asarray(x), -1)
    k = _pair(kernel_size, 1) + (1,)
    s = None if stride is None else _pair(stride, 1) + (1,)
    p = _pair(padding, 1) + (0,) if not isinstance(padding, str) else padding
    out = max_pool2d(x4, k, s, p, ceil_mode=ceil_mode)
    return jnp.squeeze(out, -1)


def max_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               return_mask=False, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                 jax.lax.max, -jnp.inf, ceil_mode)


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    summed = _pool(x, kernel_size, stride, padding, 2, data_format == "NHWC",
                   jax.lax.add, 0.0 if jnp.issubdtype(x.dtype, np.floating) else 0, ceil_mode)
    if divisor_override:
        return summed / divisor_override
    if exclusive:
        ones = jnp.ones_like(x)
        counts = _pool(ones, kernel_size, stride, padding, 2, data_format == "NHWC",
                       jax.lax.add, 0.0, ceil_mode)
        return summed / counts
    k = _pair(kernel_size, 2)
    return summed / (k[0] * k[1])


def avg_pool1d(x, kernel_size, stride=None, padding=0, ceil_mode=False, exclusive=True, name=None):
    x4 = jnp.expand_dims(jnp.asarray(x), -1)
    k = _pair(kernel_size, 1) + (1,)
    s = None if stride is None else _pair(stride, 1) + (1,)
    p = _pair(padding, 1) + (0,) if not isinstance(padding, str) else padding
    out = avg_pool2d(x4, k, s, p, ceil_mode=ceil_mode, exclusive=exclusive)
    return jnp.squeeze(out, -1)


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    x = jnp.asarray(x)
    summed = _pool(x, kernel_size, stride, padding, 3, data_format == "NDHWC",
                   jax.lax.add, 0.0, ceil_mode)
    if divisor_override:
        return summed / divisor_override
    if exclusive:
        counts = _pool(jnp.ones_like(x), kernel_size, stride, padding, 3,
                       data_format == "NDHWC", jax.lax.add, 0.0, ceil_mode)
        return summed / counts
    k = _pair(kernel_size, 3)
    return summed / (k[0] * k[1] * k[2])


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    out_h, out_w = _pair(output_size, 2)
    if data_format == "NCHW":
        H, W = x.shape[2], x.shape[3]
    else:
        H, W = x.shape[1], x.shape[2]
    if out_h is None:
        out_h = H
    if out_w is None:
        out_w = W
    if H % out_h == 0 and W % out_w == 0:
        kh, kw = H // out_h, W // out_w
        return avg_pool2d(x, (kh, kw), (kh, kw), 0, data_format=data_format)
    # general adaptive: shared variable-window machinery (defined with the
    # 3d pools below)
    axes = (2, 3) if data_format == "NCHW" else (1, 2)
    return _adaptive_pool_nd(x, (out_h, out_w), axes, jnp.mean)


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    x = jnp.asarray(x)
    out_h, out_w = _pair(output_size, 2)
    H, W = x.shape[2], x.shape[3]
    if H % out_h == 0 and W % out_w == 0:
        kh, kw = H // out_h, W // out_w
        return max_pool2d(x, (kh, kw), (kh, kw), 0)
    return _adaptive_pool_nd(x, (out_h, out_w), (2, 3), jnp.max)


def adaptive_avg_pool1d(x, output_size, name=None):
    x4 = jnp.expand_dims(jnp.asarray(x), -1)
    out = adaptive_avg_pool2d(x4, (output_size, 1))
    return jnp.squeeze(out, -1)


# ------------------------------------------------------------- vision
def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    channel_last = not data_format.startswith("NC")
    n_spatial = x.ndim - 2
    if channel_last:
        spatial = x.shape[1:-1]
    else:
        spatial = x.shape[2:]
    if size is None:
        sf = _pair(scale_factor, n_spatial)
        size = tuple(int(s * f) for s, f in zip(spatial, sf))
    else:
        size = tuple(int(s) for s in _pair(size, n_spatial))
    method = {"nearest": "nearest", "bilinear": "linear", "trilinear": "linear",
              "linear": "linear", "bicubic": "cubic", "area": "linear"}[mode]
    if channel_last:
        new_shape = (x.shape[0],) + size + (x.shape[-1],)
    else:
        new_shape = x.shape[:2] + size
    if method == "nearest":
        return jax.image.resize(x, new_shape, method="nearest")
    if align_corners:
        # jax.image.resize has no align_corners; emulate with explicit gather
        idx = []
        for i, (in_s, out_s) in enumerate(zip(spatial, size)):
            if out_s == 1:
                pos = jnp.zeros((1,), jnp.float32)
            else:
                pos = jnp.linspace(0.0, in_s - 1.0, out_s)
            idx.append(pos)
        return _separable_linear_resize(x, idx, channel_last)
    return jax.image.resize(x, new_shape, method=method)


def _separable_linear_resize(x, positions, channel_last):
    n_spatial = len(positions)
    first_spatial_axis = 1 if channel_last else 2
    out = x
    for i, pos in enumerate(positions):
        axis = first_spatial_axis + i
        lo = jnp.floor(pos).astype(jnp.int32)
        hi = jnp.clip(lo + 1, 0, x.shape[axis] - 1 if False else out.shape[axis] - 1)
        w = (pos - lo).astype(out.dtype)
        lo = jnp.clip(lo, 0, out.shape[axis] - 1)
        a = jnp.take(out, lo, axis=axis)
        b = jnp.take(out, hi, axis=axis)
        shape = [1] * out.ndim
        shape[axis] = -1
        out = a * (1 - w.reshape(shape)) + b * w.reshape(shape)
    return out


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format="NCHW", name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    r = upscale_factor
    if data_format == "NCHW":
        N, C, H, W = x.shape
        x = x.reshape(N, C // (r * r), r, r, H, W)
        x = jnp.transpose(x, (0, 1, 4, 2, 5, 3))
        return x.reshape(N, C // (r * r), H * r, W * r)
    N, H, W, C = x.shape
    x = x.reshape(N, H, W, r, r, C // (r * r))
    x = jnp.transpose(x, (0, 1, 3, 2, 4, 5))
    return x.reshape(N, H * r, W * r, C // (r * r))


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    x = jnp.asarray(x)
    kh, kw = _pair(kernel_sizes, 2)
    sh, sw = _pair(strides, 2)
    ph, pw = _pair(paddings, 2)
    dh, dw = _pair(dilations, 2)
    N, C, H, W = x.shape
    patches = jax.lax.conv_general_dilated_patches(
        x, (kh, kw), (sh, sw), [(ph, ph), (pw, pw)], rhs_dilation=(dh, dw),
        dimension_numbers=jax.lax.conv_dimension_numbers(x.shape, (1, 1, kh, kw), ("NCHW", "OIHW", "NCHW")),
    )
    return patches.reshape(N, C * kh * kw, -1)


# ------------------------------------------------------------- losses
def _reduce_loss(loss, reduction):
    if reduction == "mean":
        return jnp.mean(loss)
    if reduction == "sum":
        return jnp.sum(loss)
    return loss


def mse_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _reduce_loss(jnp.square(jnp.asarray(input) - jnp.asarray(label)), reduction)


def l1_loss(input, label, reduction="mean", name=None):  # noqa: A002
    return _reduce_loss(jnp.abs(jnp.asarray(input) - jnp.asarray(label)), reduction)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):  # noqa: A002
    d = jnp.asarray(input) - jnp.asarray(label)
    ad = jnp.abs(d)
    loss = jnp.where(ad < delta, 0.5 * d * d / delta, ad - 0.5 * delta)
    return _reduce_loss(loss, reduction)


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",  # noqa: A002
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0, name=None):
    """Softmax cross entropy. TP-sharded variant lives in
    ``paddle_tpu.distributed.parallel.mp_layers.parallel_cross_entropy``."""
    logits = jnp.asarray(input)
    label = jnp.asarray(label)
    if use_softmax:
        logp = jax.nn.log_softmax(logits, axis=axis)
    else:
        logp = jnp.log(jnp.clip(logits, 1e-15, 1.0))
    if soft_label or (label.ndim == logits.ndim and label.shape == logits.shape):
        target = label.astype(logp.dtype)
        if label_smoothing > 0:
            k = logits.shape[axis]
            target = (1 - label_smoothing) * target + label_smoothing / k
        loss = -jnp.sum(target * logp, axis=axis)
        return _reduce_loss(loss, reduction)
    # hard labels (class indices); paddle allows a trailing 1 dim
    if label.ndim == logits.ndim and label.shape[axis] == 1:
        label = jnp.squeeze(label, axis=axis)
    valid = label != ignore_index
    safe_label = jnp.where(valid, label, 0)
    picked = jnp.take_along_axis(logp, safe_label[..., None].astype(jnp.int32), axis=axis)[..., 0]
    if label_smoothing > 0:
        k = logits.shape[axis]
        smooth_term = jnp.mean(logp, axis=axis)
        picked = (1 - label_smoothing) * picked + label_smoothing * smooth_term
    loss = -picked
    if weight is not None:
        w = jnp.take(jnp.asarray(weight), safe_label)
        loss = loss * w
        if reduction == "mean":
            denom = jnp.sum(jnp.where(valid, w, 0.0))
            return jnp.sum(jnp.where(valid, loss, 0.0)) / jnp.maximum(denom, 1e-12)
    loss = jnp.where(valid, loss, jnp.zeros_like(loss))
    if reduction == "mean":
        n_valid = jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
        return jnp.sum(loss) / n_valid
    return _reduce_loss(loss, reduction)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label, ignore_index=ignore_index,
                         reduction="none", axis=axis)[..., None]
    if return_softmax:
        return loss, jax.nn.softmax(jnp.asarray(logits), axis=axis)
    return loss


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):  # noqa: A002
    logp = jnp.asarray(input)
    label = jnp.asarray(label)
    valid = label != ignore_index
    safe = jnp.where(valid, label, 0)
    picked = jnp.take_along_axis(logp, safe[..., None].astype(jnp.int32), axis=-1)[..., 0]
    loss = -picked
    if weight is not None:
        w = jnp.take(jnp.asarray(weight), safe)
        loss = loss * w
        if reduction == "mean":
            return jnp.sum(jnp.where(valid, loss, 0.0)) / jnp.maximum(jnp.sum(jnp.where(valid, w, 0.0)), 1e-12)
    loss = jnp.where(valid, loss, jnp.zeros_like(loss))
    if reduction == "mean":
        return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(loss.dtype)), 1.0)
    return _reduce_loss(loss, reduction)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):  # noqa: A002
    p = jnp.clip(jnp.asarray(input), 1e-12, 1.0 - 1e-7)
    label = jnp.asarray(label)
    loss = -(label * jnp.log(p) + (1 - label) * jnp.log1p(-p))
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    z = jnp.asarray(logit)
    label = jnp.asarray(label)
    # numerically stable: max(z,0) - z*y + log(1+exp(-|z|))
    base = jnp.maximum(z, 0) - z * label + jnp.log1p(jnp.exp(-jnp.abs(z)))
    if pos_weight is not None:
        pw = jnp.asarray(pos_weight)
        log_sig = jax.nn.log_sigmoid(z)
        log_sig_neg = jax.nn.log_sigmoid(-z)
        base = -(pw * label * log_sig + (1 - label) * log_sig_neg)
    loss = base
    if weight is not None:
        loss = loss * weight
    return _reduce_loss(loss, reduction)


def kl_div(input, label, reduction="mean", name=None):  # noqa: A002
    logp = jnp.asarray(input)
    target = jnp.asarray(label)
    loss = target * (jnp.log(jnp.clip(target, 1e-12, None)) - logp)
    if reduction == "batchmean":
        return jnp.sum(loss) / loss.shape[0]
    return _reduce_loss(loss, reduction)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):  # noqa: A002
    loss = jnp.maximum(0.0, -jnp.asarray(label) * (jnp.asarray(input) - jnp.asarray(other)) + margin)
    return _reduce_loss(loss, reduction)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False, name=None):
    """Connectionist Temporal Classification loss (reference
    ``python/paddle/nn/functional/loss.py:1736`` over the warpctc C++ op).

    TPU-native: the forward (log-alpha) recursion over the blank-extended
    label sequence runs as ONE ``lax.scan`` over time, vectorized across
    the batch — gradients come from autodiff through the scan, so no
    hand-written backward kernel is needed.

    ``log_probs``: [T, B, C] logits (time-major, like warpctc; softmax is
    applied internally). ``labels``: [B, S] int padded ids.
    """
    lp = jax.nn.log_softmax(jnp.asarray(log_probs, jnp.float32), axis=-1)
    T, B, C = lp.shape
    labels = jnp.asarray(labels, jnp.int32)
    S = labels.shape[1]
    L = 2 * S + 1
    in_len = jnp.asarray(input_lengths, jnp.int32)
    lab_len = jnp.asarray(label_lengths, jnp.int32)
    NEG = jnp.float32(-1e30)

    # blank-extended sequence: [blank, l1, blank, l2, ..., blank]
    ext = jnp.full((B, L), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(labels)
    pos = jnp.arange(L)
    valid_pos = pos[None, :] < (2 * lab_len[:, None] + 1)
    # the i-2 skip is allowed only between distinct labels
    skip_ok = jnp.concatenate(
        [jnp.zeros((B, 2), bool), ext[:, 2:] != ext[:, :-2]], axis=1)

    def emit(lp_t):
        return jnp.take_along_axis(lp_t, ext, axis=1)  # [B, L]

    alpha0 = jnp.full((B, L), NEG)
    e0 = emit(lp[0])
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    if L > 1:
        alpha0 = alpha0.at[:, 1].set(
            jnp.where(lab_len > 0, e0[:, 1], NEG))
    alpha0 = jnp.where(valid_pos, alpha0, NEG)

    def step(alpha, lp_t):
        stay = alpha
        one = jnp.concatenate([jnp.full((B, 1), NEG), alpha[:, :-1]], 1)
        two = jnp.where(
            skip_ok,
            jnp.concatenate([jnp.full((B, 2), NEG), alpha[:, :-2]], 1),
            NEG)
        new = jnp.logaddexp(jnp.logaddexp(stay, one), two) + emit(lp_t)
        new = jnp.where(valid_pos, new, NEG)
        return new, new

    _, alphas = jax.lax.scan(step, alpha0, lp[1:])
    alphas = jnp.concatenate([alpha0[None], alphas], axis=0)  # [T, B, L]
    # alpha at each sequence's last frame
    a_fin = jnp.take_along_axis(
        alphas, jnp.clip(in_len - 1, 0)[None, :, None], axis=0)[0]
    end_blank = 2 * lab_len                       # final blank position
    end_label = jnp.maximum(2 * lab_len - 1, 0)   # final label position
    v1 = jnp.take_along_axis(a_fin, end_blank[:, None], 1)[:, 0]
    v2 = jnp.where(lab_len > 0,
                   jnp.take_along_axis(a_fin, end_label[:, None], 1)[:, 0],
                   NEG)
    loss = -jnp.logaddexp(v1, v2)
    if norm_by_times:
        # warpctc semantics: normalize only the GRADIENT by the number of
        # frames; the reported loss value is unchanged
        scaled = loss / jnp.maximum(in_len.astype(jnp.float32), 1.0)
        loss = jax.lax.stop_gradient(loss - scaled) + scaled
    if reduction == "mean":
        # reference mean is per-token: mean(loss_i / label_len_i)
        return jnp.mean(loss / jnp.maximum(
            lab_len.astype(jnp.float32), 1.0))
    return _reduce_loss(loss, reduction)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):  # noqa: A002
    x = jnp.asarray(input)
    y = jnp.asarray(label)
    loss = jnp.where(y == 1, x, jnp.maximum(0.0, margin - x))
    return _reduce_loss(loss, reduction)


def cosine_similarity(x1, x2, axis=1, eps=1e-8):
    x1, x2 = jnp.asarray(x1), jnp.asarray(x2)
    dot = jnp.sum(x1 * x2, axis=axis)
    n1 = jnp.linalg.norm(x1, axis=axis)
    n2 = jnp.linalg.norm(x2, axis=axis)
    return dot / jnp.maximum(n1 * n2, eps)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    cos = cosine_similarity(input1, input2, axis=-1)
    y = jnp.asarray(label)
    loss = jnp.where(y == 1, 1 - cos, jnp.maximum(0.0, cos - margin))
    return _reduce_loss(loss, reduction)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, eps=1e-6,  # noqa: A002
                        swap=False, reduction="mean", name=None):
    a, pos, neg = jnp.asarray(input), jnp.asarray(positive), jnp.asarray(negative)
    d_pos = jnp.linalg.norm(a - pos + eps, ord=p, axis=-1)
    d_neg = jnp.linalg.norm(a - neg + eps, ord=p, axis=-1)
    if swap:
        d_neg = jnp.minimum(d_neg, jnp.linalg.norm(pos - neg + eps, ord=p, axis=-1))
    loss = jnp.maximum(0.0, d_pos - d_neg + margin)
    return _reduce_loss(loss, reduction)


def square_error_cost(input, label):  # noqa: A002
    return jnp.square(jnp.asarray(input) - jnp.asarray(label))


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    z = jnp.asarray(logit)
    y = jnp.asarray(label)
    p = jax.nn.sigmoid(z)
    ce = jnp.maximum(z, 0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
    p_t = p * y + (1 - p) * (1 - y)
    a_t = alpha * y + (1 - alpha) * (1 - y)
    loss = a_t * ((1 - p_t) ** gamma) * ce
    if normalizer is not None:
        loss = loss / normalizer
    return _reduce_loss(loss, reduction)


# ------------------------------------------------------------- attention
def scaled_dot_product_attention(query, key, value, attn_mask=None, dropout_p=0.0,
                                 is_causal=False, training=True, name=None):
    """[B, L, H, D] layout (paddle convention). Dispatches to the Pallas
    flash-attention kernel on TPU for long sequences; falls back to the XLA
    composition otherwise (XLA fuses the softmax chain well up to ~2k seq).
    """
    q, k, v = jnp.asarray(query), jnp.asarray(key), jnp.asarray(value)
    from ..kernels import flash_attention as _fa

    p_drop = dropout_p if training else 0.0
    # tpu-lint: disable=R2(flash gate reads only static shape/dtype/platform of q,k — per-shape program selection inside the bucketed compile budget, re-audited PR 12)
    if _fa.should_use_flash(q, k, attn_mask, p_drop):
        bias, bias_grad = None, True
        if attn_mask is not None:
            m = jnp.asarray(attn_mask)
            if m.dtype == jnp.bool_:
                # boolean keep-mask: not trainable -> skip the dbias pass
                bias, bias_grad = jnp.where(m, 0.0, -1e30).astype(jnp.float32), False
            else:
                bias = m
        if p_drop > 0.0:
            seed = jax.random.randint(take_rng_key("dropout"), (), 0, 2**31 - 1)
        else:
            seed = 0
        return _fa.flash_attention_blhd(q, k, v, causal=is_causal, bias=bias,
                                        dropout_p=p_drop, seed=seed,
                                        bias_grad=bias_grad)
    scale = 1.0 / math.sqrt(q.shape[-1])
    # -> [B, H, L, D]
    qt, kt, vt = (jnp.swapaxes(t, 1, 2) for t in (q, k, v))
    scores = jnp.einsum("bhqd,bhkd->bhqk", qt, kt) * scale
    if is_causal:
        Lq, Lk = scores.shape[-2], scores.shape[-1]
        causal = jnp.tril(jnp.ones((Lq, Lk), dtype=bool), k=Lk - Lq)
        scores = jnp.where(causal, scores, jnp.asarray(-jnp.inf, scores.dtype))
    if attn_mask is not None:
        m = jnp.asarray(attn_mask)
        if m.dtype == jnp.bool_:
            scores = jnp.where(m, scores, jnp.asarray(-jnp.inf, scores.dtype))
        else:
            scores = scores + m.astype(scores.dtype)
    probs = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    if dropout_p > 0.0 and training:
        probs = dropout(probs, p=dropout_p, training=True)
    out = jnp.einsum("bhqk,bhkd->bhqd", probs, vt)
    return jnp.swapaxes(out, 1, 2)


# ------------------------------------------------------------- sequence utils
def sequence_mask(lengths, maxlen=None, dtype="bool"):
    lengths = jnp.asarray(lengths)
    if maxlen is None:
        raise ValueError("maxlen must be static under jit; pass it explicitly")
    row = jnp.arange(maxlen)
    mask = row[None, :] < lengths[..., None]
    return mask.astype(convert_dtype(dtype))


def temporal_shift(x, seg_num, shift_ratio=0.25, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    NT, C, H, W = x.shape
    x = x.reshape(NT // seg_num, seg_num, C, H, W)
    fold = int(C * shift_ratio)
    left = jnp.concatenate([x[:, 1:, :fold], jnp.zeros_like(x[:, :1, :fold])], axis=1)
    right = jnp.concatenate([jnp.zeros_like(x[:, :1, fold:2 * fold]), x[:, :-1, fold:2 * fold]], axis=1)
    mid = x[:, :, 2 * fold:]
    out = jnp.concatenate([left, right, mid], axis=2)
    return out.reshape(NT, C, H, W)


def pad(x, pad, mode="constant", value=0.0, data_format="NCHW", name=None):  # noqa: A002
    from ..ops.manipulation import pad as _pad

    return _pad(x, pad, mode=mode, value=value, data_format=data_format)


# ---------------------------------------------------- API long tail (r4)
# Reference parity for the remaining nn.functional exports
# (python/paddle/nn/functional/__init__.py __all__ audit).

def log_sigmoid(x, name=None):
    return jax.nn.log_sigmoid(jnp.asarray(x))


# "inplace" variants: jax arrays are immutable, so these are value aliases
# (the reference's _ ops mutate dygraph storage; semantics here match the
# functional form, which is what traced/compiled code sees either way)
def relu_(x, name=None):
    return relu(x)


def tanh_(x, name=None):
    return jnp.tanh(jnp.asarray(x))


def softmax_(x, axis=-1, dtype=None, name=None):
    return softmax(x, axis=axis, dtype=dtype)


def elu_(x, alpha=1.0, name=None):
    return elu(x, alpha=alpha)


def pairwise_distance(x, y, p=2.0, epsilon=1e-6, keepdim=False, name=None):
    d = jnp.asarray(x) - jnp.asarray(y) + epsilon
    return jnp.linalg.norm(d, ord=p, axis=-1, keepdims=keepdim)


def bilinear(x1, x2, weight, bias=None, name=None):
    """out[b, o] = x1[b, :] @ W[o] @ x2[b, :] (+ bias)."""
    out = jnp.einsum("bi,oij,bj->bo", jnp.asarray(x1), jnp.asarray(weight),
                     jnp.asarray(x2))
    if bias is not None:
        out = out + jnp.asarray(bias)
    return out


def diag_embed(input, offset=0, dim1=-2, dim2=-1, name=None):  # noqa: A002
    x = jnp.asarray(input)
    n = x.shape[-1] + abs(offset)
    out = jnp.zeros(x.shape[:-1] + (n, n), x.dtype)
    rng_ = jnp.arange(x.shape[-1])
    rows = rng_ + max(-offset, 0)
    cols = rng_ + max(offset, 0)
    out = out.at[..., rows, cols].set(x)
    # move the two new axes to dim1/dim2
    nd = out.ndim
    d1, d2 = dim1 % nd, dim2 % nd
    if (d1, d2) != (nd - 2, nd - 1):
        perm = [i for i in range(nd) if i not in (nd - 2, nd - 1)]
        order = sorted([(d1, nd - 2), (d2, nd - 1)])
        for dst, src in order:
            perm.insert(dst, src)
        out = jnp.transpose(out, perm)
    return out


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    if data_format == "NCHW":
        N, C, H, W = x.shape
        x = x.reshape(N, groups, C // groups, H, W)
        return jnp.swapaxes(x, 1, 2).reshape(N, C, H, W)
    N, H, W, C = x.shape
    x = x.reshape(N, H, W, groups, C // groups)
    return jnp.swapaxes(x, 3, 4).reshape(N, H, W, C)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    x = jnp.asarray(x)
    r = downscale_factor
    if data_format == "NCHW":
        N, C, H, W = x.shape
        x = x.reshape(N, C, H // r, r, W // r, r)
        x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
        return x.reshape(N, C * r * r, H // r, W // r)
    N, H, W, C = x.shape
    x = x.reshape(N, H // r, r, W // r, r, C)
    x = jnp.transpose(x, (0, 1, 3, 5, 2, 4))
    return x.reshape(N, H // r, W // r, C * r * r)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    left, right, top, bottom = _pair(padding, 4)
    x = jnp.asarray(x)
    if data_format == "NCHW":
        return jnp.pad(x, ((0, 0), (0, 0), (top, bottom), (left, right)))
    return jnp.pad(x, ((0, 0), (top, bottom), (left, right), (0, 0)))


def gather_tree(ids, parents):
    """Beam-search ancestry backtrace (reference ``gather_tree`` op):
    ``ids``/``parents`` [T, B, beam] -> full sequences re-rooted so every
    step follows the surviving beam's parent chain."""
    ids = jnp.asarray(ids)
    parents = jnp.asarray(parents)
    T, B, K = ids.shape
    binx = jnp.arange(B)[:, None]

    def step(beam_at_t, t):
        # walking backwards: pick each output beam's token, then its parent
        tok = ids[t][binx, beam_at_t]
        par = parents[t][binx, beam_at_t]
        return par, tok

    _, toks = jax.lax.scan(step, jnp.broadcast_to(jnp.arange(K), (B, K)),
                           jnp.arange(T - 1, -1, -1))
    return toks[::-1]


# ------------------------------------------------ pooling long tail (r4)
def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "return_mask is not supported for adaptive max pooling")
    x4 = jnp.expand_dims(jnp.asarray(x), -1)
    out = adaptive_max_pool2d(x4, (output_size, 1), return_mask=False)
    return jnp.squeeze(out, -1)


def _adaptive_pool_nd(x, output_size, axes, reduce_fn):
    def pool_axis(arr, axis, out_size):
        size = arr.shape[axis]
        starts = (np.arange(out_size) * size) // out_size
        ends = ((np.arange(out_size) + 1) * size + out_size - 1) // out_size
        segs = [reduce_fn(jax.lax.slice_in_dim(arr, int(s), int(e), axis=axis),
                          axis=axis, keepdims=True)
                for s, e in zip(starts, ends)]
        return jnp.concatenate(segs, axis=axis)

    for axis, osz in zip(axes, output_size):
        x = pool_axis(x, axis, osz)
    return x


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    x = jnp.asarray(x)
    sizes = _pair(output_size, 3)
    axes = (2, 3, 4) if data_format == "NCDHW" else (1, 2, 3)
    sizes = [x.shape[a] if s is None else int(s)
             for a, s in zip(axes, sizes)]
    return _adaptive_pool_nd(x, sizes, axes, jnp.mean)


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    if return_mask:
        raise NotImplementedError(
            "return_mask is not supported for adaptive max pooling")
    x = jnp.asarray(x)
    sizes = [x.shape[a] if s is None else int(s)
             for a, s in zip((2, 3, 4), _pair(output_size, 3))]
    return _adaptive_pool_nd(x, sizes, (2, 3, 4), jnp.max)


def _max_pool2d_with_mask(x, kernel_size, stride, padding):
    """(pooled, flat spatial argmax) via explicit window gathers — the
    indices max_unpool consumes (reference flattens over H*W)."""
    kh, kw = _pair(kernel_size, 2)
    sh, sw = _pair(stride or kernel_size, 2)
    ph, pw = _pair(padding, 2)
    N, C, H, W = x.shape
    Ho = (H + 2 * ph - kh) // sh + 1
    Wo = (W + 2 * pw - kw) // sw + 1
    rows = (np.arange(Ho)[:, None] * sh - ph) + np.arange(kh)[None]  # [Ho,kh]
    cols = (np.arange(Wo)[:, None] * sw - pw) + np.arange(kw)[None]
    rvalid = (rows >= 0) & (rows < H)
    cvalid = (cols >= 0) & (cols < W)
    rc = jnp.asarray(np.clip(rows, 0, H - 1))
    cc = jnp.asarray(np.clip(cols, 0, W - 1))
    # windows [N, C, Ho, kh, Wo, kw]
    wnd = x[:, :, rc][:, :, :, :, cc]
    mask = jnp.asarray(rvalid)[None, None, :, :, None, None] \
        & jnp.asarray(cvalid)[None, None, None, None, :, :]
    sentinel = (-jnp.inf if jnp.issubdtype(x.dtype, np.floating)
                else jnp.iinfo(x.dtype).min)  # keep int inputs int
    wnd = jnp.where(mask, wnd, sentinel)
    wnd = jnp.transpose(wnd, (0, 1, 2, 4, 3, 5)).reshape(
        N, C, Ho, Wo, kh * kw)
    arg = jnp.argmax(wnd, axis=-1)
    pooled = jnp.max(wnd, axis=-1)
    ar = jnp.take_along_axis(jnp.asarray(rows).reshape(1, 1, Ho, 1, kh),
                             (arg // kw)[..., None].astype(jnp.int32),
                             axis=4)[..., 0]
    acw = jnp.take_along_axis(jnp.asarray(cols).reshape(1, 1, 1, Wo, kw),
                              (arg % kw)[..., None].astype(jnp.int32),
                              axis=4)[..., 0]
    return pooled, (ar * W + acw).astype(jnp.int32)


def _flat_unpool(x, idx, out_len):
    """Scatter pooled values to their flat spatial argmax positions."""
    N, C = x.shape[:2]
    flat = jnp.zeros((N, C, out_len), x.dtype)
    nidx = jnp.arange(N)[:, None, None]
    cidx = jnp.arange(C)[None, :, None]
    return flat.at[nidx, cidx, idx.reshape(N, C, -1)].set(
        x.reshape(N, C, -1))


def max_unpool2d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCHW", name=None):
    """Scatter pooled values back to their argmax positions (reference
    ``max_unpool2d``; indices are flat over H*W, as ``max_pool2d``'s
    ``return_mask`` emits)."""
    x = jnp.asarray(x)
    idx = jnp.asarray(indices)
    kh, kw = _pair(kernel_size, 2)
    sh, sw = _pair(stride or kernel_size, 2)
    ph, pw = _pair(padding, 2)
    N, C, Ho, Wo = x.shape
    if output_size is None:
        H = (Ho - 1) * sh - 2 * ph + kh
        W = (Wo - 1) * sw - 2 * pw + kw
    else:
        H, W = output_size[-2], output_size[-1]
    return _flat_unpool(x, idx, H * W).reshape(N, C, H, W)


def max_unpool1d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCL", name=None):
    x4 = jnp.expand_dims(jnp.asarray(x), -1)
    i4 = jnp.expand_dims(jnp.asarray(indices), -1)
    osz = None if output_size is None else (output_size[-1], 1)
    out = max_unpool2d(x4, i4, (kernel_size, 1),
                       (stride or kernel_size, 1), (padding, 0), osz)
    return jnp.squeeze(out, -1)


def max_unpool3d(x, indices, kernel_size, stride=None, padding=0,
                 output_size=None, data_format="NCDHW", name=None):
    """Flat-over-D*H*W indices, same scatter as 2d."""
    x = jnp.asarray(x)
    idx = jnp.asarray(indices)
    kd, kh, kw = _pair(kernel_size, 3)
    sd, sh, sw = _pair(stride or kernel_size, 3)
    pd, ph, pw = _pair(padding, 3)
    N, C, Do, Ho, Wo = x.shape
    if output_size is None:
        D = (Do - 1) * sd - 2 * pd + kd
        H = (Ho - 1) * sh - 2 * ph + kh
        W = (Wo - 1) * sw - 2 * pw + kw
    else:
        D, H, W = output_size[-3:]
    return _flat_unpool(x, idx, D * H * W).reshape(N, C, D, H, W)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1,
         name=None):
    """Inverse of :func:`unfold`: scatter-add column patches back into the
    image (overlaps sum, reference ``fold``)."""
    x = jnp.asarray(x)                       # [N, C*kh*kw, L]
    H, W = _pair(output_sizes, 2)
    kh, kw = _pair(kernel_sizes, 2)
    sh, sw = _pair(strides, 2)
    ph, pw = _pair(paddings, 2)
    dh, dw = _pair(dilations, 2)
    N = x.shape[0]
    C = x.shape[1] // (kh * kw)
    Ho = (H + 2 * ph - dh * (kh - 1) - 1) // sh + 1
    Wo = (W + 2 * pw - dw * (kw - 1) - 1) // sw + 1
    cols = x.reshape(N, C, kh, kw, Ho, Wo)
    out = jnp.zeros((N, C, H + 2 * ph, W + 2 * pw), x.dtype)
    for i in range(kh):
        for j in range(kw):
            out = out.at[:, :, i * dh:i * dh + Ho * sh:sh,
                         j * dw:j * dw + Wo * sw:sw].add(cols[:, :, i, j])
    return out[:, :, ph:ph + H, pw:pw + W]


# ------------------------------------------------- loss long tail (r4)
def dice_loss(input, label, epsilon=1e-5, name=None):  # noqa: A002
    """Reference ``dice_loss``: input [N, ..., C] probabilities, label
    [N, ..., 1] class ids."""
    x = jnp.asarray(input)
    lab = jnp.asarray(label)
    if lab.shape[-1] == 1:
        lab = lab[..., 0]
    onehot = jax.nn.one_hot(lab, x.shape[-1], dtype=x.dtype)
    reduce_axes = tuple(range(1, x.ndim))
    inter = 2.0 * jnp.sum(x * onehot, axis=reduce_axes)
    denom = jnp.sum(x, axis=reduce_axes) + jnp.sum(onehot, axis=reduce_axes)
    return jnp.mean(1.0 - (inter + epsilon) / (denom + epsilon))


def log_loss(input, label, epsilon=1e-4, name=None):  # noqa: A002
    p = jnp.asarray(input)
    y = jnp.asarray(label).astype(p.dtype)
    return -(y * jnp.log(p + epsilon) + (1 - y) * jnp.log(1 - p + epsilon))


def soft_margin_loss(input, label, reduction="mean", name=None):  # noqa: A002
    x = jnp.asarray(input)
    y = jnp.asarray(label).astype(x.dtype)
    # softplus(-yx), not log1p(exp(-yx)): the latter overflows at |x|>~88
    return _reduce_loss(jax.nn.softplus(-y * x), reduction)


def multi_label_soft_margin_loss(input, label, weight=None,  # noqa: A002
                                 reduction="mean", name=None):
    x = jnp.asarray(input)
    y = jnp.asarray(label).astype(x.dtype)
    loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
    if weight is not None:
        loss = loss * jnp.asarray(weight)
    return _reduce_loss(jnp.mean(loss, axis=-1), reduction)


def multi_margin_loss(input, label, p: int = 1, margin: float = 1.0,  # noqa: A002
                      weight=None, reduction="mean", name=None):
    x = jnp.asarray(input)
    lab = jnp.asarray(label).astype(jnp.int32)
    target = jnp.take_along_axis(x, lab[:, None], axis=1)
    m = jnp.maximum(0.0, margin - target + x) ** p
    if weight is not None:
        m = m * jnp.take(jnp.asarray(weight), lab)[:, None]
    # exclude the target class term
    m = m * (1 - jax.nn.one_hot(lab, x.shape[1], dtype=x.dtype))
    return _reduce_loss(jnp.sum(m, axis=1) / x.shape[1], reduction)


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    a = jnp.asarray(anchor)
    pos = jnp.asarray(positive)
    lab = jnp.asarray(labels).reshape(-1)
    sim = a @ pos.T                                    # [B, B]
    tgt = (lab[:, None] == lab[None, :]).astype(sim.dtype)
    tgt = tgt / jnp.sum(tgt, axis=1, keepdims=True)
    xent = jnp.mean(jnp.sum(-tgt * jax.nn.log_softmax(sim, axis=1), axis=1))
    reg = l2_reg * (jnp.mean(jnp.sum(a * a, 1))
                    + jnp.mean(jnp.sum(pos * pos, 1))) * 0.25
    return xent + reg


def triplet_margin_with_distance_loss(input, positive, negative,  # noqa: A002
                                      distance_function=None, margin=1.0,
                                      swap=False, reduction="mean",
                                      name=None):
    dist = distance_function or (
        lambda a, b: jnp.linalg.norm(jnp.asarray(a) - jnp.asarray(b),
                                     axis=-1))
    dp = dist(input, positive)
    dn = dist(input, negative)
    if swap:
        dn = jnp.minimum(dn, dist(positive, negative))
    return _reduce_loss(jnp.maximum(0.0, dp - dn + margin), reduction)


def hsigmoid_loss(input, label, num_classes, weight, bias=None,  # noqa: A002
                  path_table=None, path_code=None, is_sparse=False,
                  name=None):
    """Hierarchical sigmoid (reference ``hsigmoid_loss``): default
    complete-binary-tree coding (word2vec heap scheme — leaf ``c`` is heap
    node ``num_classes + c``; internal nodes 1..num_classes-1, weight row
    = node - 1), or a custom tree via path_table/path_code."""
    x = jnp.asarray(input)
    lab = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    w = jnp.asarray(weight)
    if path_table is None:
        depth = int(math.ceil(math.log2(max(num_classes, 2)))) + 1
        nodes, codes, masks = [], [], []
        node = lab + num_classes
        for _ in range(depth):
            parent = node // 2
            codes.append((node % 2).astype(jnp.float32))
            live = parent >= 1
            masks.append(live.astype(jnp.float32))
            nodes.append(jnp.where(live, parent, 1))
            node = parent
        path_table = jnp.stack(nodes, 1) - 1          # weight rows
        path_code = jnp.stack(codes, 1)
        mask = jnp.stack(masks, 1)
    else:
        path_table = jnp.asarray(path_table)
        path_code = jnp.asarray(path_code).astype(jnp.float32)
        mask = (path_table >= 0).astype(jnp.float32)
        path_table = jnp.maximum(path_table, 0)
    logits = jnp.einsum("bd,bkd->bk", x, w[path_table])
    if bias is not None:
        logits = logits + jnp.asarray(bias).reshape(-1)[path_table]
    # code 1 -> sigmoid(logit), code 0 -> sigmoid(-logit)
    sign = 2.0 * path_code - 1.0
    nll = jax.nn.softplus(-sign * logits) * mask
    return jnp.sum(nll, axis=1, keepdims=True)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5,
                         margin3=0.0, scale=64.0, group=None,
                         return_softmax=False, reduction="mean", name=None):
    """ArcFace-style combined-margin softmax (reference
    ``margin_cross_entropy``): target logit cos(theta) becomes
    cos(m1*theta + m2) - m3, everything scaled by ``scale``."""
    cos = jnp.clip(jnp.asarray(logits), -1.0, 1.0)
    lab = jnp.asarray(label).reshape(-1).astype(jnp.int32)
    onehot = jax.nn.one_hot(lab, cos.shape[-1], dtype=cos.dtype)
    theta = jnp.arccos(jnp.clip(cos, -1 + 1e-7, 1 - 1e-7))
    target = jnp.cos(margin1 * theta + margin2) - margin3
    adj = jnp.where(onehot > 0, target, cos) * scale
    logp = jax.nn.log_softmax(adj, axis=-1)
    loss = -jnp.take_along_axis(logp, lab[:, None], axis=1)[:, 0]
    loss = _reduce_loss(loss, reduction)
    if return_softmax:
        return loss, jax.nn.softmax(adj, axis=-1)
    return loss


def class_center_sample(label, num_classes, num_samples, group=None):
    """Partial-FC class-center sampling (reference
    ``class_center_sample``): keep every positive class plus random
    negatives up to ``num_samples``; labels are remapped into the sampled
    index space. Host-side/eager (data-prep op, dynamic output)."""
    lab = np.asarray(label).reshape(-1)
    pos = np.unique(lab)
    rest = np.setdiff1d(np.arange(num_classes), pos)
    n_extra = max(0, num_samples - pos.size)
    from ..framework.random import next_key

    # framework-governed randomness: varies per call, reproducible under
    # paddle_tpu.seed (the label-sum seeding an earlier draft used would
    # resample the SAME negatives for any batch with colliding label sums)
    seed = int(jax.random.randint(next_key(), (), 0, 2 ** 31 - 1))
    rng = np.random.default_rng(seed)
    extra = rng.choice(rest, size=min(n_extra, rest.size), replace=False)
    sampled = np.concatenate([pos, np.sort(extra)]).astype(np.int64)
    remap = {c: i for i, c in enumerate(sampled)}
    remapped = np.asarray([remap[c] for c in lab], np.int64)
    return jnp.asarray(remapped), jnp.asarray(sampled)


# ------------------------------------------------ vision warps (r4)
def affine_grid(theta, out_shape, align_corners=True, name=None):
    """[N, 2, 3] affine params -> [N, H, W, 2] normalized sampling grid."""
    theta = jnp.asarray(theta)
    N, _, H, W = (out_shape[0], out_shape[1], out_shape[2], out_shape[3])

    def axis_coords(n):
        if align_corners:
            return jnp.linspace(-1.0, 1.0, n)
        step = 2.0 / n
        return jnp.linspace(-1.0 + step / 2, 1.0 - step / 2, n)

    ys = axis_coords(H)
    xs = axis_coords(W)
    gy, gx = jnp.meshgrid(ys, xs, indexing="ij")
    base = jnp.stack([gx, gy, jnp.ones_like(gx)], axis=-1)  # [H, W, 3]
    return jnp.einsum("hwk,nik->nhwi", base, theta)


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample [N, C, H, W] at normalized grid [N, Ho, Wo, 2] (reference
    ``grid_sample``; bilinear/nearest, zeros/border/reflection padding)."""
    x = jnp.asarray(x)
    grid = jnp.asarray(grid)
    N, C, H, W = x.shape

    def unnorm(g, size):
        if align_corners:
            return (g + 1) * (size - 1) / 2
        return ((g + 1) * size - 1) / 2

    gx = unnorm(grid[..., 0], W)
    gy = unnorm(grid[..., 1], H)

    def reflect(v, lo, hi):
        rng_ = hi - lo
        if rng_ <= 0:  # size-1 axis: every coordinate maps to the texel
            return jnp.full_like(v, max(lo, 0.0))
        v = jnp.abs((v - lo) % (2 * rng_))
        return jnp.where(v > rng_, 2 * rng_ - v, v) + lo

    if padding_mode == "reflection":
        # reference semantics: reflect about [0, s-1] with align_corners,
        # about [-0.5, s-0.5] without
        if align_corners:
            gx = reflect(gx, 0.0, W - 1.0)
            gy = reflect(gy, 0.0, H - 1.0)
        else:
            gx = jnp.clip(reflect(gx, -0.5, W - 0.5), 0, W - 1)
            gy = jnp.clip(reflect(gy, -0.5, H - 0.5), 0, H - 1)

    def gather(ix, iy):
        inb = (ix >= 0) & (ix < W) & (iy >= 0) & (iy < H)
        ixc = jnp.clip(ix, 0, W - 1)
        iyc = jnp.clip(iy, 0, H - 1)
        vals = x[jnp.arange(N)[:, None, None], :, iyc, ixc]  # [N,Ho,Wo,C]
        if padding_mode == "zeros":
            vals = vals * inb[..., None]
        return vals

    if mode == "nearest":
        out = gather(jnp.round(gx).astype(jnp.int32),
                     jnp.round(gy).astype(jnp.int32))
        return jnp.moveaxis(out, -1, 1)
    x0 = jnp.floor(gx).astype(jnp.int32)
    y0 = jnp.floor(gy).astype(jnp.int32)
    wx = (gx - x0)[..., None]
    wy = (gy - y0)[..., None]
    out = (gather(x0, y0) * (1 - wx) * (1 - wy)
           + gather(x0 + 1, y0) * wx * (1 - wy)
           + gather(x0, y0 + 1) * (1 - wx) * wy
           + gather(x0 + 1, y0 + 1) * wx * wy)
    return jnp.moveaxis(out, -1, 1)


def sparse_attention(query, key, value, sparse_csr_offset,
                     sparse_csr_columns, key_padding_mask=None,
                     attn_mask=None, name=None):
    """Block-sparse attention (reference CUDA-only ``sparse_attention``).
    TPU stance: the CSR layout is materialized as a dense boolean mask and
    fed to the fused XLA softmax-attention — numerically identical to the
    reference; for real long-context sparsity use the Pallas flash kernel
    (``kernels/flash_attention``) or ring attention instead."""
    q = jnp.asarray(query)
    k = jnp.asarray(key)
    v = jnp.asarray(value)
    B, H, L, D = q.shape
    offs = np.asarray(sparse_csr_offset)
    cols = np.asarray(sparse_csr_columns)
    mask = np.zeros((B, H, L, L), bool)
    for b in range(B):
        for h in range(H):
            o = offs[b, h]
            c = cols[b, h]
            for r in range(L):
                mask[b, h, r, c[o[r]:o[r + 1]]] = True
    s = jnp.einsum("bhld,bhmd->bhlm", q, k) / math.sqrt(D)
    s = jnp.where(jnp.asarray(mask), s, -1e30)
    if attn_mask is not None:
        s = s + jnp.asarray(attn_mask)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhlm,bhmd->bhld", p, v)
