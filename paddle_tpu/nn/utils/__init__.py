"""Parameter reparameterization utilities.

Reference parity: ``python/paddle/nn/utils/`` (``weight_norm_hook.py``,
``spectral_norm_hook.py``, ``transform_parameters.py``). TPU-native: the
reparameterization runs in a forward-pre-hook; under ``functional_call``
the hook sees traced ``weight_g``/``weight_v`` leaves, so the recompute
jit-compiles into the step like any other op.
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from ..layer import Layer

__all__ = ["weight_norm", "remove_weight_norm", "spectral_norm",
           "parameters_to_vector", "vector_to_parameters"]


def _norm_except_dim(v, dim: Optional[int]):
    if dim is None:
        return jnp.sqrt(jnp.sum(v * v))
    axes = tuple(i for i in range(v.ndim) if i != dim % v.ndim)
    return jnp.sqrt(jnp.sum(v * v, axis=axes, keepdims=True))


def weight_norm(layer: Layer, name: str = "weight", dim: Optional[int] = 0):
    """Reparameterize ``layer.<name>`` as ``g * v / ||v||`` (reference
    ``weight_norm``): magnitude ``<name>_g`` and direction ``<name>_v``
    train independently."""
    if f"{name}_v" in layer._parameters:
        raise ValueError(f"weight_norm already applied to {name!r}")
    w = layer._parameters.pop(name)
    g = _norm_except_dim(jnp.asarray(w), dim)
    layer.add_parameter(f"{name}_g", g)
    layer.add_parameter(f"{name}_v", jnp.asarray(w))

    def hook(lyr, inputs):
        v = getattr(lyr, f"{name}_v")
        gg = getattr(lyr, f"{name}_g")
        object.__setattr__(lyr, name,
                           gg * v / (_norm_except_dim(v, dim) + 1e-12))
        return None

    helper = layer.register_forward_pre_hook(hook)
    if not hasattr(layer, "_wn_state"):
        object.__setattr__(layer, "_wn_state", {})
    layer._wn_state[name] = {"dim": dim, "hook": helper}  # per-param entry
    hook(layer, ())  # materialize eagerly so .weight reads work pre-forward
    return layer


def remove_weight_norm(layer: Layer, name: str = "weight"):
    """Fold g/v back into a plain ``<name>`` parameter."""
    state = getattr(layer, "_wn_state", {}).get(name)
    if state is None:
        raise ValueError(f"{name!r} has no weight norm to remove")
    v = layer._parameters.pop(f"{name}_v")
    g = layer._parameters.pop(f"{name}_g")
    w = g * v / (_norm_except_dim(v, state["dim"]) + 1e-12)
    state["hook"].remove()
    del layer._wn_state[name]
    layer.add_parameter(name, w)
    return layer


def spectral_norm(layer: Layer, name: str = "weight",
                  n_power_iterations: int = 1, eps: float = 1e-12,
                  dim: int = 0):
    """Divide ``layer.<name>`` by its largest singular value, estimated by
    power iteration carried in ``<name>_u``/``<name>_v`` buffers (reference
    ``spectral_norm``)."""
    w = jnp.asarray(layer._parameters.pop(name))
    layer.add_parameter(f"{name}_orig", w)
    mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
    key = jax.random.key(0)
    ku, kv = jax.random.split(key)
    layer.register_buffer(f"{name}_u", jax.random.normal(ku, (mat.shape[0],)))
    layer.register_buffer(f"{name}_v", jax.random.normal(kv, (mat.shape[1],)))

    def _l2(x):
        return x / (jnp.linalg.norm(x) + eps)

    def hook(lyr, inputs):
        w_orig = getattr(lyr, f"{name}_orig")
        m = jnp.moveaxis(w_orig, dim, 0).reshape(w_orig.shape[dim], -1)
        u = getattr(lyr, f"{name}_u")
        v = getattr(lyr, f"{name}_v")
        for _ in range(n_power_iterations):
            v = _l2(m.T @ u)
            u = _l2(m @ v)
        u = jax.lax.stop_gradient(u)
        v = jax.lax.stop_gradient(v)
        # persist the iteration (buffer update flows through functional_call)
        lyr._buffers[f"{name}_u"] = u
        lyr._buffers[f"{name}_v"] = v
        sigma = u @ (m @ v)
        object.__setattr__(lyr, name, w_orig / sigma)
        return None

    layer.register_forward_pre_hook(hook)
    hook(layer, ())
    return layer


def parameters_to_vector(parameters, name=None):
    """Flatten a parameter list into one vector (reference
    ``transform_parameters.py``)."""
    return jnp.concatenate([jnp.asarray(p).reshape(-1) for p in parameters])


def vector_to_parameters(vec, parameters, name=None):
    """Split ``vec`` back into arrays shaped like ``parameters``.

    DIFFERENCE from the reference: paddle writes the slices into the
    parameter tensors in place; jax arrays are immutable, so this RETURNS
    the new arrays — assign them back yourself (e.g. rebuild a state_dict
    and ``layer.set_state_dict`` it). Discarding the return value does
    nothing."""
    out, off = [], 0
    vec = jnp.asarray(vec)
    for p in parameters:
        a = jnp.asarray(p)
        out.append(vec[off:off + a.size].reshape(a.shape))
        off += a.size
    return out
