"""Parameter initializers.

Reference parity: ``python/paddle/nn/initializer/`` (Constant, Normal,
TruncatedNormal, Uniform, Xavier*, Kaiming*, Assign, Orthogonal, Dirac).
Each initializer is a callable ``(key, shape, dtype) -> jax.Array``; keys come
from the global generator at layer-construction time.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        # paddle linear weights are [in, out]
        return shape[0], shape[1]
    # conv kernels [out_c, in_c, *spatial] (paddle layout)
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, key, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, key, shape, dtype):
        return jnp.full(shape, self.value, dtype=dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, key, shape, dtype):
        return jax.random.normal(key, shape, dtype=dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, key, shape, dtype):
        # truncation at 2 sigma, matching the reference's
        # truncated_gaussian_random kernel
        return jax.random.truncated_normal(key, -2.0, 2.0, shape, dtype=dtype) * self.std + self.mean


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, key, shape, dtype):
        return jax.random.uniform(key, shape, dtype=dtype, minval=self.low, maxval=self.high)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, key, shape, dtype):
        fin, fout = _fans(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        std = self.gain * math.sqrt(2.0 / (fin + fout))
        return jax.random.normal(key, shape, dtype=dtype) * std


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, key, shape, dtype):
        fin, fout = _fans(shape)
        fin = self.fan_in or fin
        fout = self.fan_out or fout
        limit = self.gain * math.sqrt(6.0 / (fin + fout))
        return jax.random.uniform(key, shape, dtype=dtype, minval=-limit, maxval=limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, key, shape, dtype):
        fin, _ = _fans(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        std = gain / math.sqrt(fin)
        return jax.random.normal(key, shape, dtype=dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu"):
        self.fan_in = fan_in
        self.negative_slope = negative_slope
        self.nonlinearity = nonlinearity

    def __call__(self, key, shape, dtype):
        fin, _ = _fans(shape)
        fin = self.fan_in or fin
        gain = math.sqrt(2.0 / (1 + self.negative_slope**2)) if self.nonlinearity in ("relu", "leaky_relu") else 1.0
        limit = gain * math.sqrt(3.0 / fin)
        return jax.random.uniform(key, shape, dtype=dtype, minval=-limit, maxval=limit)


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, key, shape, dtype):
        out = jnp.asarray(self.value, dtype=dtype)
        if tuple(out.shape) != tuple(shape):
            out = out.reshape(shape)
        return out


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, key, shape, dtype):
        return jax.nn.initializers.orthogonal(scale=self.gain)(key, shape, dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, key, shape, dtype):
        # identity-preserving conv kernel [out_c, in_c, *spatial]
        out = np.zeros(shape, dtype=np.float32)
        out_c, in_c = shape[0], shape[1]
        spatial_center = tuple(s // 2 for s in shape[2:])
        per_group = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                out[(g * per_group + i, i) + spatial_center] = 1.0
        return jnp.asarray(out, dtype=dtype)


class Bilinear(Initializer):
    """Bilinear-upsampling kernel init for transposed convs (reference
    ``fluid/initializer.py`` ``BilinearInitializer``): every [kh, kw]
    position of the 4-D weight gets ``(1-|x/f-c|)(1-|y/f-c|)`` with
    ``f = ceil(k/2)``, ``c = (2f-1-f%2)/(2f)`` — a conv_transpose with
    ``stride=factor``, ``kernel=2*factor-factor%2`` then upsamples by
    ``factor`` exactly."""

    def __call__(self, key, shape, dtype):
        shape = tuple(shape)
        if len(shape) != 4:
            raise ValueError("Bilinear initializer needs a 4-D weight")
        if shape[2] != shape[3]:
            raise ValueError("Bilinear initializer needs square kernels")
        k = shape[3]
        f = math.ceil(k / 2.0)
        c = (2 * f - 1 - f % 2) / (2.0 * f)
        x = np.arange(k)
        filt = (1 - np.abs(x / f - c))
        patt = np.outer(filt, filt).astype(np.float32)
        return jnp.broadcast_to(jnp.asarray(patt), shape).astype(dtype)


# global defaults installed by set_global_initializer: [weight, bias]
_GLOBAL_INIT = [None, None]


def set_global_initializer(weight_init, bias_init=None):
    """Set the framework-wide default initializers (reference
    ``fluid/initializer.py:1346``): they apply to parameters created
    WITHOUT an explicit ``param_attr``/``bias_attr`` initializer (which
    keeps priority), replacing each layer's built-in default. Pass
    ``None`` to cancel."""
    if weight_init is not None and not isinstance(weight_init, Initializer):
        raise TypeError("weight_init must be an Initializer or None")
    if bias_init is not None and not isinstance(bias_init, Initializer):
        raise TypeError("bias_init must be an Initializer or None")
    _GLOBAL_INIT[0] = weight_init
    _GLOBAL_INIT[1] = bias_init


def _resolve_initializer(attr, default_initializer, is_bias: bool = False):
    """Priority (the reference's contract): an initializer carried by
    ``attr`` (ParamAttr-ish or a bare Initializer) wins; then the global
    default installed by :func:`set_global_initializer`; then the
    caller's ``default_initializer`` (the layer's built-in)."""
    if attr is not None and attr is not False:
        if isinstance(attr, Initializer):
            return attr
        init = getattr(attr, "initializer", None)
        if isinstance(init, Initializer):
            return init
    ginit = _GLOBAL_INIT[1] if is_bias else _GLOBAL_INIT[0]
    if ginit is not None:
        return ginit
    return default_initializer


# paddle also exposes functional-style aliases
constant = Constant
normal = Normal
uniform = Uniform
xavier_normal = XavierNormal
xavier_uniform = XavierUniform
kaiming_normal = KaimingNormal
kaiming_uniform = KaimingUniform


def calculate_gain(nonlinearity, param=None):
    if nonlinearity == "tanh":
        return 5.0 / 3
    if nonlinearity == "relu":
        return math.sqrt(2.0)
    if nonlinearity == "leaky_relu":
        a = 0.01 if param is None else param
        return math.sqrt(2.0 / (1 + a**2))
    if nonlinearity == "selu":
        return 3.0 / 4
    return 1.0
