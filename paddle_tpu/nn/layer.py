"""Layer: the module base class.

Reference parity: ``python/paddle/fluid/dygraph/layers.py`` (Layer with
sublayers/parameters/buffers/hooks/state_dict). TPU-native twist: a Layer is
*also* a functional program — :func:`functional_call` runs a layer with an
explicit parameter/buffer pytree and returns updated buffers, which is what a
``jit``-compiled train step differentiates. Eager forward (outside jit) works
directly on the stored arrays, giving the reference's dygraph feel.

No autograd tape exists here: the reference's 21k-LoC eager GradNode engine
(``paddle/fluid/eager/``) is replaced by ``jax.grad`` over
:func:`functional_call`.
"""
from __future__ import annotations

import contextlib
from collections import OrderedDict
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..framework.dtype import convert_dtype, get_default_dtype
from ..framework import random as framework_random


# --------------------------------------------------------------------- RNG
class RNGContext:
    """Named deterministic key streams for functional calls.

    The analogue of the reference's ``RNGStatesTracker``
    (``fleet/meta_parallel/parallel_layers/random.py:32``): each named stream
    (e.g. "dropout", "global") yields keys by folding an incrementing counter
    into a base key, so a traced forward is deterministic given the base keys.
    """

    def __init__(self, rngs: Dict[str, Any]):
        self._base = dict(rngs)
        self._counters: Dict[str, int] = {}

    def next(self, name: str = "dropout"):
        base = self._base.get(name)
        if base is None:
            base = self._base.get("default")
        if base is None:
            return None
        c = self._counters.get(name, 0)
        self._counters[name] = c + 1
        return jax.random.fold_in(base, c)


_rng_ctx_stack: List[RNGContext] = []


@contextlib.contextmanager
def rng_context(rngs: Dict[str, Any]):
    ctx = RNGContext(rngs)
    _rng_ctx_stack.append(ctx)
    try:
        yield ctx
    finally:
        _rng_ctx_stack.pop()


def take_rng_key(name: str = "dropout"):
    """Key for stochastic layers: functional stream when inside a
    functional_call, global stateful generator otherwise (eager)."""
    if _rng_ctx_stack:
        key = _rng_ctx_stack[-1].next(name)
        if key is not None:
            return key
        raise RuntimeError(
            f"layer requested rng stream {name!r} inside a functional call, "
            f"but no key was provided via rngs="
        )
    return framework_random.next_key()


# --------------------------------------------------------------------- Layer
class Parameter:
    """Marker wrapper: assigning a ``Parameter`` to a Layer attribute registers
    it in ``_parameters`` (the role the reference's ``EagerParamBase`` subclass
    check plays in ``Layer.__setattr__``, ``layers.py``). The stored value is
    always the raw ``jax.Array``; this wrapper exists only at assignment time.
    """

    __slots__ = ("value", "trainable")

    def __init__(self, value, trainable: bool = True):
        self.value = jnp.asarray(value)
        self.trainable = trainable


class Layer:
    """Base class for all neural network layers."""

    def __init__(self, name_scope: Optional[str] = None, dtype=None):
        # use object.__setattr__ to avoid recursion before dicts exist
        object.__setattr__(self, "_parameters", OrderedDict())
        object.__setattr__(self, "_buffers", OrderedDict())
        object.__setattr__(self, "_non_persistable_buffer_names", set())
        object.__setattr__(self, "_sub_layers", OrderedDict())
        self.training = True
        self._dtype = convert_dtype(dtype) or get_default_dtype()
        # per-parameter PartitionSpec-like tuples (local names); collected
        # tree-wide by paddle_tpu.distributed.shard.param_shardings()
        self._param_shardings: Dict[str, tuple] = {}
        self._forward_pre_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._forward_post_hooks: "OrderedDict[int, Callable]" = OrderedDict()
        self._hook_id = 0
        self._name_scope = name_scope or type(self).__name__.lower()

    # ------------------------------------------------------------- attributes
    def __setattr__(self, name: str, value: Any):
        params = self.__dict__.get("_parameters")
        subs = self.__dict__.get("_sub_layers")
        bufs = self.__dict__.get("_buffers")
        if isinstance(value, Layer):
            if subs is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            subs[name] = value
            self.__dict__.pop(name, None)
            return
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value.value
            self.__dict__.pop(name, None)
            return
        if params is not None and name in params:
            if value is None:
                del params[name]
                object.__setattr__(self, name, None)
            else:
                params[name] = jnp.asarray(value)
            return
        if bufs is not None and name in bufs:
            bufs[name] = jnp.asarray(value)
            return
        if subs is not None and name in subs:
            if value is None:
                del subs[name]
            else:
                subs[name] = value
            if not isinstance(value, Layer):
                object.__setattr__(self, name, value)
            return
        object.__setattr__(self, name, value)

    def __getattr__(self, name: str):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"{type(self).__name__!r} object has no attribute {name!r}")

    def __delattr__(self, name: str):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    # ------------------------------------------------------------- creation
    def create_parameter(
        self,
        shape,
        dtype=None,
        attr=None,
        is_bias: bool = False,
        default_initializer=None,
    ):
        """Create (and return) a parameter array. Mirrors
        ``Layer.create_parameter`` (reference ``layers.py``); ParamAttr is
        reduced to optional initializer/name."""
        from .initializer import Constant, XavierUniform, _resolve_initializer

        dtype = convert_dtype(dtype) or self._dtype
        init = _resolve_initializer(attr, default_initializer,
                                    is_bias=is_bias)
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        key = framework_random.next_key()
        return Parameter(init(key, tuple(shape), dtype))

    def add_parameter(self, name: str, parameter):
        if parameter is None:
            self._parameters[name] = None
        elif isinstance(parameter, Parameter):
            self._parameters[name] = parameter.value
        else:
            self._parameters[name] = jnp.asarray(parameter)
        self.__dict__.pop(name, None)
        return self._parameters.get(name)

    def register_buffer(self, name: str, tensor, persistable: bool = True):
        self._buffers[name] = None if tensor is None else jnp.asarray(tensor)
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        self.__dict__.pop(name, None)
        return self._buffers.get(name)

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def set_param_sharding(self, name: str, spec: tuple):
        """Declare how parameter ``name`` (local) shards over mesh axes,
        e.g. ``("mp", None)`` for a vocab-sharded embedding. GSPMD inserts
        the collectives the reference writes by hand in mp_layers.py."""
        self._param_shardings[name] = tuple(spec)

    def named_param_shardings(self, prefix: str = ""):
        for name, spec in self._param_shardings.items():
            yield (f"{prefix}.{name}" if prefix else name), spec
        for sname, sub in self._sub_layers.items():
            if sub is None:
                continue
            sp = f"{prefix}.{sname}" if prefix else sname
            yield from sub.named_param_shardings(prefix=sp)

    # ------------------------------------------------------------- traversal
    def named_sublayers(self, prefix: str = "", include_self: bool = False) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, sub in self._sub_layers.items():
            if sub is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield p, sub
            yield from sub.named_sublayers(prefix=p)

    def sublayers(self, include_self: bool = False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self) -> Iterator["Layer"]:
        for sub in self._sub_layers.values():
            if sub is not None:
                yield sub

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, sub in self._sub_layers.items():
            if sub is not None:
                yield name, sub

    def named_parameters(self, prefix: str = "", include_sublayers: bool = True):
        for name, p in self._parameters.items():
            if p is not None:
                yield (f"{prefix}.{name}" if prefix else name), p
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{sname}" if prefix else sname
                yield from sub.named_parameters(prefix=sp)

    def parameters(self, include_sublayers: bool = True) -> List[Any]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix: str = "", include_sublayers: bool = True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (f"{prefix}.{name}" if prefix else name), b
        if include_sublayers:
            for sname, sub in self._sub_layers.items():
                if sub is None:
                    continue
                sp = f"{prefix}.{sname}" if prefix else sname
                yield from sub.named_buffers(prefix=sp)

    def buffers(self, include_sublayers: bool = True) -> List[Any]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def apply(self, fn: Callable[["Layer"], None]) -> "Layer":
        for sub in self.children():
            sub.apply(fn)
        fn(self)
        return self

    # ------------------------------------------------------------- mode
    def train(self) -> "Layer":
        self.training = True
        for sub in self.children():
            sub.train()
        return self

    def eval(self) -> "Layer":
        self.training = False
        for sub in self.children():
            sub.eval()
        return self

    # ------------------------------------------------------------- hooks
    def register_forward_pre_hook(self, hook) -> "HookRemoveHelper":
        self._hook_id += 1
        self._forward_pre_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id)

    def register_forward_post_hook(self, hook) -> "HookRemoveHelper":
        self._hook_id += 1
        self._forward_post_hooks[self._hook_id] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id)

    # ------------------------------------------------------------- state dict
    def state_dict(self, destination=None, include_sublayers: bool = True,
                   structured_name_prefix: str = "") -> "OrderedDict[str, Any]":
        out = OrderedDict() if destination is None else destination
        for name, p in self.named_parameters(prefix=structured_name_prefix.rstrip(".")):
            out[name] = p
        for name, b in self._named_persistable_buffers(prefix=structured_name_prefix.rstrip(".")):
            out[name] = b
        return out

    def _named_persistable_buffers(self, prefix: str = ""):
        for name, b in self._buffers.items():
            if b is not None and name not in self._non_persistable_buffer_names:
                yield (f"{prefix}.{name}" if prefix else name), b
        for sname, sub in self._sub_layers.items():
            if sub is None:
                continue
            sp = f"{prefix}.{sname}" if prefix else sname
            yield from sub._named_persistable_buffers(prefix=sp)

    def set_state_dict(self, state_dict: Dict[str, Any], use_structured_name: bool = True):
        missing, unexpected = [], []
        consumed = set()
        for name, _ in list(self.named_parameters()) + list(self.named_buffers()):
            if name in state_dict:
                self._set_by_path(name, jnp.asarray(state_dict[name]))
                consumed.add(name)
            else:
                missing.append(name)
        unexpected = [k for k in state_dict if k not in consumed]
        return missing, unexpected

    load_dict = set_state_dict

    def _set_by_path(self, path: str, value):
        parts = path.split(".")
        layer = self
        for p in parts[:-1]:
            layer = layer._sub_layers[p]
        leaf = parts[-1]
        if leaf in layer._parameters:
            layer._parameters[leaf] = value
        elif leaf in layer._buffers:
            layer._buffers[leaf] = value
        else:
            raise KeyError(f"no parameter or buffer named {path}")

    def _get_by_path(self, path: str):
        parts = path.split(".")
        layer = self
        for p in parts[:-1]:
            layer = layer._sub_layers[p]
        leaf = parts[-1]
        if leaf in layer._parameters:
            return layer._parameters[leaf]
        return layer._buffers[leaf]

    # ------------------------------------------------------------- dtype
    def to(self, dtype=None):
        if dtype is not None:
            d = convert_dtype(dtype)
            for name, p in list(self.named_parameters()):
                if jnp.issubdtype(p.dtype, np.floating):
                    self._set_by_path(name, p.astype(d))
        return self

    astype = to

    def float(self):
        return self.to("float32")

    def bfloat16(self):
        return self.to("bfloat16")

    # ------------------------------------------------------------- call
    def forward(self, *args, **kwargs):
        raise NotImplementedError

    def __call__(self, *args, **kwargs):
        for hook in self._forward_pre_hooks.values():
            res = hook(self, args)
            if res is not None:
                args = res if isinstance(res, tuple) else (res,)
        out = self.forward(*args, **kwargs)
        for hook in self._forward_post_hooks.values():
            res = hook(self, args, out)
            if res is not None:
                out = res
        return out

    def extra_repr(self) -> str:
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = "\n  ".join(sub_repr)
            lines.append(f"({name}): {sub_repr}")
        body = ""
        if extra or lines:
            body = "\n  " + "\n  ".join(([extra] if extra else []) + lines) + "\n"
        return f"{type(self).__name__}({body})"


class HookRemoveHelper:
    def __init__(self, hooks: Dict[int, Callable], hook_id: int):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


# -------------------------------------------------------- functional bridge
def param_state(layer: Layer) -> Dict[str, Any]:
    """Trainable parameter pytree (flat path->array dict)."""
    return dict(layer.named_parameters())


def buffer_state(layer: Layer) -> Dict[str, Any]:
    """Mutable non-trainable state pytree (BN stats, counters, ...)."""
    return dict(layer.named_buffers())


def functional_call(
    layer: Layer,
    params: Dict[str, Any],
    buffers: Optional[Dict[str, Any]],
    *args,
    rngs: Optional[Dict[str, Any]] = None,
    **kwargs,
):
    """Run ``layer`` with explicit state; returns ``(out, new_buffers)``.

    This is the jit/grad entry point: ``params``/``buffers`` may be tracers.
    The layer's stored arrays are swapped in-place for the duration of the
    call and restored afterwards (single-threaded trace-time mutation, same
    trick as flax.nnx's merge/split).
    """
    saved = {}
    for name in list(params) + list(buffers or {}):
        saved[name] = layer._get_by_path(name)
    try:
        for name, v in params.items():
            layer._set_by_path(name, v)
        for name, v in (buffers or {}).items():
            layer._set_by_path(name, v)
        # rngs=None inherits any ambient rng context (nested functional calls)
        ctx = rng_context(rngs) if rngs is not None else contextlib.nullcontext()
        with ctx:
            out = layer(*args, **kwargs)
        new_buffers = {name: layer._get_by_path(name) for name in (buffers or {})}
    finally:
        for name, v in saved.items():
            layer._set_by_path(name, v)
    return out, new_buffers
