"""Seq2seq decoding: BeamSearchDecoder + dynamic_decode.

Reference parity: ``python/paddle/nn/decode.py`` (``BeamSearchDecoder``
over an RNN cell, ``dynamic_decode`` driving it to max length / all-beams
finished).

TPU-native: each step is dense [B, beam, ...] math (top-k over
beam*vocab); the driver loop is a Python loop over ``max_step_num`` with
a finished mask — decoding is inference-side and eager here (compile the
per-step cell with ``to_static`` if needed). ``gather_tree`` backtraces
the surviving beams' ancestry at the end, same as the reference op.
"""
from __future__ import annotations

from typing import Callable, Optional

import jax
import jax.numpy as jnp

from . import functional as F
from .layer import Layer

__all__ = ["BeamSearchDecoder", "dynamic_decode"]


class BeamSearchDecoder:
    """Beam search over a cell: ``cell(inputs [B*beam, emb], states)``
    -> (logits-or-hidden, new_states); an output layer maps cell output to
    vocab logits when the cell itself does not."""

    def __init__(self, cell, start_token: int, end_token: int,
                 beam_size: int, embedding_fn: Optional[Callable] = None,
                 output_fn: Optional[Callable] = None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn or (lambda ids: ids)
        self.output_fn = output_fn or (lambda x: x)

    # states are pytrees with leading dim B*beam
    def initialize(self, initial_states, batch_size: int):
        K = self.beam_size
        tok = jnp.full((batch_size, K), self.start_token, jnp.int32)
        # only beam 0 is live initially (the reference's -inf trick keeps
        # duplicate start beams from all surviving the first top-k)
        log_probs = jnp.tile(
            jnp.asarray([[0.0] + [-1e9] * (K - 1)], jnp.float32),
            (batch_size, 1))
        finished = jnp.zeros((batch_size, K), bool)
        states = jax.tree.map(
            lambda s: jnp.repeat(jnp.asarray(s), K, axis=0), initial_states)
        return tok, log_probs, finished, states

    def step(self, tok, log_probs, finished, states):
        B, K = tok.shape
        emb = self.embedding_fn(tok.reshape(B * K))
        out, new_states = self.cell(emb, states)
        logits = self.output_fn(out)
        V = logits.shape[-1]
        step_lp = jax.nn.log_softmax(
            jnp.asarray(logits, jnp.float32), -1).reshape(B, K, V)
        # finished beams only extend with end_token at zero cost
        fin_mask = jnp.full((V,), -1e9).at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], fin_mask[None, None, :],
                            step_lp)
        total = log_probs[..., None] + step_lp           # [B, K, V]
        top_lp, top_idx = jax.lax.top_k(total.reshape(B, K * V), K)
        parent = top_idx // V                            # [B, K]
        token = (top_idx % V).astype(jnp.int32)
        bidx = jnp.arange(B)[:, None]
        new_finished = finished[bidx, parent] | (token == self.end_token)
        # reorder states along the beam dim to follow surviving parents
        flat_parent = (bidx * K + parent).reshape(-1)
        new_states = jax.tree.map(lambda s: jnp.asarray(s)[flat_parent],
                                  new_states)
        return token, top_lp, new_finished, new_states, parent


def dynamic_decode(decoder: BeamSearchDecoder, inits=None,
                   max_step_num: int = 100, batch_size: Optional[int] = None,
                   **kwargs):
    """Drive ``decoder`` until all beams finish or ``max_step_num``.
    Returns ``(sequences [B, beam, T], final_log_probs [B, beam])`` with
    beam ancestry resolved via ``gather_tree``."""
    if batch_size is None:
        leaf = jax.tree.leaves(inits)[0]
        batch_size = leaf.shape[0]
    tok, log_probs, finished, states = decoder.initialize(inits, batch_size)
    tokens, parents = [], []
    for _ in range(max_step_num):
        tok, log_probs, finished, states, parent = decoder.step(
            tok, log_probs, finished, states)
        tokens.append(tok)
        parents.append(parent)
        if bool(jnp.all(finished)):
            break
    ids = jnp.stack(tokens)                  # [T, B, K]
    par = jnp.stack(parents)
    seqs = F.gather_tree(ids, par)           # [T, B, K]
    return jnp.transpose(seqs, (1, 2, 0)), log_probs
