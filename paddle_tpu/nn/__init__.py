"""paddle_tpu.nn — layer library (reference: ``python/paddle/nn/``)."""
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layer import (  # noqa: F401
    Layer,
    Parameter,
    buffer_state,
    functional_call,
    param_state,
    rng_context,
    take_rng_key,
)
from .layers.activation import (  # noqa: F401
    CELU, ELU, GELU, SELU, Hardshrink, Hardsigmoid, Hardswish, Hardtanh,
    LeakyReLU, LogSigmoid, LogSoftmax, Maxout, Mish, PReLU, ReLU, ReLU6,
    RReLU, Sigmoid, Silu, Softmax, Softmax2D, Softplus, Softshrink,
    Softsign, Swish, Tanh, Tanhshrink, ThresholdedReLU,
)
from .layers.common import (  # noqa: F401
    AlphaDropout, Bilinear, ChannelShuffle, CosineSimilarity, Dropout,
    Dropout2D, Dropout3D, Embedding, Flatten, Fold, Identity, Linear, Pad1D,
    Pad2D, Pad3D, PairwiseDistance, PixelShuffle, PixelUnshuffle, Unfold,
    Upsample, UpsamplingBilinear2D, UpsamplingNearest2D, ZeroPad2D,
)
from .layers.containers import LayerDict, LayerList, ParameterList, Sequential  # noqa: F401
from .layers.conv import (  # noqa: F401
    Conv1D, Conv1DTranspose, Conv2D, Conv2DTranspose, Conv3D, Conv3DTranspose,
)
from .layers.loss import (  # noqa: F401
    BCELoss, BCEWithLogitsLoss, CosineEmbeddingLoss, CrossEntropyLoss,
    CTCLoss, HingeEmbeddingLoss, HSigmoidLoss, KLDivLoss, L1Loss,
    MarginRankingLoss, MSELoss, MultiLabelSoftMarginLoss, MultiMarginLoss,
    NLLLoss, SmoothL1Loss, SoftMarginLoss, TripletMarginLoss,
    TripletMarginWithDistanceLoss,
)
from .layers.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, GroupNorm, InstanceNorm1D,
    InstanceNorm2D, InstanceNorm3D, LayerNorm, LocalResponseNorm, RMSNorm,
    SpectralNorm, SyncBatchNorm,
)
from .layers.pooling import (  # noqa: F401
    AdaptiveAvgPool1D, AdaptiveAvgPool2D, AdaptiveAvgPool3D,
    AdaptiveMaxPool1D, AdaptiveMaxPool2D, AdaptiveMaxPool3D, AvgPool1D,
    AvgPool2D, AvgPool3D, MaxPool1D, MaxPool2D, MaxPool3D, MaxUnPool1D,
    MaxUnPool2D, MaxUnPool3D,
)
from .layers.rnn import (  # noqa: F401
    GRU, LSTM, RNN, BiRNN, GRUCell, LSTMCell, RNNCellBase, SimpleRNN,
    SimpleRNNCell,
)
from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
from .decode import BeamSearchDecoder, dynamic_decode  # noqa: F401
from .layers.transformer import (  # noqa: F401
    MultiHeadAttention, Transformer, TransformerDecoder, TransformerDecoderLayer,
    TransformerEncoder, TransformerEncoderLayer,
)
from . import utils  # noqa: F401  (weight/spectral norm, param transforms)
