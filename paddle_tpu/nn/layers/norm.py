"""Normalization layers (reference: ``python/paddle/nn/layer/norm.py``).

BatchNorm running stats live in registered buffers and are updated
functionally — ``functional_call`` captures the new values, so the jitted
train step carries them as explicit state (no in-place CUDA mutation as in
the reference's ``batch_norm`` kernel).
"""
from __future__ import annotations

import jax.numpy as jnp

from .. import functional as F
from ..initializer import Constant
from ..layer import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self.num_features = num_features
        self.momentum = momentum
        self.epsilon = epsilon
        self.data_format = data_format
        self.use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
        self.register_buffer("_mean", jnp.zeros((num_features,), jnp.float32))
        self.register_buffer("_variance", jnp.ones((num_features,), jnp.float32))

    def forward(self, x):
        out, new_mean, new_var = F.batch_norm(
            x, self._mean, self._variance, self.weight, self.bias,
            training=self.training, momentum=self.momentum, epsilon=self.epsilon,
            data_format=self.data_format, use_global_stats=self.use_global_stats)
        if self.training and not self.use_global_stats:
            self._mean = new_mean
            self._variance = new_var
        return out

    def extra_repr(self):
        return f"num_features={self.num_features}, momentum={self.momentum}, epsilon={self.epsilon}"


class BatchNorm(_BatchNormBase):
    """Legacy ``paddle.nn.BatchNorm`` (act fused)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, data_layout="NCHW", use_global_stats=None):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         data_format, use_global_stats, name)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica batch norm. Inside ``shard_map``/``pmap`` the mean/var
    reduce over the mesh 'data' axis (reference: ``sync_batch_norm_op.cu``
    NCCL allreduce of per-GPU stats); under plain pjit, GSPMD already
    computes global stats because the batch axis is just sharded.
    """

    def __init__(self, *args, axis_name=None, **kwargs):
        super().__init__(*args, **kwargs)
        self._axis_name = axis_name

    def forward(self, x):
        import jax

        if self._axis_name is None:
            return super().forward(x)
        ch_axis = 1 if self.data_format.startswith("NC") else x.ndim - 1
        reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
        mean = jnp.mean(x, axis=reduce_axes)
        meansq = jnp.mean(jnp.square(x), axis=reduce_axes)
        mean = jax.lax.pmean(mean, self._axis_name)
        meansq = jax.lax.pmean(meansq, self._axis_name)
        var = meansq - jnp.square(mean)
        shape = [1] * x.ndim
        shape[ch_axis] = -1
        out = (x - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + self.epsilon)
        if self.weight is not None:
            out = out * self.weight.reshape(shape)
        if self.bias is not None:
            out = out + self.bias.reshape(shape)
        if self.training:
            n = x.size // x.shape[ch_axis]
            unbiased = var * n / max(n - 1, 1)
            self._mean = self.momentum * self._mean + (1 - self.momentum) * mean
            self._variance = self.momentum * self._variance + (1 - self.momentum) * unbiased
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        """Recursively convert BatchNorm* sublayers to SyncBatchNorm."""
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = cls(layer.num_features, layer.momentum, layer.epsilon,
                      data_format=layer.data_format)
            new.set_state_dict(layer.state_dict())
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self.normalized_shape = tuple(normalized_shape)
        self.epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self.normalized_shape, attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self.normalized_shape, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self.normalized_shape, self.weight, self.bias, self.epsilon)

    def extra_repr(self):
        return f"normalized_shape={self.normalized_shape}, epsilon={self.epsilon}"


class RMSNorm(Layer):
    """Llama-family norm; absent in the reference (see SURVEY §2.3 note on
    missing modern blocks) but required by BASELINE.md's Llama-2 target."""

    def __init__(self, hidden_size, epsilon=1e-6):
        super().__init__()
        self.epsilon = epsilon
        self.weight = self.create_parameter((hidden_size,), default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self.epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self.num_groups = num_groups
        self.num_channels = num_channels
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                (num_channels,), attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter((num_channels,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self.num_groups, self.weight, self.bias,
                            self.epsilon, self.data_format)


class InstanceNorm1D(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCL", name=None):
        super().__init__()
        self.num_features = num_features
        self.epsilon = epsilon
        self.data_format = data_format
        if weight_attr is False:
            self.weight = None
            self.bias = None
        else:
            self.weight = self.create_parameter(
                (num_features,), attr=weight_attr, default_initializer=Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self.epsilon, data_format=self.data_format)


class InstanceNorm2D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class InstanceNorm3D(InstanceNorm1D):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", name=None):
        super().__init__(num_features, epsilon, momentum, weight_attr, bias_attr, data_format)


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.size = size
        self.alpha = alpha
        self.beta = beta
        self.k = k
        self.data_format = data_format

    def forward(self, x):
        return F.local_response_norm(x, self.size, self.alpha, self.beta, self.k, self.data_format)


class SpectralNorm(Layer):
    """Power-iteration spectral norm (reference: ``spectral_norm_op``)."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        self.dim = dim
        self.power_iters = power_iters
        self.eps = eps
        import numpy as np

        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..initializer import Normal

        self.weight_u = self.create_parameter((h,), default_initializer=Normal(0.0, 1.0))
        self.weight_v = self.create_parameter((w,), default_initializer=Normal(0.0, 1.0))

    def forward(self, weight):
        w = jnp.moveaxis(jnp.asarray(weight), self.dim, 0)
        mat = w.reshape(w.shape[0], -1)
        u, v = self.weight_u, self.weight_v
        for _ in range(self.power_iters):
            v = mat.T @ u
            v = v / (jnp.linalg.norm(v) + self.eps)
            u = mat @ v
            u = u / (jnp.linalg.norm(u) + self.eps)
        sigma = u @ mat @ v
        return jnp.moveaxis((mat / sigma).reshape(w.shape), 0, self.dim)
