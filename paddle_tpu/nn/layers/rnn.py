"""Recurrent layers (reference: ``python/paddle/nn/layer/rnn.py``).

TPU-native: the time loop is a single ``lax.scan`` — one compiled kernel per
layer/direction instead of the reference's per-step cuDNN calls. Input layout
[batch, time, size] when ``time_major=False`` (paddle default).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..initializer import Uniform
from ..layer import Layer


class RNNCellBase(Layer):
    def get_initial_states(self, batch_size, hidden_size, dtype=jnp.float32):
        return jnp.zeros((batch_size, hidden_size), dtype)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((hidden_size, input_size), default_initializer=init)
        self.weight_hh = self.create_parameter((hidden_size, hidden_size), default_initializer=init)
        self.bias_ih = self.create_parameter((hidden_size,), is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((hidden_size,), is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0], self.hidden_size, inputs.dtype)
        pre = inputs @ self.weight_ih.T + self.bias_ih + states @ self.weight_hh.T + self.bias_hh
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        h = act(pre)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((4 * hidden_size, input_size), default_initializer=init)
        self.weight_hh = self.create_parameter((4 * hidden_size, hidden_size), default_initializer=init)
        self.bias_ih = self.create_parameter((4 * hidden_size,), is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((4 * hidden_size,), is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            z = self.get_initial_states(inputs.shape[0], self.hidden_size, inputs.dtype)
            states = (z, z)
        h, c = states
        gates = inputs @ self.weight_ih.T + self.bias_ih + h @ self.weight_hh.T + self.bias_hh
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
        g = jnp.tanh(g)
        c_new = f * c + i * g
        h_new = o * jnp.tanh(c_new)
        return h_new, (h_new, c_new)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter((3 * hidden_size, input_size), default_initializer=init)
        self.weight_hh = self.create_parameter((3 * hidden_size, hidden_size), default_initializer=init)
        self.bias_ih = self.create_parameter((3 * hidden_size,), is_bias=True, default_initializer=init)
        self.bias_hh = self.create_parameter((3 * hidden_size,), is_bias=True, default_initializer=init)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs.shape[0], self.hidden_size, inputs.dtype)
        h = states
        gi = inputs @ self.weight_ih.T + self.bias_ih
        gh = h @ self.weight_hh.T + self.bias_hh
        ir, iz, ic = jnp.split(gi, 3, axis=-1)
        hr, hz, hc = jnp.split(gh, 3, axis=-1)
        r = jax.nn.sigmoid(ir + hr)
        z = jax.nn.sigmoid(iz + hz)
        c = jnp.tanh(ic + r * hc)
        h_new = (1.0 - z) * c + z * h
        return h_new, h_new


class RNN(Layer):
    """Wraps a cell into a scanned sequence layer."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = jnp.asarray(inputs)
        if not self.time_major:
            x = jnp.swapaxes(x, 0, 1)  # -> [T, B, C]
        if self.is_reverse:
            x = jnp.flip(x, axis=0)
        if initial_states is None:
            if isinstance(self.cell, LSTMCell):
                z = jnp.zeros((x.shape[1], self.cell.hidden_size), x.dtype)
                initial_states = (z, z)
            else:
                initial_states = jnp.zeros((x.shape[1], self.cell.hidden_size), x.dtype)

        cell = self.cell

        def step(state, xt):
            out, new_state = cell(xt, state)
            return new_state, out

        final_state, outputs = jax.lax.scan(step, initial_states, x)
        if self.is_reverse:
            outputs = jnp.flip(outputs, axis=0)
        if not self.time_major:
            outputs = jnp.swapaxes(outputs, 0, 1)
        return outputs, final_state


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        states_fw, states_bw = (None, None) if initial_states is None else initial_states
        out_fw, st_fw = self.rnn_fw(inputs, states_fw)
        out_bw, st_bw = self.rnn_bw(inputs, states_bw)
        return jnp.concatenate([out_fw, out_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    def __init__(self, mode, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__()
        self.mode = mode
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        bidirect = direction in ("bidirect", "bidirectional")
        self.num_directions = 2 if bidirect else 1
        cell_cls = {"LSTM": LSTMCell, "GRU": GRUCell, "RNN_TANH": SimpleRNNCell,
                    "RNN_RELU": SimpleRNNCell}[mode]

        from .containers import LayerList

        self.rnns = LayerList()
        for layer_i in range(num_layers):
            in_size = input_size if layer_i == 0 else hidden_size * self.num_directions
            extra = {"activation": "relu"} if mode == "RNN_RELU" else {}
            if bidirect:
                self.rnns.append(BiRNN(cell_cls(in_size, hidden_size, **extra),
                                       cell_cls(in_size, hidden_size, **extra), time_major))
            else:
                self.rnns.append(RNN(cell_cls(in_size, hidden_size, **extra),
                                     time_major=time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        out = inputs
        final_states = []
        for i, rnn in enumerate(self.rnns):
            st = None if initial_states is None else jax.tree.map(
                lambda t: t[i], initial_states)
            out, fs = rnn(out, st)
            final_states.append(fs)
            if self.dropout > 0 and i < self.num_layers - 1 and self.training:
                from .. import functional as F

                out = F.dropout(out, self.dropout, training=True)
        stacked = jax.tree.map(lambda *ts: jnp.stack(ts), *final_states)
        return out, stacked


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, activation="tanh", **kwargs):
        mode = "RNN_TANH" if activation == "tanh" else "RNN_RELU"
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("LSTM", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, **kwargs):
        super().__init__("GRU", input_size, hidden_size, num_layers, direction,
                         time_major, dropout)
