"""Gradient clipping (reference: ``python/paddle/fluid/clip.py`` —
ClipGradByValue / ClipGradByNorm / ClipGradByGlobalNorm).

Each clip is a pure pytree->pytree function; the hybrid-parallel variant that
sums norm contributions across mesh axes lives in
``paddle_tpu.distributed.parallel.hybrid_optimizer``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


class ClipGradBase:
    def __call__(self, grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):  # noqa: A002
        self.max = max
        self.min = -max if min is None else min

    def __call__(self, grads):
        return jax.tree.map(
            lambda g: None if g is None else jnp.clip(g, self.min, self.max), grads,
            is_leaf=lambda x: x is None)


class ClipGradByNorm(ClipGradBase):
    """Per-tensor norm clip."""

    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        def clip_one(g):
            if g is None:
                return None
            norm = jnp.sqrt(jnp.sum(jnp.square(g.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            return (g.astype(jnp.float32) * scale).astype(g.dtype)

        return jax.tree.map(clip_one, grads, is_leaf=lambda x: x is None)


class ClipGradByGlobalNorm(ClipGradBase):
    """Global-norm clip across the whole gradient pytree."""

    def __init__(self, clip_norm, group_name="default_group"):
        self.clip_norm = clip_norm

    def __call__(self, grads):
        leaves = [g for g in jax.tree.leaves(grads) if g is not None]
        if not leaves:
            return grads
        gnorm_sq = sum(jnp.sum(jnp.square(g.astype(jnp.float32))) for g in leaves)
        gnorm = jnp.sqrt(gnorm_sq)
        scale = self.clip_norm / jnp.maximum(gnorm, self.clip_norm)
        return jax.tree.map(
            lambda g: None if g is None else (g.astype(jnp.float32) * scale).astype(g.dtype),
            grads, is_leaf=lambda x: x is None)


def clip_grad_norm_(grads, max_norm, norm_type=2.0):
    """Functional torch-style helper; returns (clipped, total_norm)."""
    leaves = [g for g in jax.tree.leaves(grads) if g is not None]
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g)) for g in leaves]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g.astype(jnp.float32)) ** norm_type) for g in leaves])) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-12), 1.0)
    clipped = jax.tree.map(lambda g: None if g is None else (g * scale).astype(g.dtype),
                           grads, is_leaf=lambda x: x is None)
    return clipped, total
