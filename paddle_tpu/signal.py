"""paddle_tpu.signal — frame/STFT/ISTFT.

Reference parity: ``python/paddle/signal.py`` (``frame``, ``overlap_add``,
``stft``, ``istft``). TPU-native: framing is a gather (static shapes), the
transform is jnp.fft — all jittable; no cuFFT plans to manage.
"""
from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length: int, hop_length: int, axis: int = -1, name=None):
    """Slice ``x`` into overlapping frames along ``axis``; output has
    ``frame_length`` then frame-count dims in place of ``axis`` (matching
    the reference layout: [..., frame_length, num_frames] for axis=-1)."""
    x = jnp.asarray(x)
    if axis not in (-1, x.ndim - 1, 0):
        raise ValueError("frame: axis must be first or last")
    last = axis in (-1, x.ndim - 1)
    n = x.shape[-1] if last else x.shape[0]
    if frame_length > n:
        raise ValueError(f"frame_length {frame_length} > signal length {n}")
    num_frames = 1 + (n - frame_length) // hop_length
    starts = jnp.arange(num_frames) * hop_length
    idx = starts[:, None] + jnp.arange(frame_length)[None, :]  # [F, L]
    if last:
        frames = x[..., idx]                  # [..., F, L]
        return jnp.swapaxes(frames, -1, -2)   # [..., L, F]
    frames = x[idx]                            # [F, L, ...]
    return jnp.moveaxis(frames, 1, 0)          # [L, F, ...]


def overlap_add(x, hop_length: int, axis: int = -1, name=None):
    """Inverse of :func:`frame` (sum overlapping frames).

    ``x``: [..., frame_length, num_frames] (axis=-1) or
    [frame_length, num_frames, ...] (axis=0).
    """
    x = jnp.asarray(x)
    if axis not in (-1, x.ndim - 1, 0):
        raise ValueError("overlap_add: axis must be first or last")
    last = axis in (-1, x.ndim - 1)
    if not last:
        # normalize to [..., L, F]
        x = jnp.moveaxis(x, (0, 1), (-2, -1))
    L, F = x.shape[-2], x.shape[-1]
    out_len = (F - 1) * hop_length + L
    out = jnp.zeros(x.shape[:-2] + (out_len,), x.dtype)
    idx = (jnp.arange(F)[:, None] * hop_length
           + jnp.arange(L)[None, :]).reshape(-1)          # [F*L]
    vals = jnp.swapaxes(x, -1, -2).reshape(x.shape[:-2] + (F * L,))
    out = out.at[..., idx].add(vals)
    if not last:
        out = jnp.moveaxis(out, -1, 0)
    return out


def stft(x, n_fft: int, hop_length: Optional[int] = None,
         win_length: Optional[int] = None, window=None, center: bool = True,
         pad_mode: str = "reflect", normalized: bool = False,
         onesided: bool = True, name=None):
    """Short-time Fourier transform; returns [..., freq, num_frames]
    complex (reference ``paddle.signal.stft``)."""
    x = jnp.asarray(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones(win_length, x.dtype)
    window = jnp.asarray(window)
    if win_length < n_fft:  # center-pad window to n_fft
        pad = (n_fft - win_length) // 2
        window = jnp.pad(window, (pad, n_fft - win_length - pad))
    if center:
        pad = n_fft // 2
        x = jnp.pad(x, [(0, 0)] * (x.ndim - 1) + [(pad, pad)],
                    mode=pad_mode)
    frames = frame(x, n_fft, hop_length, axis=-1)      # [..., n_fft, F]
    frames = frames * window[:, None]
    if onesided:
        spec = jnp.fft.rfft(frames, axis=-2)
    else:
        spec = jnp.fft.fft(frames, axis=-2)
    if normalized:
        spec = spec / jnp.sqrt(jnp.asarray(n_fft, spec.real.dtype))
    return spec


def istft(x, n_fft: int, hop_length: Optional[int] = None,
          win_length: Optional[int] = None, window=None, center: bool = True,
          normalized: bool = False, onesided: bool = True,
          length: Optional[int] = None, return_complex: bool = False,
          name=None):
    """Inverse STFT with window-envelope normalization (reference
    ``paddle.signal.istft``)."""
    x = jnp.asarray(x)
    hop_length = hop_length or n_fft // 4
    win_length = win_length or n_fft
    if window is None:
        window = jnp.ones(win_length)
    window = jnp.asarray(window)
    if win_length < n_fft:
        pad = (n_fft - win_length) // 2
        window = jnp.pad(window, (pad, n_fft - win_length - pad))
    if normalized:
        x = x * jnp.sqrt(jnp.asarray(n_fft, jnp.float32))
    if onesided:
        frames = jnp.fft.irfft(x, n=n_fft, axis=-2)    # [..., n_fft, F]
    else:
        frames = jnp.fft.ifft(x, axis=-2)
        frames = frames.real if not return_complex else frames
    frames = frames * window[:, None]
    sig = overlap_add(frames, hop_length, axis=-1)
    # window envelope for COLA normalization
    env_frames = jnp.broadcast_to((window ** 2)[:, None],
                                  (n_fft, x.shape[-1]))
    env = overlap_add(env_frames, hop_length, axis=-1)
    sig = sig / jnp.maximum(env, 1e-11)
    if center:
        pad = n_fft // 2
        sig = sig[..., pad:sig.shape[-1] - pad]
    if length is not None:
        sig = sig[..., :length]
    return sig
