"""Viterbi decoding for sequence tagging.

Reference parity: ``python/paddle/text/viterbi_decode.py`` (the
``viterbi_decode`` C++ op + ``ViterbiDecoder`` layer). TPU-native: the
forward max-product recursion and the backtrace are both ``lax.scan``s, so
the whole decode jit-compiles (batch-parallel, no host loop); variable
lengths are handled by masking, matching the kernel's semantics: positions
beyond a sequence's length freeze the recursion and pad the path with 0.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..nn.layer import Layer

__all__ = ["viterbi_decode", "ViterbiDecoder"]


def viterbi_decode(potentials, transition_params, lengths,
                   include_bos_eos_tag: bool = True, name=None):
    """Highest-scoring tag path per sequence.

    Args: potentials [B, T, N] unary scores; transition_params [N, N];
    lengths [B] int. With ``include_bos_eos_tag`` the last row/column of
    the transition matrix acts as the BOS tag and the second-to-last as
    EOS (reference kernel semantics).

    Returns ``(scores [B], paths [B, max(lengths)] int64-compatible)``.
    """
    pot = jnp.asarray(potentials)
    trans = jnp.asarray(transition_params)
    lengths = jnp.asarray(lengths).astype(jnp.int32)
    B, T, N = pot.shape

    alpha = pot[:, 0]
    if include_bos_eos_tag:
        alpha = alpha + trans[-1][None, :]

    def fwd(carry, xt):
        alpha, t = carry
        scores = alpha[:, :, None] + trans[None, :, :]  # [B, from, to]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)
        new_alpha = jnp.max(scores, axis=1) + xt
        live = (t < lengths)[:, None]
        return (jnp.where(live, new_alpha, alpha), t + 1), best_prev

    (alpha, _), history = lax.scan(
        fwd, (alpha, jnp.int32(1)), jnp.swapaxes(pot[:, 1:], 0, 1))
    # history[t-1]: best previous tag for each current tag at position t

    if include_bos_eos_tag:
        alpha = alpha + trans[:, -2][None, :]
    scores = jnp.max(alpha, axis=-1)
    last_tag = jnp.argmax(alpha, axis=-1).astype(jnp.int32)

    def bwd(carry, inp):
        tag, = carry
        best_prev_t, t = inp  # position t in [T-1 .. 1]
        emit = jnp.where(t <= lengths - 1, tag, 0)
        prev = jnp.take_along_axis(best_prev_t, tag[:, None], 1)[:, 0]
        tag = jnp.where(t <= lengths - 1, prev, tag)
        return (tag,), emit

    ts = jnp.arange(T - 1, 0, -1, dtype=jnp.int32)
    (tag0,), emitted = lax.scan(
        bwd, (last_tag,), (history[::-1], ts))
    paths = jnp.concatenate([tag0[:, None],
                             jnp.swapaxes(emitted, 0, 1)[:, ::-1]], axis=1)
    if not isinstance(lengths, jax.core.Tracer):
        paths = paths[:, :int(jnp.max(lengths))]
    return scores, paths


class ViterbiDecoder(Layer):
    """Layer wrapper holding the transition matrix (reference
    ``ViterbiDecoder``)."""

    def __init__(self, transitions, include_bos_eos_tag: bool = True,
                 name=None):
        super().__init__()
        self.transitions = jnp.asarray(transitions)
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
