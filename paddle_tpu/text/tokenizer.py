"""In-graph(-pipeline) BERT tokenizer: the faster_tokenizer analogue.

Reference parity: ``paddle/fluid/operators/string/faster_tokenizer_op.cc``
(+ ``faster_tokenizer_op.h``): a graph op holding the vocab as a VOCAB
tensor, running basic+wordpiece tokenization inside the serving program so
a saved model consumes RAW STRINGS and emits ``(input_ids,
token_type_ids)``.

TPU-native: strings cannot enter XLA, so "in-graph" becomes "in-pipeline":
:class:`FasterTokenizer` is a Layer whose forward runs on host (numpy) and
returns device-ready int32 batches. For serving parity a text Predictor
composes it in front of a compiled program — the same single-artifact
serve-raw-text contract, with the string stage pinned to host exactly
where the reference pins its op (CPU-only kernel).
"""
# tpu-lint: disable-file=R2(host-side string tokenizer by contract — forward consumes python strings/lists, never traced arrays; the analyzer reaches it only through the functional_call->every-forward over-approximation)
from __future__ import annotations

import unicodedata
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..nn.layer import Layer

__all__ = ["FasterTokenizer", "load_vocab"]


def load_vocab(path: str) -> Dict[str, int]:
    """vocab.txt (one token per line, id = line number) -> dict."""
    vocab: Dict[str, int] = {}
    with open(path, encoding="utf-8") as f:
        for i, line in enumerate(f):
            vocab[line.rstrip("\n")] = i
    return vocab


def _is_punct(ch: str) -> bool:
    cp = ord(ch)
    if (33 <= cp <= 47 or 58 <= cp <= 64 or 91 <= cp <= 96
            or 123 <= cp <= 126):
        return True
    return unicodedata.category(ch).startswith("P")


def _is_cjk(ch: str) -> bool:
    """CJK ideographs get split into single-char words (reference
    BasicTokenizer::tokenize_chinese_chars — the op's primary use case is
    Chinese BERT/ERNIE)."""
    cp = ord(ch)
    return (0x4E00 <= cp <= 0x9FFF or 0x3400 <= cp <= 0x4DBF
            or 0x20000 <= cp <= 0x2A6DF or 0x2A700 <= cp <= 0x2CEAF
            or 0xF900 <= cp <= 0xFAFF or 0x2F800 <= cp <= 0x2FA1F)


def _basic_tokenize(text: str, do_lower_case: bool) -> List[str]:
    """BERT BasicTokenizer: clean, lowercase+strip accents, split on
    whitespace and punctuation (reference ``BertTokenizer::BasicTokenizer``
    in faster_tokenizer_op.h)."""
    if do_lower_case:
        text = text.lower()
        text = unicodedata.normalize("NFD", text)
        text = "".join(c for c in text if unicodedata.category(c) != "Mn")
    out: List[str] = []
    cur = []
    for ch in text:
        if ch.isspace():
            if cur:
                out.append("".join(cur))
                cur = []
        elif _is_punct(ch) or _is_cjk(ch):
            if cur:
                out.append("".join(cur))
                cur = []
            out.append(ch)
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur))
    return out


def _wordpiece(token: str, vocab: Dict[str, int], unk: str,
               max_chars: int = 100) -> List[str]:
    """Greedy longest-match-first wordpiece (reference
    ``WordPieceTokenizer::Tokenize``)."""
    if len(token) > max_chars:
        return [unk]
    pieces: List[str] = []
    start = 0
    while start < len(token):
        end = len(token)
        piece = None
        while start < end:
            sub = token[start:end]
            if start > 0:
                sub = "##" + sub
            if sub in vocab:
                piece = sub
                break
            end -= 1
        if piece is None:
            return [unk]
        pieces.append(piece)
        start = end
    return pieces


class FasterTokenizer(Layer):
    """BERT tokenizer layer (reference ``FasterTokenizer`` python wrapper in
    ``test_faster_tokenizer_op.py:69`` over ``faster_tokenizer_op.cc``).

    ``forward(text, text_pair=None, ...)`` -> ``(input_ids,
    token_type_ids)`` int32 arrays, one row per input string, padded to the
    longest sequence in the batch (or ``max_seq_len`` when
    ``pad_to_max_seq_len``).
    """

    def __init__(self, vocab: Dict[str, int], cls_token: str = "[CLS]",
                 sep_token: str = "[SEP]", pad_token: str = "[PAD]",
                 unk_token: str = "[UNK]"):
        super().__init__()
        self.vocab = dict(vocab)
        self.cls_token, self.sep_token = cls_token, sep_token
        self.pad_token, self.unk_token = pad_token, unk_token

    def _encode_one(self, text: str, do_lower_case: bool,
                    is_split_into_words: bool) -> List[int]:
        words = ([text] if is_split_into_words
                 else _basic_tokenize(text, do_lower_case))
        ids: List[int] = []
        for w in words:
            for piece in _wordpiece(w, self.vocab, self.unk_token):
                ids.append(self.vocab.get(piece,
                                          self.vocab.get(self.unk_token, 0)))
        return ids

    def forward(self, text: Sequence[str],
                text_pair: Optional[Sequence[str]] = None,
                do_lower_case: bool = True, max_seq_len: int = -1,
                pad_to_max_seq_len: bool = False,
                is_split_into_words: bool = False
                ) -> Tuple[np.ndarray, np.ndarray]:
        if isinstance(text, str):
            text = [text]
        if isinstance(text_pair, str):
            text_pair = [text_pair]
        if text_pair is not None and len(text_pair) != len(text):
            raise ValueError("text and text_pair must align")
        cls_id = self.vocab[self.cls_token]
        sep_id = self.vocab[self.sep_token]
        pad_id = self.vocab.get(self.pad_token, 0)

        rows: List[List[int]] = []
        segs: List[List[int]] = []
        for i, t in enumerate(text):
            a = self._encode_one(t, do_lower_case, is_split_into_words)
            b = (self._encode_one(text_pair[i], do_lower_case,
                                  is_split_into_words)
                 if text_pair is not None else None)
            if max_seq_len and max_seq_len > 0:
                # reference truncation: longest-first down to the budget
                # (clamped at 0: max_seq_len smaller than the special
                # tokens leaves no room for content at all)
                budget = max(
                    max_seq_len - 2 - (1 if b is not None else 0), 0)
                if b is None:
                    a = a[:budget]
                else:
                    while len(a) + len(b) > budget and (a or b):
                        (a if len(a) >= len(b) else b).pop()
            ids = [cls_id] + a + [sep_id]
            seg = [0] * len(ids)
            if b is not None:
                ids += b + [sep_id]
                seg += [1] * (len(b) + 1)
            rows.append(ids)
            segs.append(seg)

        width = (max_seq_len if (pad_to_max_seq_len and max_seq_len > 0)
                 else max(len(r) for r in rows))
        input_ids = np.full((len(rows), width), pad_id, np.int32)
        token_type = np.zeros((len(rows), width), np.int32)
        for i, (r, s) in enumerate(zip(rows, segs)):
            # width can undercut even the special tokens (max_seq_len < 2):
            # clip rather than overflow the padded buffer
            r, s = r[:width], s[:width]
            input_ids[i, :len(r)] = r
            token_type[i, :len(s)] = s
        return input_ids, token_type
