"""Text datasets (reference ``python/paddle/text/datasets``: Imdb,
Imikolov, Movielens, Conll05, UCIHousing).

No network egress here, so each dataset parses the published archive from
a local ``data_file`` path (the same formats the reference downloads); the
error message states the expected file when missing.
"""
from __future__ import annotations

import gzip
import io
import os
import re
import tarfile
from typing import Dict, List, Optional

import numpy as np

from ...io.dataset import Dataset

__all__ = ["Imdb", "Imikolov", "UCIHousing", "Movielens", "Conll05"]


def _require(data_file: Optional[str], what: str) -> str:
    if not data_file or not os.path.exists(data_file):
        raise RuntimeError(
            f"{what} requires data_file pointing at the published archive "
            f"(automatic download is unavailable in this environment); got "
            f"{data_file!r}")
    return data_file


class Imdb(Dataset):
    """IMDB sentiment (reference ``imdb.py``): parses aclImdb tar, builds
    the frequency-sorted word dict, yields (ids, label)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 cutoff: int = 150):
        super().__init__()
        data_file = _require(data_file, "Imdb")
        pat = re.compile(rf"aclImdb/{mode}/(pos|neg)/.*\.txt$")
        self._docs: List[List[str]] = []
        self._labels: List[int] = []
        freq: Dict[str, int] = {}
        with tarfile.open(data_file) as tf:
            for member in tf.getmembers():
                m = pat.match(member.name)
                if not m:
                    continue
                text = tf.extractfile(member).read().decode(
                    "utf-8", "ignore").lower()
                words = re.sub(r"[^a-z0-9\s]", "", text).split()
                self._docs.append(words)
                self._labels.append(0 if m.group(1) == "pos" else 1)
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        # frequency-sorted dict with cutoff (reference build_dict)
        kept = sorted((w for w, c in freq.items() if c >= cutoff),
                      key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(kept)}
        self.word_idx["<unk>"] = len(self.word_idx)

    def __len__(self):
        return len(self._docs)

    def __getitem__(self, idx):
        unk = self.word_idx["<unk>"]
        ids = np.asarray([self.word_idx.get(w, unk) for w in self._docs[idx]],
                         np.int64)
        return ids, np.int64(self._labels[idx])


class Imikolov(Dataset):
    """PTB n-gram dataset (reference ``imikolov.py``)."""

    def __init__(self, data_file: Optional[str] = None, data_type: str = "NGRAM",
                 window_size: int = 5, mode: str = "train", min_word_freq: int = 50):
        super().__init__()
        data_file = _require(data_file, "Imikolov")
        name = {"train": "ptb.train.txt", "test": "ptb.valid.txt"}[mode]
        freq: Dict[str, int] = {}
        lines: List[List[str]] = []
        with tarfile.open(data_file) as tf:
            member = next(m for m in tf.getmembers()
                          if m.name.endswith(name))
            for line in tf.extractfile(member).read().decode().splitlines():
                words = line.strip().split()
                lines.append(words)
                for w in words:
                    freq[w] = freq.get(w, 0) + 1
        kept = sorted((w for w, c in freq.items()
                       if c >= min_word_freq and w != "<s>"),
                      key=lambda w: (-freq[w], w))
        self.word_idx = {w: i for i, w in enumerate(kept)}
        # boundary + unknown tokens always get ids (reference build_dict
        # appends <s>/<e>/<unk>), so sentence-start/end n-grams survive
        for tok in ("<s>", "<e>", "<unk>"):
            self.word_idx.setdefault(tok, len(self.word_idx))
        unk = self.word_idx["<unk>"]
        self._samples = []
        for words in lines:
            ids = [self.word_idx.get(w, unk)
                   for w in ["<s>"] * (window_size - 1) + words + ["<e>"]]
            if data_type == "NGRAM":
                for i in range(window_size, len(ids) + 1):
                    self._samples.append(
                        np.asarray(ids[i - window_size:i], np.int64))
            else:  # SEQ
                if ids:
                    self._samples.append(np.asarray(ids, np.int64))

    def __len__(self):
        return len(self._samples)

    def __getitem__(self, idx):
        return self._samples[idx]


class UCIHousing(Dataset):
    """Boston housing regression (reference ``uci_housing.py``): 13
    features normalized feature-wise, 506 rows, 80/20 split."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train"):
        super().__init__()
        data_file = _require(data_file, "UCIHousing")
        raw = np.fromfile(data_file, sep=" ") if not data_file.endswith(".gz") \
            else np.asarray(gzip.open(data_file).read().split(), float)
        data = raw.reshape(-1, 14)
        maxs, mins, avgs = data.max(0), data.min(0), data.mean(0)
        feats = (data[:, :13] - avgs[:13]) / (maxs[:13] - mins[:13])
        data = np.concatenate([feats, data[:, 13:]], axis=1)
        split = int(len(data) * 0.8)
        self.data = (data[:split] if mode == "train" else data[split:]
                     ).astype(np.float32)

    def __len__(self):
        return len(self.data)

    def __getitem__(self, idx):
        row = self.data[idx]
        return row[:13], row[13:]


class Movielens(Dataset):
    """MovieLens-1M rating prediction (reference ``movielens.py``)."""

    def __init__(self, data_file: Optional[str] = None, mode: str = "train",
                 test_ratio: float = 0.1, rand_seed: int = 0):
        super().__init__()
        data_file = _require(data_file, "Movielens")
        users, movies, ratings = {}, {}, []
        with tarfile.open(data_file) as tf:
            base = os.path.dirname(tf.getmembers()[0].name).split("/")[0]

            def read(name):
                return tf.extractfile(f"{base}/{name}").read().decode(
                    "ISO-8859-1").splitlines()

            for line in read("users.dat"):
                uid, gender, age, job, _ = line.split("::")
                users[int(uid)] = (0 if gender == "M" else 1, int(age),
                                  int(job))
            for line in read("movies.dat"):
                mid, title, genres = line.split("::")
                movies[int(mid)] = (title, genres.split("|"))
            rng = np.random.RandomState(rand_seed)
            for line in read("ratings.dat"):
                uid, mid, rating, _ = line.split("::")
                is_test = rng.rand() < test_ratio
                if is_test == (mode == "test"):
                    ratings.append((int(uid), int(mid), float(rating)))
        self._users, self._movies, self._ratings = users, movies, ratings

    def __len__(self):
        return len(self._ratings)

    def __getitem__(self, idx):
        uid, mid, rating = self._ratings[idx]
        gender, age, job = self._users[uid]
        return (np.int64(uid), np.int64(gender), np.int64(age),
                np.int64(job), np.int64(mid), np.float32(rating))


class Conll05(Dataset):
    """CoNLL-2005 SRL (reference ``conll05.py``): the test split is the
    only publicly distributable portion; parses the published tgz."""

    def __init__(self, data_file: Optional[str] = None,
                 word_dict_file: Optional[str] = None,
                 verb_dict_file: Optional[str] = None,
                 target_dict_file: Optional[str] = None, mode: str = "test"):
        super().__init__()
        data_file = _require(data_file, "Conll05")
        self._samples = []
        with tarfile.open(data_file) as tf:
            words_members = sorted(m.name for m in tf.getmembers()
                                   if m.name.endswith(".words.gz"))
            props_members = sorted(m.name for m in tf.getmembers()
                                   if m.name.endswith(".props.gz"))
            for wname, pname in zip(words_members, props_members):
                wtext = gzip.decompress(tf.extractfile(wname).read()).decode()
                ptext = gzip.decompress(tf.extractfile(pname).read()).decode()
                sent, props = [], []
                for wline, pline in zip(wtext.splitlines(),
                                        ptext.splitlines()):
                    wline, pline = wline.strip(), pline.strip()
                    if not wline:
                        if sent:
                            self._samples.append((sent, props))
                        sent, props = [], []
                        continue
                    sent.append(wline)
                    props.append(pline.split())
                if sent:
                    self._samples.append((sent, props))

    def __len__(self):
        return len(self._samples)

    def __getitem__(self, idx):
        return self._samples[idx]


# paddle names the SRL dataset Conll05st; keep both spellings
Conll05st = Conll05


class _WMT(Dataset):
    """WMT translation pairs from a local tab-separated file (reference
    ``wmt14.py``/``wmt16.py`` download+tokenize; this environment has no
    egress, so the published archive must be provided locally; lines:
    ``src_ids<TAB>trg_ids`` of space-separated ints, or raw
    ``src<TAB>trg`` text tokenized by whitespace against the dicts)."""

    def __init__(self, data_file=None, mode="train", src_dict_size=-1,
                 trg_dict_size=-1, lang="en"):
        super().__init__()
        data_file = _require(data_file, type(self).__name__)
        self._samples = []
        with open(data_file, encoding="utf-8") as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if len(parts) < 2:
                    continue
                src, trg = parts[0].split(), parts[1].split()

                def ids(tokens):
                    import zlib

                    try:
                        return np.asarray([int(t) for t in tokens], np.int64)
                    except ValueError:  # raw text: hash-bucket tokenize
                        # crc32, not hash(): python's hash is salted per
                        # process — ids must agree across runs/workers
                        return np.asarray(
                            [zlib.crc32(t.encode()) % 30000
                             for t in tokens], np.int64)

                self._samples.append((ids(src), ids(trg)))

    def __getitem__(self, idx):
        return self._samples[idx]

    def __len__(self):
        return len(self._samples)


class WMT14(_WMT):
    pass


class WMT16(_WMT):
    pass


__all__ += ["Conll05st", "WMT14", "WMT16"]
