"""paddle_tpu.text — text datasets + sequence decoding.

Reference parity: ``python/paddle/text`` (dataset loaders and
``viterbi_decode``/``ViterbiDecoder``).
"""
from .datasets import Conll05, Imdb, Imikolov, Movielens, UCIHousing
from .tokenizer import FasterTokenizer, load_vocab
from .viterbi_decode import ViterbiDecoder, viterbi_decode

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05",
           "viterbi_decode", "ViterbiDecoder", "FasterTokenizer",
           "load_vocab"]
