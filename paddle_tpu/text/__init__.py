"""paddle_tpu.text — text datasets + sequence decoding.

Reference parity: ``python/paddle/text`` (dataset loaders and
``viterbi_decode``/``ViterbiDecoder``).
"""
from .datasets import (Conll05, Conll05st, Imdb, Imikolov, Movielens,
                       UCIHousing, WMT14, WMT16)
from .tokenizer import FasterTokenizer, load_vocab
from .viterbi_decode import ViterbiDecoder, viterbi_decode

__all__ = ["Imdb", "Imikolov", "Movielens", "UCIHousing", "Conll05",
           "Conll05st", "WMT14", "WMT16", "viterbi_decode",
           "ViterbiDecoder", "FasterTokenizer", "load_vocab"]
