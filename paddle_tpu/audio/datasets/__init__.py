"""Audio datasets (reference ``python/paddle/audio/datasets``: ESC50, TESS).

This environment has no network egress, so datasets load from a local
``data_dir`` laid out like the published archives; the download step of the
reference is replaced by a clear error pointing at the expected layout.
"""
from __future__ import annotations

import csv
import os
from typing import List, Optional

import numpy as np

from ...io.dataset import Dataset
from ..backends.wave_backend import load as load_wav
from ..features.layers import LogMelSpectrogram, MelSpectrogram, MFCC, Spectrogram

__all__ = ["ESC50", "TESS", "AudioClassificationDataset"]

_FEATURES = {None: None, "raw": None, "spectrogram": Spectrogram,
             "melspectrogram": MelSpectrogram,
             "logmelspectrogram": LogMelSpectrogram, "mfcc": MFCC}


class AudioClassificationDataset(Dataset):
    """(wav file, label) dataset with optional on-the-fly feature extraction
    (reference ``audio/datasets/dataset.py``)."""

    def __init__(self, files: List[str], labels: List[int],
                 feat_type: Optional[str] = "raw", sample_rate: int = None,
                 duration: Optional[float] = None, **feat_kwargs):
        super().__init__()
        if feat_type not in _FEATURES:
            raise ValueError(f"feat_type must be one of {sorted(k for k in _FEATURES if k)}")
        self.files = files
        self.labels = labels
        self.sample_rate = sample_rate
        self.duration = duration
        cls = _FEATURES[feat_type]
        self._extractor = cls(**feat_kwargs) if cls else None

    def __len__(self):
        return len(self.files)

    def __getitem__(self, idx):
        wav, sr = load_wav(self.files[idx])
        if self.sample_rate is not None and sr != self.sample_rate:
            raise ValueError(
                f"{self.files[idx]}: file sample rate {sr} != requested "
                f"{self.sample_rate} (resampling is not implemented; "
                f"preprocess offline or omit sample_rate)")
        wav = wav[0]  # mono channel
        if self.duration is not None:
            n = int(self.duration * sr)
            wav = np.pad(wav[:n], (0, max(0, n - wav.shape[0])))
        if self._extractor is not None:
            return np.asarray(self._extractor(wav)), self.labels[idx]
        return wav, self.labels[idx]


class ESC50(AudioClassificationDataset):
    """ESC-50 environmental sounds (reference ``esc50.py``). Expects the
    extracted archive at ``data_dir`` (``meta/esc50.csv`` + ``audio/``)."""

    def __init__(self, mode: str = "train", split: int = 1,
                 feat_type: str = "raw", data_dir: Optional[str] = None,
                 **kwargs):
        if data_dir is None or not os.path.isdir(data_dir):
            raise RuntimeError(
                "ESC50 needs data_dir pointing at the extracted ESC-50 "
                "archive (containing meta/esc50.csv and audio/); automatic "
                "download is unavailable in this environment")
        meta = os.path.join(data_dir, "meta", "esc50.csv")
        files, labels = [], []
        with open(meta) as f:
            for row in csv.DictReader(f):
                in_fold = int(row["fold"]) == split
                if (mode == "dev") == in_fold:
                    files.append(os.path.join(data_dir, "audio",
                                              row["filename"]))
                    labels.append(int(row["target"]))
        super().__init__(files, labels, feat_type=feat_type, **kwargs)


class TESS(AudioClassificationDataset):
    """TESS emotional speech (reference ``tess.py``). Expects the extracted
    archive at ``data_dir`` (per-emotion subdirectories of wavs)."""

    _EMOTIONS = ["angry", "disgust", "fear", "happy", "neutral",
                 "ps", "sad"]

    def __init__(self, mode: str = "train", n_folds: int = 5, split: int = 1,
                 feat_type: str = "raw", data_dir: Optional[str] = None,
                 **kwargs):
        if data_dir is None or not os.path.isdir(data_dir):
            raise RuntimeError(
                "TESS needs data_dir pointing at the extracted TESS archive; "
                "automatic download is unavailable in this environment")
        files, labels = [], []
        wavs = sorted(
            os.path.join(r, f)
            for r, _, fs in os.walk(data_dir) for f in fs
            if f.lower().endswith(".wav"))
        for i, path in enumerate(wavs):
            fold = i % n_folds + 1
            if (mode == "dev") == (fold == split):
                emotion = os.path.basename(path).split("_")[-1][:-4].lower()
                if emotion in self._EMOTIONS:
                    files.append(path)
                    labels.append(self._EMOTIONS.index(emotion))
        super().__init__(files, labels, feat_type=feat_type, **kwargs)
