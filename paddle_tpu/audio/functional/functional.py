"""Audio DSP primitives.

Reference parity: ``python/paddle/audio/functional/functional.py`` (mel
scale conversions, filterbank construction, dB conversion, DCT basis).
TPU-native: pure jnp — every function is jit-able and differentiable, and
the constructed matrices (fbank, DCT) are constants XLA folds into the
surrounding matmuls.
"""
from __future__ import annotations

import math
from typing import Optional, Union

import jax.numpy as jnp

from ...framework.dtype import convert_dtype


def hz_to_mel(freq, htk: bool = False):
    """Hertz -> mel (slaney by default, HTK formula with ``htk=True``)."""
    freq = jnp.asarray(freq, jnp.float32) if not jnp.isscalar(freq) else freq
    if htk:
        return 2595.0 * jnp.log10(1.0 + jnp.asarray(freq) / 700.0)
    # slaney: linear below 1 kHz, log above
    f_min, f_sp = 0.0, 200.0 / 3
    mels = (jnp.asarray(freq) - f_min) / f_sp
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(jnp.asarray(freq) >= min_log_hz,
                     min_log_mel + jnp.log(jnp.maximum(jnp.asarray(freq), 1e-10)
                                           / min_log_hz) / logstep,
                     mels)


def mel_to_hz(mel, htk: bool = False):
    mel = jnp.asarray(mel)
    if htk:
        return 700.0 * (10.0 ** (mel / 2595.0) - 1.0)
    f_min, f_sp = 0.0, 200.0 / 3
    freqs = f_min + f_sp * mel
    min_log_hz = 1000.0
    min_log_mel = (min_log_hz - f_min) / f_sp
    logstep = math.log(6.4) / 27.0
    return jnp.where(mel >= min_log_mel,
                     min_log_hz * jnp.exp(logstep * (mel - min_log_mel)),
                     freqs)


def mel_frequencies(n_mels: int = 64, f_min: float = 0.0,
                    f_max: float = 11025.0, htk: bool = False,
                    dtype: str = "float32"):
    low = hz_to_mel(f_min, htk=htk)
    high = hz_to_mel(f_max, htk=htk)
    mels = jnp.linspace(low, high, n_mels)
    return mel_to_hz(mels, htk=htk).astype(convert_dtype(dtype))


def fft_frequencies(sr: int, n_fft: int, dtype: str = "float32"):
    return jnp.linspace(0.0, float(sr) / 2, n_fft // 2 + 1,
                        dtype=convert_dtype(dtype))


def compute_fbank_matrix(sr: int, n_fft: int, n_mels: int = 64,
                         f_min: float = 0.0, f_max: Optional[float] = None,
                         htk: bool = False, norm: Union[str, float] = "slaney",
                         dtype: str = "float32"):
    """Triangular mel filterbank [n_mels, n_fft//2 + 1] (reference
    ``compute_fbank_matrix``)."""
    if f_max is None:
        f_max = float(sr) / 2
    fftfreqs = fft_frequencies(sr, n_fft, dtype="float64")
    mel_f = mel_frequencies(n_mels + 2, f_min, f_max, htk, dtype="float64")
    fdiff = jnp.diff(mel_f)
    ramps = mel_f[:, None] - fftfreqs[None, :]  # [n_mels+2, n_bins]
    lower = -ramps[:-2] / fdiff[:-1, None]
    upper = ramps[2:] / fdiff[1:, None]
    weights = jnp.maximum(0.0, jnp.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (mel_f[2:n_mels + 2] - mel_f[:n_mels])
        weights = weights * enorm[:, None]
    elif isinstance(norm, (int, float)):
        weights = weights / jnp.maximum(
            jnp.sum(jnp.abs(weights) ** norm, axis=1,
                    keepdims=True) ** (1.0 / norm), 1e-10)
    return weights.astype(convert_dtype(dtype))


def power_to_db(spect, ref_value: float = 1.0, amin: float = 1e-10,
                top_db: Optional[float] = 80.0):
    """Power spectrogram -> decibels with optional dynamic-range clamp."""
    spect = jnp.asarray(spect)
    log_spec = 10.0 * jnp.log10(jnp.maximum(amin, spect))
    log_spec = log_spec - 10.0 * math.log10(max(amin, ref_value))
    if top_db is not None:
        if top_db < 0:
            raise ValueError("top_db must be non-negative")
        log_spec = jnp.maximum(log_spec, log_spec.max() - top_db)
    return log_spec


def create_dct(n_mfcc: int, n_mels: int, norm: Optional[str] = "ortho",
               dtype: str = "float32"):
    """DCT-II basis [n_mels, n_mfcc] (reference ``create_dct``)."""
    n = jnp.arange(n_mels, dtype=jnp.float64)
    k = jnp.arange(n_mfcc, dtype=jnp.float64)[None, :]
    basis = jnp.cos(math.pi / n_mels * (n[:, None] + 0.5) * k)
    if norm == "ortho":
        basis = basis * jnp.where(k == 0, 1.0 / math.sqrt(n_mels),
                                  math.sqrt(2.0 / n_mels))
    else:
        basis = basis * 2.0
    return basis.astype(convert_dtype(dtype))
