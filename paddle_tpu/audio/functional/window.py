"""Window functions.

Reference parity: ``python/paddle/audio/functional/window.py`` (registry of
window generators behind ``get_window``). Same registry shape; bodies are
jnp so windows fold into jitted feature pipelines.
"""
from __future__ import annotations

import math
from typing import Tuple, Union

import jax.numpy as jnp

from ...framework.dtype import convert_dtype

_REGISTRY = {}


def _register(fn):
    _REGISTRY[fn.__name__.lstrip("_")] = fn
    return fn


def _extend(M: int, sym: bool) -> Tuple[int, bool]:
    """Periodic windows compute M+1 symmetric points and drop the last."""
    return (M, False) if sym else (M + 1, True)


def _truncate(w, needed: bool):
    return w if not needed else w[:-1]


def _general_cosine(M: int, a, sym: bool = True):
    if M <= 1:
        return jnp.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    fac = jnp.linspace(-math.pi, math.pi, M)
    w = jnp.zeros(M)
    for k, coef in enumerate(a):
        w = w + coef * jnp.cos(k * fac)
    return _truncate(w, trunc)


@_register
def _hamming(M: int, sym: bool = True):
    return _general_cosine(M, [0.54, 0.46], sym)


@_register
def _hann(M: int, sym: bool = True):
    return _general_cosine(M, [0.5, 0.5], sym)


@_register
def _blackman(M: int, sym: bool = True):
    return _general_cosine(M, [0.42, 0.50, 0.08], sym)


@_register
def _nuttall(M: int, sym: bool = True):
    return _general_cosine(M, [0.3635819, 0.4891775, 0.1365995, 0.0106411],
                           sym)


@_register
def _cosine(M: int, sym: bool = True):
    if M <= 1:
        return jnp.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    w = jnp.sin(math.pi / M * (jnp.arange(M) + 0.5))
    return _truncate(w, trunc)


@_register
def _triang(M: int, sym: bool = True):
    if M <= 1:
        return jnp.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    n = jnp.arange(1, (M + 1) // 2 + 1)
    if M % 2 == 0:
        w = (2 * n - 1.0) / M
        w = jnp.concatenate([w, w[::-1]])
    else:
        w = 2 * n / (M + 1.0)
        w = jnp.concatenate([w, w[-2::-1]])
    return _truncate(w, trunc)


@_register
def _bohman(M: int, sym: bool = True):
    if M <= 1:
        return jnp.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    fac = jnp.abs(jnp.linspace(-1, 1, M)[1:-1])
    w = (1 - fac) * jnp.cos(math.pi * fac) + 1.0 / math.pi * jnp.sin(
        math.pi * fac)
    w = jnp.concatenate([jnp.zeros(1), w, jnp.zeros(1)])
    return _truncate(w, trunc)


@_register
def _gaussian(M: int, std: float, sym: bool = True):
    if M <= 1:
        return jnp.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    n = jnp.arange(M) - (M - 1.0) / 2
    w = jnp.exp(-(n ** 2) / (2 * std * std))
    return _truncate(w, trunc)


@_register
def _general_gaussian(M: int, p: float, sig: float, sym: bool = True):
    if M <= 1:
        return jnp.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    n = jnp.arange(M) - (M - 1.0) / 2
    w = jnp.exp(-0.5 * jnp.abs(n / sig) ** (2 * p))
    return _truncate(w, trunc)


@_register
def _exponential(M: int, center=None, tau: float = 1.0, sym: bool = True):
    if sym and center is not None:
        raise ValueError("center is not supported for symmetric windows")
    if M <= 1:
        return jnp.ones(max(M, 0))
    M, trunc = _extend(M, sym)
    if center is None:
        center = (M - 1) / 2
    n = jnp.arange(M)
    w = jnp.exp(-jnp.abs(n - center) / tau)
    return _truncate(w, trunc)


@_register
def _tukey(M: int, alpha: float = 0.5, sym: bool = True):
    if M <= 1:
        return jnp.ones(max(M, 0))
    if alpha <= 0:
        return jnp.ones(M)
    if alpha >= 1.0:
        return _hann(M, sym=sym)
    M, trunc = _extend(M, sym)
    n = jnp.arange(M)
    width = int(alpha * (M - 1) / 2.0)
    n1, n2, n3 = n[:width + 1], n[width + 1:M - width - 1], n[M - width - 1:]
    w1 = 0.5 * (1 + jnp.cos(math.pi * (-1 + 2.0 * n1 / alpha / (M - 1))))
    w2 = jnp.ones(n2.shape[0])
    w3 = 0.5 * (1 + jnp.cos(math.pi * (-2.0 / alpha + 1 +
                                       2.0 * n3 / alpha / (M - 1))))
    return _truncate(jnp.concatenate([w1, w2, w3]), trunc)


def get_window(window: Union[str, Tuple[str, float]], win_length: int,
               fftbins: bool = True, dtype: str = "float64"):
    """Window by name (or ``(name, param)``), reference ``get_window``.
    ``fftbins=True`` gives the periodic variant used by STFT."""
    sym = not fftbins
    if isinstance(window, (tuple, list)):
        name, *params = window
    elif isinstance(window, str):
        if window in ("gaussian", "exponential"):
            raise ValueError(f"window {window!r} needs a parameter: pass "
                             f"('{window}', value)")
        name, params = window, []
    else:
        raise ValueError(f"unsupported window spec {window!r}")
    if name not in _REGISTRY:
        raise ValueError(f"unknown window {name!r}; available: "
                         f"{sorted(_REGISTRY)}")
    w = _REGISTRY[name](win_length, *params, sym=sym)
    return w.astype(convert_dtype(dtype))
