"""paddle_tpu.audio — audio DSP, features, IO, datasets.

Reference parity: ``python/paddle/audio`` (functional mel/window/dB
toolkit, feature nn.Layers, wave backend, ESC50/TESS datasets).
"""
from . import backends, datasets, features, functional  # noqa: F401

__all__ = ["backends", "datasets", "features", "functional", "load", "save",
           "info"]

from .backends.wave_backend import info, load, save  # noqa: F401,E402
