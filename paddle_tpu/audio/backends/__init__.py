"""Audio IO backends (reference ``audio/backends``). One backend: stdlib
wave (16-bit PCM). ``list_available_backends``/``set_backend`` keep the
reference's backend-registry API shape."""
from . import wave_backend
from .wave_backend import AudioInfo, info, load, save

__all__ = ["info", "load", "save", "AudioInfo", "list_available_backends",
           "get_current_backend", "set_backend"]


def list_available_backends():
    return ["wave_backend"]


def get_current_backend():
    return "wave_backend"


def set_backend(backend_name: str):
    if backend_name != "wave_backend":
        raise NotImplementedError(
            f"only 'wave_backend' is available, got {backend_name!r}")
