"""WAV file IO via the stdlib ``wave`` module.

Reference parity: ``python/paddle/audio/backends/wave_backend.py`` —
``load``/``save``/``info`` for 16-bit PCM WAV. numpy in/out (feature
layers take arrays; files never touch the device path).
"""
from __future__ import annotations

import wave
from dataclasses import dataclass

import numpy as np


@dataclass
class AudioInfo:
    sample_rate: int
    num_samples: int
    num_channels: int
    bits_per_sample: int
    encoding: str = "PCM_S"


def info(filepath: str) -> AudioInfo:
    with wave.open(filepath, "rb") as f:
        return AudioInfo(sample_rate=f.getframerate(),
                         num_samples=f.getnframes(),
                         num_channels=f.getnchannels(),
                         bits_per_sample=f.getsampwidth() * 8)


def load(filepath: str, frame_offset: int = 0, num_frames: int = -1,
         normalize: bool = True, channels_first: bool = True):
    """Returns ``(waveform, sample_rate)``; float32 in [-1, 1] when
    ``normalize`` else the raw int16 samples."""
    with wave.open(filepath, "rb") as f:
        sr = f.getframerate()
        nch = f.getnchannels()
        width = f.getsampwidth()
        if width != 2:
            raise ValueError(
                f"only 16-bit PCM WAV is supported, got {width * 8}-bit")
        f.setpos(frame_offset)
        n = f.getnframes() - frame_offset if num_frames < 0 else num_frames
        data = np.frombuffer(f.readframes(n), dtype=np.int16)
    data = data.reshape(-1, nch)
    if normalize:
        # 32767 divisor matches save()'s multiplier so a float round-trip is
        # pure quantization error (<= 0.5/32767)
        data = (data / 32767.0).astype(np.float32)
    wav = data.T if channels_first else data
    return wav, sr


def save(filepath: str, src, sample_rate: int, channels_first: bool = True,
         bits_per_sample: int = 16) -> None:
    if bits_per_sample != 16:
        raise ValueError("only 16-bit PCM WAV is supported")
    src = np.asarray(src)
    if src.ndim == 1:
        src = src[None, :] if channels_first else src[:, None]
    audio = src if not channels_first else src.T  # [frames, channels]
    if audio.dtype.kind == "f":
        audio = np.clip(audio, -1.0, 1.0)
        audio = (audio * 32767.0).astype(np.int16)
    elif audio.dtype != np.int16:
        # writing wider ints raw would corrupt the 2-byte-sample header
        audio = np.clip(audio, -32768, 32767).astype(np.int16)
    with wave.open(filepath, "wb") as f:
        f.setnchannels(audio.shape[1])
        f.setsampwidth(2)
        f.setframerate(sample_rate)
        f.writeframes(np.ascontiguousarray(audio).tobytes())
