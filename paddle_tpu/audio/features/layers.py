"""Audio feature extraction layers.

Reference parity: ``python/paddle/audio/features/layers.py`` (Spectrogram,
MelSpectrogram, LogMelSpectrogram, MFCC as nn.Layers over the audio
functionals). TPU-native: the fbank/DCT matrices are layer buffers, the
STFT rides :func:`paddle_tpu.signal.stft` — everything jit-compiles into
one fused pipeline (frame → rfft → |.|^2 → matmul chains on the MXU).
"""
from __future__ import annotations

from typing import Optional, Union

import jax.numpy as jnp

from ...nn.layer import Layer
from ...signal import stft
from ..functional.functional import (compute_fbank_matrix, create_dct,
                                     power_to_db)
from ..functional.window import get_window


class Spectrogram(Layer):
    """STFT magnitude/power spectrogram (reference ``Spectrogram``)."""

    def __init__(self, n_fft: int = 512, hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", dtype: str = "float32"):
        super().__init__()
        if power <= 0:
            raise ValueError("power must be positive")
        self.n_fft = n_fft
        self.power = power
        self.hop_length = hop_length or n_fft // 4
        self.win_length = win_length or n_fft
        if self.win_length > n_fft:
            raise ValueError(
                f"win_length ({self.win_length}) cannot exceed n_fft "
                f"({n_fft})")
        self.center = center
        self.pad_mode = pad_mode
        # raw window; stft itself center-pads win_length < n_fft
        self.register_buffer("fft_window", get_window(
            window, self.win_length, fftbins=True, dtype=dtype))

    def forward(self, x):
        spec = stft(jnp.asarray(x), n_fft=self.n_fft,
                    hop_length=self.hop_length, win_length=self.win_length,
                    window=self.fft_window, center=self.center,
                    pad_mode=self.pad_mode)
        return jnp.abs(spec) ** self.power


class MelSpectrogram(Layer):
    """Spectrogram -> mel filterbank (reference ``MelSpectrogram``)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 dtype: str = "float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft, hop_length, win_length,
                                        window, power, center, pad_mode,
                                        dtype)
        self.n_mels = n_mels
        self.register_buffer("fbank_matrix", compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype))

    def forward(self, x):
        spect = self._spectrogram(x)  # [..., n_bins, frames]
        return jnp.matmul(self.fbank_matrix, spect)


class LogMelSpectrogram(Layer):
    """Mel spectrogram in dB (reference ``LogMelSpectrogram``)."""

    def __init__(self, sr: int = 22050, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return power_to_db(self._melspectrogram(x), ref_value=self.ref_value,
                           amin=self.amin, top_db=self.top_db)


class MFCC(Layer):
    """Mel-frequency cepstral coefficients (reference ``MFCC``)."""

    def __init__(self, sr: int = 22050, n_mfcc: int = 40, n_fft: int = 512,
                 hop_length: Optional[int] = None,
                 win_length: Optional[int] = None, window: str = "hann",
                 power: float = 2.0, center: bool = True,
                 pad_mode: str = "reflect", n_mels: int = 64,
                 f_min: float = 50.0, f_max: Optional[float] = None,
                 htk: bool = False, norm: Union[str, float] = "slaney",
                 ref_value: float = 1.0, amin: float = 1e-10,
                 top_db: Optional[float] = None, dtype: str = "float32"):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError("n_mfcc cannot exceed n_mels")
        self._log_melspectrogram = LogMelSpectrogram(
            sr, n_fft, hop_length, win_length, window, power, center,
            pad_mode, n_mels, f_min, f_max, htk, norm, ref_value, amin,
            top_db, dtype)
        self.register_buffer("dct_matrix",
                             create_dct(n_mfcc, n_mels, dtype=dtype))

    def forward(self, x):
        mel = self._log_melspectrogram(x)  # [..., n_mels, frames]
        return jnp.matmul(jnp.swapaxes(mel, -1, -2),
                          self.dct_matrix).swapaxes(-1, -2)
