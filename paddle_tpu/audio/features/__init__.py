from .layers import MFCC, LogMelSpectrogram, MelSpectrogram, Spectrogram

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]
