"""Input-pipeline cursor: where a training run is in its data stream.

Checkpoints produced by the self-healing layer
(:mod:`paddle_tpu.framework.supervisor`) record a :class:`DataCursor`
alongside the model/optimizer state, so a restart (crash, preemption,
rollback) can resume the SAME data trajectory instead of replaying the
epoch from the top: the loader is fast-forwarded to ``batch_index`` of
``epoch`` and the worker-seed stream (``epoch_seed``) is realigned.

Determinism caveat: replay is exact only when the loader's batch order is
itself deterministic — ``shuffle=False``, or a custom seeded sampler. The
stock ``RandomSampler`` draws from a fresh OS-seeded RNG each epoch, so a
resumed shuffled epoch sees a *different* permutation; the restored weights
are still exact, only the remaining batch order differs.
"""
from __future__ import annotations

import itertools
from dataclasses import dataclass
from typing import Iterator, Optional


@dataclass
class DataCursor:
    """Position in the input pipeline: the NEXT batch to be consumed."""

    epoch: int = 0
    batch_index: int = 0
    epoch_seed: int = 0     # DataLoader._epoch_seed (worker RNG stream)
    global_step: int = 0    # compiled-step count at this position

    def as_state(self) -> dict:
        """Plain-int dict for embedding in a checkpoint state tree."""
        return {"epoch": int(self.epoch),
                "batch_index": int(self.batch_index),
                "epoch_seed": int(self.epoch_seed),
                "global_step": int(self.global_step)}

    @classmethod
    def from_state(cls, state: Optional[dict]) -> Optional["DataCursor"]:
        """Rebuild from checkpoint leaves; ``None`` (old checkpoint without
        a cursor) stays ``None`` — the caller restarts the epoch."""
        if state is None:
            return None
        return cls(epoch=int(state.get("epoch", 0)),
                   batch_index=int(state.get("batch_index", 0)),
                   epoch_seed=int(state.get("epoch_seed", 0)),
                   global_step=int(state.get("global_step", 0)))

    def rescale(self, old_global_batch: int,
                new_global_batch: int) -> "DataCursor":
        """Re-express this cursor under a CHANGED global batch size.

        The elastic default keeps the global batch constant across a
        shrink/grow (``distributed.elastic_mesh.rescale_batch``), in which
        case the cursor is already valid. When a resize deliberately
        changes the global batch, the invariant to preserve is the number
        of SAMPLES consumed: ``batch_index * old_global_batch``. The new
        index rounds DOWN to a batch boundary, so a partial batch's worth
        of samples is replayed rather than skipped — replaying a few
        samples perturbs nothing, skipping them silently drops data.
        """
        if old_global_batch <= 0 or new_global_batch <= 0:
            raise ValueError("batch sizes must be positive")
        if old_global_batch == new_global_batch:
            return DataCursor(**self.as_state())
        consumed = self.batch_index * old_global_batch
        return DataCursor(epoch=self.epoch,
                          batch_index=consumed // new_global_batch,
                          epoch_seed=self.epoch_seed,
                          global_step=self.global_step)


def resume_batches(loader, start_batch: int) -> Iterator:
    """One epoch of ``loader`` starting at ``start_batch``.

    Fast-forward is cheap where the loader's structure allows it: a
    single-process map-style loader skips the leading batches at the
    *sampler* level (no dataset access, no collation). Everything else
    (iterable datasets, worker pools, bare iterables) is advanced by
    draining — the data work is repaid but no device steps run.
    """
    start_batch = int(start_batch)
    if start_batch <= 0:
        yield from loader
        return
    batch_sampler = getattr(loader, "batch_sampler", None)
    if (batch_sampler is not None
            and getattr(loader, "num_workers", 1) == 0
            and not getattr(loader, "_iterable_mode", False)):
        dataset, collate = loader.dataset, loader.collate_fn
        for indices in itertools.islice(iter(batch_sampler), start_batch,
                                        None):
            yield collate([dataset[i] for i in indices])
        return
    it = iter(loader)
    try:
        for _ in range(start_batch):
            next(it)
    except StopIteration:
        return
    yield from it
