"""DataLoader worker-process machinery.

Reference parity: ``python/paddle/fluid/dataloader/worker.py`` (worker loop,
``WorkerInfo``) and ``dataloader_iter.py``'s ``_DataLoaderIterMultiProcess``
(index queue fan-out, result reordering, worker lifecycle). TPU-native
simplifications: batches cross process boundaries as pickled numpy (PJRT's
async host->HBM transfer replaces the pin-memory/shared-memory staging the
reference needs for CUDA), and there is no DataLoader C++ channel — the
queues are ``multiprocessing`` primitives.
"""
from __future__ import annotations

import dataclasses
import queue
import traceback
from typing import Any, Callable, Optional

_worker_info: Optional["WorkerInfo"] = None


@dataclasses.dataclass
class WorkerInfo:
    """Visible to dataset code inside a worker (reference ``WorkerInfo``):
    shard an IterableDataset by ``id``/``num_workers``."""

    id: int
    num_workers: int
    seed: int
    dataset: Any = None


def get_worker_info() -> Optional[WorkerInfo]:
    """Inside a worker process, the worker's info; None in the main process
    (reference ``paddle.io.get_worker_info``)."""
    return _worker_info


class _ExceptionWrapper:
    """Carry a worker exception (with its traceback text) to the parent.

    Stores only strings: pickling the exception *class* would make the
    queue's feeder thread fail silently on locally-defined exception types,
    losing the reply and hanging the parent."""

    def __init__(self, exc: BaseException):
        self.exc_type_name = type(exc).__name__
        self.msg = "".join(traceback.format_exception(
            type(exc), exc, exc.__traceback__))

    def reraise(self):
        raise RuntimeError(
            f"DataLoader worker raised {self.exc_type_name}:\n{self.msg}")


class _ShardDone:
    """Reply payload: this worker's shard is exhausted (carries no batch).
    The credit that got this reply yields nothing; the parent stops
    crediting the worker."""

    def __init__(self, worker_id: int):
        self.worker_id = worker_id


def worker_loop(dataset, collate_fn: Callable, index_queue, data_queue,
                worker_id: int, num_workers: int, seed: int,
                worker_init_fn: Optional[Callable], iterable_mode: bool,
                batch_size: int, drop_last: bool) -> None:
    """Worker main. Both modes are credit-driven: the parent enqueues jobs
    and the worker replies ``(task_id, payload)`` with the id echoed
    opaquely (the parent tags ids with the epoch so stale replies from an
    abandoned iterator are discardable). Map-style jobs carry sample
    indices; iterable-style jobs are bare credits, each worth one batch off
    this worker's shard iterator — bounding queued data to the outstanding
    credit count even for infinite streams."""
    global _worker_info
    _worker_info = WorkerInfo(id=worker_id, num_workers=num_workers,
                              seed=seed + worker_id, dataset=dataset)
    try:
        import random

        import numpy as np

        # reseed BOTH RNGs: fork hands every worker the parent's identical
        # stdlib-random state, and the base seed varies per pool so
        # restarted workers don't replay the same augmentation stream
        np.random.seed((seed + worker_id) % (2 ** 32))
        random.seed(seed + worker_id)
        if worker_init_fn is not None:
            worker_init_fn(worker_id)

        import pickle

        def put_batch(task_id, batch):
            # pre-pickle the batch OURSELVES: mp.Queue pickles in a feeder
            # thread where errors are swallowed and the reply silently
            # lost — the parent would hang forever. Pickling here makes an
            # unpicklable batch a catchable, reportable exception. (The
            # bytes payload re-pickles as a cheap memcpy.)
            try:
                data_queue.put((task_id, pickle.dumps(batch)))
            except BaseException as e:
                data_queue.put((task_id, _ExceptionWrapper(e)))

        it = iter(dataset) if iterable_mode else None
        exhausted = False
        while True:
            job = index_queue.get()
            if job is None:
                break
            if iterable_mode:
                task_id = job
                if exhausted:
                    data_queue.put((task_id, _ShardDone(worker_id)))
                    continue
                batch = []
                try:
                    while len(batch) < batch_size:
                        batch.append(next(it))
                except StopIteration:
                    exhausted = True
                except BaseException as e:
                    data_queue.put((task_id, _ExceptionWrapper(e)))
                    exhausted = True
                    continue
                if batch and (len(batch) == batch_size or not drop_last):
                    try:
                        put_batch(task_id, collate_fn(batch))
                    except BaseException as e:
                        data_queue.put((task_id, _ExceptionWrapper(e)))
                else:
                    data_queue.put((task_id, _ShardDone(worker_id)))
            else:
                task_id, indices = job
                try:
                    batch = collate_fn([dataset[i] for i in indices])
                except BaseException as e:
                    data_queue.put((task_id, _ExceptionWrapper(e)))
                    continue
                put_batch(task_id, batch)
    except KeyboardInterrupt:
        pass
