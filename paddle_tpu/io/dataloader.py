"""DataLoader.

Reference parity: ``python/paddle/fluid/reader.py:312`` and
``fluid/dataloader/dataloader_iter.py`` (``_DataLoaderIterSingleProcess`` /
``_DataLoaderIterMultiProcess``: worker pool, index-queue fan-out, ordered
result reassembly, worker_init_fn, persistent workers). TPU-native notes:

- ``num_workers=0``: multithreaded prefetch — workers produce numpy batches;
  the host->HBM hop is async under PJRT, so a thread is enough when the
  transform is cheap.
- ``num_workers>0``: real worker *processes* (GIL-free transforms), batches
  return as pickled numpy. The reference's shared-memory + pin-memory
  staging exists to feed CUDA streams; PJRT's asynchronous device_put plays
  that role here, so the loader stops at numpy.
"""
from __future__ import annotations

import multiprocessing as mp
import queue
import threading
from typing import Callable, Iterable, Optional

import numpy as np

from .dataset import BatchSampler, Dataset, IterableDataset
from .worker import _ExceptionWrapper, _ShardDone, worker_loop


def default_collate_fn(batch):
    """Stack samples into batch arrays, mirroring paddle's default collate."""
    if len(batch) == 0:
        raise ValueError(
            "default_collate_fn got an empty batch; check the dataset / "
            "sampler (a batch must contain at least one sample)")
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (bool, np.bool_)):
        # before the int branch: bool IS an int subclass and would upcast
        return np.asarray(batch, np.bool_)
    if isinstance(sample, np.generic):
        # numpy scalar: preserve its dtype instead of python-number rules
        return np.asarray(batch, sample.dtype)
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    return np.stack([np.asarray(s) for s in batch])


_PUT_POLL_S = 0.05


class _PrefetchState:
    """State shared between a prefetch iterator and its producer thread.

    Split out so the THREAD never holds a reference to the iterator: an
    abandoned iterator then actually becomes garbage, its ``__del__`` runs
    ``close()``, and the thread (referencing only this state) unblocks.
    """

    __slots__ = ("err", "producer_busy_s", "closed")

    def __init__(self):
        self.err = None
        self.producer_busy_s = 0.0   # producer time in next()+transform
        self.closed = threading.Event()


def _prefetch_worker(producer, q, sentinel, transform, state):
    import time as _time

    def put(item) -> bool:
        # bounded put that aborts instead of blocking forever once the
        # consumer has walked away (the close() handshake)
        while not state.closed.is_set():
            try:
                q.put(item, timeout=_PUT_POLL_S)
                return True
            except queue.Full:
                continue
        return False

    try:
        it = iter(producer)
        while not state.closed.is_set():
            t0 = _time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                break
            if transform is not None:
                item = transform(item)
            state.producer_busy_s += _time.perf_counter() - t0
            if not put(item):
                return
    except BaseException as e:  # propagate into consumer
        state.err = e
    finally:
        put(sentinel)


class _PrefetchIterator:
    """Bounded background-thread prefetch.

    - ``transform`` (optional) runs in the producer thread — the hook
      :class:`paddle_tpu.io.device_prefetch.DevicePrefetchIterator` uses to
      overlap host->device transfer with consumer compute. It must not
      close over this iterator (see :class:`_PrefetchState`).
    - A producer exception is delivered on the consumer's NEXT ``__next__``
      (already-queued good batches are dropped), not after the queue drains.
    - ``close()`` unblocks and joins the thread; it runs from ``__del__``
      and on exhaustion/error, so an abandoned iterator cannot leak a
      thread parked on the bounded queue.
    """

    def __init__(self, producer: Iterable, depth: int, transform=None):
        self._queue: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
        self._sentinel = object()
        self._state = _PrefetchState()
        self._done = False
        self._batches = 0
        self._stall_s = 0.0          # consumer time blocked waiting for data
        self._thread = threading.Thread(
            target=_prefetch_worker,
            args=(producer, self._queue, self._sentinel, transform,
                  self._state),
            daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        import time as _time

        if self._done:
            raise StopIteration
        if self._state.err is not None:
            # prompt delivery: don't make the consumer chew through queued
            # batches before learning the epoch already failed
            err, self._state.err = self._state.err, None
            self.close()
            raise err
        t0 = _time.perf_counter()
        item = self._queue.get()
        self._stall_s += _time.perf_counter() - t0
        if item is self._sentinel:
            if self._state.err is not None:
                err, self._state.err = self._state.err, None
                self.close()
                raise err
            self.close()
            raise StopIteration
        self._batches += 1
        return item

    def stats(self) -> dict:
        """Pipeline health counters: batches delivered, consumer stall
        seconds (input-bound time), producer busy seconds."""
        return {"batches": self._batches,
                "consumer_stall_s": self._stall_s,
                "producer_busy_s": self._state.producer_busy_s}

    def close(self):
        """Unblock and join the producer thread (idempotent)."""
        self._done = True
        if self._state.closed.is_set():
            return
        self._state.closed.set()
        # drain so a producer blocked mid-put observes the close flag
        while self._thread.is_alive():
            try:
                self._queue.get_nowait()
            except queue.Empty:
                pass
            self._thread.join(timeout=_PUT_POLL_S)

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass


class _Hole:
    """Reorder-buffer slot for a credit that produced no batch."""


_HOLE = _Hole()


class _WorkerPool:
    """A set of worker processes plus their queues. Owned by exactly one
    live iterator at a time (its ``epoch`` tag disambiguates stale replies
    left behind by an abandoned predecessor on a persistent pool)."""

    def __init__(self, loader: "DataLoader", base_seed: int):
        import warnings

        ctx = loader._mp_ctx()
        self.num_workers = loader.num_workers
        self.index_queues = [ctx.Queue() for _ in range(self.num_workers)]
        self.data_queue = ctx.Queue()
        self.epoch_counter = 0
        self.workers = []
        for wid in range(self.num_workers):
            p = ctx.Process(
                target=worker_loop,
                args=(loader.dataset, loader.collate_fn,
                      self.index_queues[wid], self.data_queue, wid,
                      self.num_workers, base_seed, loader.worker_init_fn,
                      loader._iterable_mode,
                      loader.batch_size if loader._iterable_mode else 0,
                      loader.drop_last if loader._iterable_mode else False),
                daemon=True)
            with warnings.catch_warnings():
                # JAX warns on fork because the child could deadlock on XLA
                # runtime locks; our workers run only numpy/dataset code and
                # never enter the runtime. Users who do need full isolation
                # can pass mp_context="spawn"/"forkserver".
                warnings.filterwarnings(
                    "ignore", message=".*fork.*", category=RuntimeWarning)
                warnings.filterwarnings(
                    "ignore", message=".*fork.*", category=DeprecationWarning)
                p.start()
            self.workers.append(p)

    def shutdown(self):
        if self.workers is None:
            return
        for q in self.index_queues:
            try:
                q.put(None)
            except (OSError, ValueError):
                pass
        for w in self.workers:
            w.join(timeout=5.0)
            if w.is_alive():
                w.terminate()
        for q in self.index_queues + [self.data_queue]:
            q.close()
        self.workers = None

    @property
    def alive(self):
        return self.workers is not None


class _MultiprocessIterator:
    """Worker-pool iterator (reference ``_DataLoaderIterMultiProcess``).

    Credit-driven in both modes: at most ``prefetch_factor * num_workers``
    tasks are outstanding, bounding queued batches even for infinite
    iterable datasets. Task ids are ``(epoch, idx)`` so replies from an
    abandoned predecessor on a reused persistent pool are recognizably
    stale and dropped. Map-style results reassemble in sampler order
    through the reorder buffer — output order is identical to the
    single-process loader. Iterable-style workers answer each credit with
    the next batch of their own shard (shard by :func:`get_worker_info`
    inside the dataset), interleaving round-robin.

    Pool ownership: each iterator owns its pool exclusively. Non-persistent
    loaders build a fresh pool per iterator (concurrent iterators work,
    like the single-process path). A persistent loader caches one pool and
    hands it to the newest iterator — creating a new iterator *invalidates*
    the previous one (iterating it raises), because two consumers of one
    data queue would silently eat each other's replies.
    """

    def __init__(self, loader: "DataLoader", pool: _WorkerPool,
                 owns_pool: bool):
        self._loader = loader
        self._pool = pool
        self._owns_pool = owns_pool
        self._num_workers = loader.num_workers
        self._timeout = loader.timeout or None
        self._iterable = loader._iterable_mode
        self._invalidated = False
        self._exhausted = False
        self._epoch = pool.epoch_counter
        pool.epoch_counter += 1
        self._send_idx = 0       # next credit to issue
        self._rcvd_idx = 0       # next slot to yield
        self._reorder = {}       # idx -> batch | _HOLE | _ExceptionWrapper
        self._active = set(range(self._num_workers))  # accepting credits
        self._rr = 0
        self._sampler_iter = (None if self._iterable
                              else iter(loader.batch_sampler))
        for _ in range(loader.prefetch_factor * self._num_workers):
            if not self._enqueue_next():
                break

    def _enqueue_next(self) -> bool:
        if self._iterable:
            if not self._active:
                return False
            order = sorted(self._active)
            wid = order[self._rr % len(order)]
            self._rr += 1
            self._pool.index_queues[wid].put((self._epoch, self._send_idx))
        else:
            try:
                indices = next(self._sampler_iter)
            except StopIteration:
                return False
            wid = self._send_idx % self._num_workers
            self._pool.index_queues[wid].put(
                ((self._epoch, self._send_idx), list(indices)))
        self._send_idx += 1
        return True

    def __iter__(self):
        return self

    def _get(self):
        while True:
            dead = [w for w in self._pool.workers if not w.is_alive()]
            try:
                return self._pool.data_queue.get(
                    timeout=self._timeout if self._timeout else 5.0)
            except queue.Empty:
                if dead:
                    raise RuntimeError(
                        f"DataLoader worker(s) died unexpectedly "
                        f"(pids {[w.pid for w in dead]})")
                if self._timeout:
                    raise RuntimeError(
                        f"DataLoader timed out after {self._timeout}s")

    def _finish(self):
        self._exhausted = True
        if self._owns_pool:
            self._pool.shutdown()
        elif self._loader._active_iter is self:
            self._loader._active_iter = None

    def __next__(self):
        if self._invalidated:
            raise RuntimeError(
                "this DataLoader iterator was invalidated because a newer "
                "iterator took over the persistent worker pool; do not "
                "interleave two iterators of a persistent_workers loader")
        if self._exhausted:
            raise StopIteration
        while True:
            if self._rcvd_idx in self._reorder:
                payload = self._reorder.pop(self._rcvd_idx)
                self._rcvd_idx += 1
                self._enqueue_next()
                if payload is _HOLE:
                    continue
                if isinstance(payload, _ExceptionWrapper):
                    payload.reraise()
                return payload
            if self._rcvd_idx >= self._send_idx:
                # nothing outstanding, nothing more to issue
                self._finish()
                raise StopIteration
            tag, payload = self._get()
            epoch, idx = tag
            if epoch != self._epoch:
                continue  # stale reply from an abandoned predecessor
            if isinstance(payload, _ShardDone):
                self._active.discard(payload.worker_id)
                payload = _HOLE
            elif isinstance(payload, bytes):
                # batches arrive pre-pickled (see worker.put_batch)
                import pickle

                payload = pickle.loads(payload)
            self._reorder[idx] = payload

    def __del__(self):
        try:
            if self._owns_pool and not self._exhausted:
                self._pool.shutdown()
        except Exception:
            pass


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None,
                 persistent_workers=False, mp_context=None, seed=0,
                 pad_batches=False, length_buckets=None, length_fields=None,
                 pad_value=0):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self.timeout = timeout
        self.worker_init_fn = worker_init_fn
        self.persistent_workers = persistent_workers
        self.seed = seed
        self._mp_context_name = mp_context
        self._mp = None
        self._pool = None          # persistent pool cache
        self._active_iter = None   # newest iterator on the persistent pool
        self._epoch_seed = 0
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
            self.batch_size = getattr(batch_sampler, "batch_size", batch_size)
            self.drop_last = getattr(batch_sampler, "drop_last", drop_last)
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)
            self.batch_size = batch_size
            self.drop_last = drop_last
        self.pad_batches = bool(pad_batches)
        self.length_buckets = tuple(length_buckets) if length_buckets else None
        if self.pad_batches or self.length_buckets:
            from .batching import PaddedBatcher

            # shape-stable stream: the wrapper is picklable, so worker
            # processes pad/bucket on their side of the queue too
            self.collate_fn = PaddedBatcher(
                self.collate_fn, batch_size=self.batch_size,
                pad_batches=self.pad_batches,
                length_buckets=self.length_buckets,
                length_fields=length_fields, pad_value=pad_value)

    # ------------------------------------------------- worker lifecycle
    def _mp_ctx(self):
        # lazy: num_workers=0 loaders must construct on platforms without
        # fork; "fork" matches the reference's Linux default — workers run
        # only numpy/dataset code, never the parent's XLA runtime
        if self._mp is None:
            self._mp = mp.get_context(self._mp_context_name or "fork")
        return self._mp

    def _next_base_seed(self) -> int:
        # vary per pool so restarted (non-persistent) workers don't replay
        # identical augmentation streams every epoch; persistent workers get
        # epoch diversity for free from their continuing RNG state
        base = self.seed + self._epoch_seed * 1000003
        self._epoch_seed += 1
        return base

    def _shutdown_workers(self):
        """Tear down the persistent pool (no-op for non-persistent loaders,
        whose pools die with their iterators)."""
        if self._pool is not None:
            self._pool.shutdown()
            self._pool = None
        self._active_iter = None

    def __del__(self):
        try:
            self._shutdown_workers()
        except Exception:
            pass

    # ------------------------------------------------------- iteration
    def _produce(self):
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.num_workers > 0:
            persistent = self.persistent_workers and not self._iterable_mode
            if not persistent:
                # fresh pool per iterator: concurrent iterators each get
                # their own queues (iterable workers also hold per-epoch
                # stream state, so they always restart)
                return _MultiprocessIterator(
                    self, _WorkerPool(self, self._next_base_seed()),
                    owns_pool=True)
            if self._pool is None or not self._pool.alive:
                self._pool = _WorkerPool(self, self._next_base_seed())
            if self._active_iter is not None:
                # newest iterator takes the pool; the predecessor would eat
                # its replies off the shared data queue, so invalidate it
                self._active_iter._invalidated = True
            it = _MultiprocessIterator(self, self._pool, owns_pool=False)
            self._active_iter = it
            return it
        if self.use_buffer_reader:
            return _PrefetchIterator(self._produce(),
                                     depth=self.prefetch_factor * max(self.num_workers, 1))
        return iter(self._produce())

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)
