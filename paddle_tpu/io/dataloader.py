"""DataLoader.

Reference parity: ``python/paddle/fluid/reader.py:312`` (multiprocess worker
pool + shared-memory tensors + pin-memory thread). TPU-native version:
multithreaded prefetch (workers produce numpy batches; the hot path is
host->HBM transfer which jax handles asynchronously) plus an optional
device_put prefetch depth — double-buffering input batches against step
execution, the role the reference's ``buffered_reader.cc`` H2D pipeline
plays. True multiprocess loading belongs to the C++ data channel
(``paddle_tpu/ps``) for the industrial path.
"""
from __future__ import annotations

import queue
import threading
from typing import Callable, Iterable, Optional

import numpy as np

from .dataset import BatchSampler, Dataset, IterableDataset


def default_collate_fn(batch):
    """Stack samples into batch arrays, mirroring paddle's default collate."""
    sample = batch[0]
    if isinstance(sample, (tuple, list)):
        return type(sample)(default_collate_fn([b[i] for b in batch])
                            for i in range(len(sample)))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    if isinstance(sample, (int, float)):
        return np.asarray(batch)
    return np.stack([np.asarray(s) for s in batch])


class _PrefetchIterator:
    def __init__(self, producer: Iterable, depth: int):
        self._queue: "queue.Queue" = queue.Queue(maxsize=depth)
        self._sentinel = object()
        self._err = None

        def run():
            try:
                for item in producer:
                    self._queue.put(item)
            except BaseException as e:  # propagate into consumer
                self._err = e
            finally:
                self._queue.put(self._sentinel)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def __iter__(self):
        return self

    def __next__(self):
        item = self._queue.get()
        if item is self._sentinel:
            if self._err is not None:
                raise self._err
            raise StopIteration
        return item


class DataLoader:
    def __init__(self, dataset: Dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=False, timeout=0, worker_init_fn=None):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch_factor = max(prefetch_factor, 1)
        self.use_buffer_reader = use_buffer_reader
        self._iterable_mode = isinstance(dataset, IterableDataset)
        if self._iterable_mode:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last
        elif batch_sampler is not None:
            self.batch_sampler = batch_sampler
        else:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size, drop_last=drop_last)

    def _produce(self):
        if self._iterable_mode:
            batch = []
            for sample in self.dataset:
                batch.append(sample)
                if len(batch) == self.batch_size:
                    yield self.collate_fn(batch)
                    batch = []
            if batch and not self.drop_last:
                yield self.collate_fn(batch)
        else:
            for indices in self.batch_sampler:
                yield self.collate_fn([self.dataset[i] for i in indices])

    def __iter__(self):
        if self.use_buffer_reader:
            return _PrefetchIterator(self._produce(),
                                     depth=self.prefetch_factor * max(self.num_workers, 1))
        return iter(self._produce())

    def __len__(self):
        if self._iterable_mode:
            raise TypeError("IterableDataset DataLoader has no len()")
        return len(self.batch_sampler)
