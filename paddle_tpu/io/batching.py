"""Shape stabilization for XLA: tail-batch padding and length bucketing.

On TPU every novel batch shape triggers a full XLA recompile, so a ragged
tail batch or free-form sequence lengths turn an epoch into O(#shapes)
compilations. :class:`PaddedBatcher` makes the stream shape-stable:

- **tail padding** — a short final batch is padded up to ``batch_size`` by
  repeating its last sample (real data, so losses/metrics stay finite) and
  a boolean validity mask is appended so consumers can discard the filler;
- **length bucketing** — each sample's leading (sequence) axis is rounded
  up to the smallest of a fixed set of ``length_buckets``, so an epoch
  compiles O(#buckets) programs instead of O(#lengths). Sequences longer
  than the largest bucket round up to the next multiple of it, keeping the
  shape set bounded either way.

The batcher wraps any collate_fn and is picklable, so it rides into
DataLoader worker processes unchanged. It is wired up as
``DataLoader(pad_batches=..., length_buckets=...)`` and surfaced through
``hapi.Model.fit``.
"""
from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np

__all__ = ["PaddedBatcher", "bucket_for", "pad_to_length"]


def bucket_for(length: int, buckets: Sequence[int]) -> int:
    """Deterministic bucket assignment: the smallest bucket >= ``length``.

    Beyond the largest bucket, lengths round up to the next multiple of it
    (a bounded overflow ladder rather than an error or an unbounded shape
    set). Buckets are sorted internally, so declaration order is free.
    """
    if not buckets:
        return length
    srt = sorted(int(b) for b in buckets)
    if srt[0] <= 0:
        raise ValueError(f"length_buckets must be positive, got {buckets}")
    for b in srt:
        if length <= b:
            return b
    top = srt[-1]
    return ((length + top - 1) // top) * top


def pad_to_length(arr: np.ndarray, length: int, pad_value=0) -> np.ndarray:
    """Pad ``arr`` along axis 0 up to ``length`` with ``pad_value``."""
    arr = np.asarray(arr)
    if arr.ndim == 0 or arr.shape[0] >= length:
        return arr
    widths = [(0, length - arr.shape[0])] + [(0, 0)] * (arr.ndim - 1)
    return np.pad(arr, widths, constant_values=pad_value)


def _sample_arrays(sample):
    """Flatten one sample into its ndarray leaves (tuple/list/dict aware)."""
    if isinstance(sample, (tuple, list)):
        out = []
        for s in sample:
            out.extend(_sample_arrays(s))
        return out
    if isinstance(sample, dict):
        out = []
        for k in sorted(sample):
            out.extend(_sample_arrays(sample[k]))
        return out
    return [np.asarray(sample)]


def _map_sample(sample, fn):
    """Apply ``fn`` to each ndarray leaf of a sample, preserving structure."""
    if isinstance(sample, (tuple, list)):
        return type(sample)(_map_sample(s, fn) for s in sample)
    if isinstance(sample, dict):
        return {k: _map_sample(v, fn) for k, v in sample.items()}
    return fn(np.asarray(sample))


class PaddedBatcher:
    """Collate wrapper that emits shape-stable batches.

    Parameters
    ----------
    collate_fn : the underlying collate (``default_collate_fn`` by default;
        resolved lazily to avoid an import cycle with dataloader.py).
    batch_size : target batch size; short batches are padded up to it.
    pad_batches : pad the tail batch and append a bool validity mask of
        shape ``(batch_size,)`` as the LAST element of the batch tuple
        (``emit_mask=False`` pads silently without the mask).
    length_buckets : fixed set of lengths the samples' leading axis is
        rounded up to (see :func:`bucket_for`). ``None`` disables.
    length_fields : which top-level elements of a tuple/list sample carry
        the variable-length sequence axis (e.g. ``(0,)`` for
        ``(ids, soft_label)``). ``None`` buckets every rank>=1 array leaf —
        right for ``(ids, labels)``-style LM samples, wrong for samples
        mixing sequences with fixed-size vectors/images, which would be
        padded too; name the sequence fields explicitly there.
    pad_value : fill for bucketed sequence positions (default 0).
    emit_mask : append the validity mask (only meaningful with
        ``pad_batches``).
    """

    def __init__(self, collate_fn: Optional[Callable] = None,
                 batch_size: Optional[int] = None, pad_batches: bool = True,
                 length_buckets: Optional[Sequence[int]] = None,
                 length_fields: Optional[Sequence[int]] = None,
                 pad_value=0, emit_mask: bool = True):
        self.collate_fn = collate_fn
        self.batch_size = batch_size
        self.pad_batches = bool(pad_batches)
        self.length_buckets = (tuple(sorted(int(b) for b in length_buckets))
                               if length_buckets else None)
        self.length_fields = (tuple(length_fields)
                              if length_fields is not None else None)
        self.pad_value = pad_value
        self.emit_mask = emit_mask

    def _collate(self, batch):
        if self.collate_fn is not None:
            return self.collate_fn(batch)
        from .dataloader import default_collate_fn

        return default_collate_fn(batch)

    def _seq_parts(self, sample):
        """The sub-structure(s) of a sample that carry the sequence axis."""
        if (self.length_fields is not None
                and isinstance(sample, (tuple, list))):
            return [sample[i] for i in self.length_fields]
        return [sample]

    def _bucket_samples(self, batch):
        # batch-level bucket: every sample in the batch lands on the bucket
        # of the LONGEST sample, so one batch yields one shape
        max_len = 0
        for sample in batch:
            for part in self._seq_parts(sample):
                for arr in _sample_arrays(part):
                    if arr.ndim >= 1:
                        max_len = max(max_len, arr.shape[0])
        target = bucket_for(max_len, self.length_buckets)

        def pad(arr):
            if arr.ndim >= 1:
                return pad_to_length(arr, target, self.pad_value)
            return arr

        def bucket_sample(s):
            if self.length_fields is None or not isinstance(s, (tuple, list)):
                return _map_sample(s, pad)
            fields = set(self.length_fields)
            return type(s)(_map_sample(part, pad) if i in fields else part
                           for i, part in enumerate(s))

        return [bucket_sample(s) for s in batch]

    def __call__(self, batch):
        if not batch:
            raise ValueError("PaddedBatcher got an empty batch")
        batch = list(batch)
        if self.length_buckets:
            batch = self._bucket_samples(batch)
        n_real = len(batch)
        target = self.batch_size
        if self.pad_batches and target and n_real < target:
            # repeat the last sample: filler is drawn from the data
            # distribution, so an unmasked loss stays finite and sane
            batch = batch + [batch[-1]] * (target - n_real)
        out = self._collate(batch)
        if self.pad_batches and self.emit_mask:
            mask = np.zeros(len(batch), np.bool_)
            mask[:n_real] = True
            if isinstance(out, tuple):
                out = out + (mask,)
            elif isinstance(out, list):
                out = out + [mask]
            elif isinstance(out, dict):
                out = dict(out)
                out["valid_mask"] = mask
            else:
                out = (out, mask)
        return out
