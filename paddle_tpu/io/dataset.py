"""Datasets and samplers (reference: ``python/paddle/io/`` +
``python/paddle/fluid/dataloader/``)."""
from __future__ import annotations

import bisect
import math
from typing import Iterable, Iterator, List, Optional, Sequence

import numpy as np


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset is not subscriptable")

    def __len__(self):
        raise RuntimeError("IterableDataset has no len()")


class TensorDataset(Dataset):
    def __init__(self, tensors: Sequence):
        self.tensors = [np.asarray(t) for t in tensors]
        n = len(self.tensors[0])
        assert all(len(t) == n for t in self.tensors)

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return len(self.tensors[0])


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = list(indices)

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


class ConcatDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)
        self.cumulative = np.cumsum([len(d) for d in self.datasets]).tolist()

    def __getitem__(self, idx):
        ds_idx = bisect.bisect_right(self.cumulative, idx)
        prev = 0 if ds_idx == 0 else self.cumulative[ds_idx - 1]
        return self.datasets[ds_idx][idx - prev]

    def __len__(self):
        return self.cumulative[-1]


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class ComposeDataset(Dataset):
    """Zip datasets by index: sample i is the flattened concatenation of
    every component's sample i (reference ``paddle.io.ComposeDataset``)."""

    def __init__(self, datasets):
        self.datasets = list(datasets)
        assert self.datasets, "need at least one dataset"
        n = len(self.datasets[0])
        assert all(len(d) == n for d in self.datasets), \
            "ComposeDataset requires equal lengths"

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (tuple, list)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)

    def __len__(self):
        return len(self.datasets[0])


def random_split(dataset, lengths, generator=None):
    from ..framework import random as fr

    n = len(dataset)
    if sum(lengths) != n:
        raise ValueError("sum of lengths must equal dataset size")
    perm = np.random.RandomState(fr.default_generator()._seed).permutation(n)
    out, off = [], 0
    for L in lengths:
        out.append(Subset(dataset, perm[off:off + L].tolist()))
        off += L
    return out


# ---------------------------------------------------------------- samplers
class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None, generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples
        self.generator = generator

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        rng = np.random.default_rng()
        if self.replacement:
            return iter(rng.integers(0, n, self.num_samples).tolist())
        return iter(rng.permutation(n)[: self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        rng = np.random.default_rng()
        return iter(rng.choice(len(p), self.num_samples, replace=self.replacement, p=p).tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    def __init__(self, dataset=None, sampler=None, shuffle=False, batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Rank-sharded batch sampler (reference:
    ``python/paddle/io/dataloader/batch_sampler.py`` DistributedBatchSampler).
    In SPMD pjit mode each host loads its slice of the global batch."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        from ..distributed import env as dist_env

        self.dataset = dataset
        self.batch_size = batch_size
        self.nranks = num_replicas if num_replicas is not None else dist_env.get_world_size()
        self.local_rank = rank if rank is not None else dist_env.get_rank()
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.epoch = 0
        self.num_samples = int(math.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng = np.random.RandomState(self.epoch)
            rng.shuffle(indices)
        indices = np.concatenate([indices, indices[: self.total_size - n]])
        local = indices[self.local_rank:self.total_size:self.nranks]
        batch = []
        for idx in local.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch
