"""paddle_tpu.io — datasets and loading (reference: ``python/paddle/io/``)."""
from .slot_dataset import InMemoryDataset  # noqa: F401
from .dataloader import DataLoader, default_collate_fn  # noqa: F401
from .batching import PaddedBatcher, bucket_for, pad_to_length  # noqa: F401
from .device_prefetch import (  # noqa: F401
    DevicePrefetchIterator, prefetch_to_device,
)
from .worker import WorkerInfo, get_worker_info  # noqa: F401
from .cursor import DataCursor, resume_batches  # noqa: F401
from .dataset import (  # noqa: F401
    BatchSampler, ChainDataset, ComposeDataset, ConcatDataset, Dataset,
    DistributedBatchSampler, IterableDataset, RandomSampler, Sampler,
    SequenceSampler, Subset, TensorDataset, WeightedRandomSampler,
    random_split,
)
