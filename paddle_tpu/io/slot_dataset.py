"""InMemoryDataset — the industrial slot-record training feed.

Reference parity: ``python/paddle/distributed/fleet/dataset/dataset.py:349``
(``InMemoryDataset``: ``load_into_memory``/``local_shuffle``/
``global_shuffle``/``release_memory``) over the C++
``MultiSlotDataset``/``SlotRecordInMemoryDataFeed``
(``data_set.h:350``, ``data_feed.h:1615``). Parsing/shuffle/batching run
in the native C++ store (:mod:`paddle_tpu.native`); batches come out
padded to static [batch, max_per_slot] shapes so the jitted CTR model
compiles once (SURVEY.md §7 dynamic-shape strategy).

Text format per line (tab separated)::

    <label>\\t<slot_id>:<sign>[,<sign>...]\\t...
"""
from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .. import native

__all__ = ["InMemoryDataset", "QueueDataset", "BoxPSDataset"]


class InMemoryDataset:
    def __init__(self, slots: Sequence[int], batch_size: int = 256,
                 max_per_slot: int = 16, pad_value: int = -1,
                 drop_last: bool = True):
        self.slots = [int(s) for s in slots]
        self.batch_size = batch_size
        self.max_per_slot = max_per_slot
        self.pad_value = pad_value
        self.drop_last = drop_last
        self._lib = native.get_lib()
        arr = np.asarray(self.slots, np.int64)
        self._h = self._lib.pt_feed_create(native.as_i64_ptr(arr), arr.size)
        self._epoch = 0

    # ----------------------------------------------------------- lifecycle
    def set_batch_size(self, batch_size: int) -> None:
        self.batch_size = batch_size

    def load_into_memory(self, filelist: Sequence[str]) -> int:
        """Parse files into the in-memory store (thread-parallel in C++).
        Returns total records resident."""
        for path in filelist:
            rc = self._lib.pt_feed_load_file(self._h, str(path).encode())
            if rc == -1:
                raise IOError(f"cannot read {path}")
            if rc == -2:
                raise ValueError(f"malformed slot-record line in {path}")
        return len(self)

    def local_shuffle(self, seed: Optional[int] = None) -> None:
        if seed is None:
            seed = np.random.randint(0, 2 ** 62)
        self._lib.pt_feed_shuffle(self._h, int(seed))

    def global_shuffle(self, fleet=None, seed: Optional[int] = None) -> None:
        """Single-host deployment: every record is already visible to this
        process, so a local shuffle IS the global shuffle (the reference
        shuffles across trainers over RPC, ``data_set.h`` global_shuffle)."""
        self.local_shuffle(seed)

    def release_memory(self) -> None:
        self._lib.pt_feed_clear(self._h)

    def __len__(self) -> int:
        return int(self._lib.pt_feed_num_records(self._h))

    # ------------------------------------------------------------ batching
    def _batch(self, start: int, bs: int) -> Tuple[Dict[int, np.ndarray],
                                                   Dict[int, np.ndarray],
                                                   np.ndarray]:
        slot_signs: Dict[int, np.ndarray] = {}
        slot_counts: Dict[int, np.ndarray] = {}
        for idx, slot in enumerate(self.slots):
            out = np.empty((bs, self.max_per_slot), np.int64)
            cnt = np.empty(bs, np.int32)
            self._lib.pt_feed_batch_slot(
                self._h, start, bs, idx, self.max_per_slot, self.pad_value,
                native.as_i64_ptr(out), native.as_i32_ptr(cnt))
            slot_signs[slot] = out
            slot_counts[slot] = cnt
        labels = np.empty(bs, np.float32)
        self._lib.pt_feed_batch_labels(self._h, start, bs,
                                       native.as_f32_ptr(labels))
        return slot_signs, slot_counts, labels

    def __iter__(self) -> Iterator[Tuple[Dict[int, np.ndarray],
                                         Dict[int, np.ndarray], np.ndarray]]:
        """Yields (signs {slot: [B, K] int64 padded}, counts {slot: [B]},
        labels [B] float32)."""
        n = len(self)
        bs = self.batch_size
        full = n // bs
        for b in range(full):
            yield self._batch(b * bs, bs)
        rem = n - full * bs
        if rem and not self.drop_last:
            yield self._batch(full * bs, rem)

    def __del__(self):
        h, self._h = getattr(self, "_h", None), None
        if h and native is not None:
            try:
                self._lib.pt_feed_destroy(h)
            except Exception:
                pass


class QueueDataset(InMemoryDataset):
    """Streaming slot dataset (reference ``QueueDataset``): feeds files in
    order without the in-memory global shuffle pass — the reference skips
    its shuffle channels; here the collapse is ``shuffle=False`` on the
    same C++ slot feed."""

    def local_shuffle(self, seed=None):
        # reference QueueDataset raises here too: streaming mode cannot
        # shuffle, and a silent no-op would train on file-ordered data
        raise NotImplementedError(
            "QueueDataset streams files in order; use InMemoryDataset for "
            "shuffled training")

    def global_shuffle(self, fleet=None, seed=None):
        raise NotImplementedError(
            "QueueDataset streams files in order; use InMemoryDataset for "
            "shuffled training")


class BoxPSDataset(QueueDataset):
    """Reference ``BoxPSDataset`` targets the BoxPS ads engine
    (``paddle/fluid/framework/fleet/box_wrapper.h``); its data path is the
    streaming slot feed, which is what this collapse keeps."""
