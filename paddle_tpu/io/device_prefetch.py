"""Async host->HBM prefetch: overlap the H2D hop with device compute.

The compiled step consumes batch N while a background thread already
issues the (PJRT-async) transfer for batch N+1 — the input/compute overlap
discipline that dominates step time once the step itself is fused. With a
``sharding`` (or mesh) the transfer lands each host's slice directly in
its GSPMD layout via ``make_array_from_process_local_data`` instead of a
replicated copy; without one it is a plain ``jax.device_put``.

Usage::

    it = prefetch_to_device(loader, depth=2)          # single device
    it = prefetch_to_device(loader, sharding=named)   # sharded landing
    for batch in it:
        loss = step(batch)
    it.close()   # also runs on exhaustion / GC

``it.stats()`` reports consumer stall seconds — the direct measure of an
input-bound pipeline.
"""
from __future__ import annotations

from typing import Iterable, Optional

import numpy as np

from .dataloader import _PrefetchIterator

__all__ = ["DevicePrefetchIterator", "prefetch_to_device"]


def _transfer_leaf(x, sharding, device):
    import jax

    arr = np.asarray(x)
    if sharding is not None:
        from ..framework.jax_compat import make_array_from_process_local_data

        try:
            from jax.sharding import NamedSharding, PartitionSpec

            if (isinstance(sharding, NamedSharding)
                    and arr.ndim < len(sharding.spec)):
                # lower-rank rider (e.g. the [B] validity mask next to
                # [B, S] data): clip the spec to the leaf's rank instead
                # of crashing on the rank mismatch
                sharding = NamedSharding(
                    sharding.mesh, PartitionSpec(*sharding.spec[:arr.ndim]))
        except ImportError:
            pass
        return make_array_from_process_local_data(sharding, arr)
    if device is not None:
        return jax.device_put(arr, device)
    return jax.device_put(arr)


class DevicePrefetchIterator(_PrefetchIterator):
    """Double-buffered device prefetch over any host-batch iterable.

    ``depth`` bounds the number of batches resident in HBM ahead of the
    consumer (2 = classic double buffering). The transfer runs in the
    producer thread under a ``h2d_prefetch`` profiler span; ``close()``
    (also called on exhaustion, error delivery, and GC) unblocks and joins
    the thread.
    """

    def __init__(self, producer: Iterable, depth: int = 2, sharding=None,
                 mesh=None, device=None, spec=None):
        if sharding is None and mesh is not None:
            if spec is None:
                # no silent default: PartitionSpec() (replicated) would
                # assert each process's DIFFERENT local batch is the same
                # global array on multi-host — pass the batch-axis spec
                raise ValueError(
                    "DevicePrefetchIterator(mesh=...) needs spec= (e.g. "
                    "PartitionSpec('dp') for a batch-sharded landing); or "
                    "pass sharding= directly")
            from jax.sharding import NamedSharding

            sharding = NamedSharding(mesh, spec)
        self._sharding = sharding
        self._device = device

        # a plain closure, NOT a bound method: the producer thread must not
        # hold a reference to the iterator or GC-driven shutdown breaks
        # (see dataloader._PrefetchState)
        def to_device(batch):
            import jax

            from ..profiler import RecordEvent

            with RecordEvent("h2d_prefetch"):
                return jax.tree.map(
                    lambda x: _transfer_leaf(x, sharding, device), batch)

        super().__init__(producer, depth=depth, transform=to_device)


def prefetch_to_device(data: Iterable, depth: int = 2, sharding=None,
                       mesh=None, device=None,
                       spec=None) -> DevicePrefetchIterator:
    """Wrap an iterable of host batches in a :class:`DevicePrefetchIterator`."""
    return DevicePrefetchIterator(data, depth=depth, sharding=sharding,
                                  mesh=mesh, device=device, spec=spec)
