"""Incremental engine: content-hash result cache + git-aware scoping.

The analyzer is interprocedural (trace roots in one file make a helper
in another reachable), so a naive per-file finding cache would silently
go stale when a *different* file changes. The cache therefore has two
honest modes, both keyed on content hashes (never mtimes):

- **warm whole-repo**: a full run persists, per (analyzer digest, path
  set), the per-file content hashes and the complete post-suppression
  finding list plus stats/lock-graph/import-graph. The next run hashes
  the tree (milliseconds); when EVERY hash matches, the cached result is
  the exact answer and is served without parsing a single file. Any
  drift → full re-analysis, cache refreshed. Whole-repo lint time is
  therefore bounded by hashing, not analysis, for the overwhelmingly
  common "nothing changed since CI last ran" case.
- **``--changed-only``** (the pre-commit path): git names the changed
  files; the cached import graph expands them one hop each way (what
  they import, what imports them) so cross-file trace roots and lock
  edges still resolve; only that closure is parsed and linted, and only
  findings IN the changed files gate. Sub-second on a one-file diff.
  Without a prior full-run cache the import graph is unknown and the
  tool falls back to a full run (and says so).

The analyzer digest hashes ``paddle_tpu/analysis/*.py`` itself, so
editing any rule invalidates every cached result automatically.
"""
from __future__ import annotations

import hashlib
import json
import os
import subprocess
from typing import Dict, List, Optional, Tuple

from .model import Finding, iter_py_files

__all__ = ["LintCache", "git_changed_files", "CACHE_SCHEMA"]

# 2: entries carry the R9 lifecycle_graph next to lock_graph (the
# analyzer digest already invalidates on any rule edit; the schema bump
# keeps a downgraded checkout from mis-reading the richer entries)
CACHE_SCHEMA = 2


def _sha1_file(path: str) -> str:
    h = hashlib.sha1()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


def _analyzer_digest() -> str:
    """Content hash of the analysis package itself — a rule edit must
    invalidate every cached result."""
    pkg = os.path.dirname(os.path.abspath(__file__))
    h = hashlib.sha1()
    for fn in sorted(os.listdir(pkg)):
        if fn.endswith(".py"):
            h.update(fn.encode())
            h.update(_sha1_file(os.path.join(pkg, fn)).encode())
    return h.hexdigest()


class LintCache:
    """One cache directory (default ``<repo>/.tpu_lint_cache/``), one
    entry per (analyzer digest, lint path set)."""

    def __init__(self, root: str, cache_dir: Optional[str] = None):
        self.root = root
        self.dir = cache_dir or os.path.join(root, ".tpu_lint_cache")
        self.analyzer = _analyzer_digest()

    # ------------------------------------------------------------ keys
    def _entry_path(self, paths: List[str]) -> str:
        key = hashlib.sha1(("\x00".join(sorted(paths))).encode()
                           ).hexdigest()[:16]
        return os.path.join(self.dir, f"run_{key}.json")

    def tree_digests(self, paths: List[str]) -> Dict[str, str]:
        abs_paths = [p if os.path.isabs(p) else os.path.join(self.root, p)
                     for p in paths]
        out: Dict[str, str] = {}
        for path in iter_py_files(abs_paths):
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            out[rel] = _sha1_file(path)
        return out

    # ---------------------------------------------------------- lookup
    def load(self, paths: List[str],
             digests: Dict[str, str]) -> Optional[dict]:
        """The cached entry when it matches the live tree exactly."""
        try:
            with open(self._entry_path(paths), "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if data.get("schema") != CACHE_SCHEMA \
                or data.get("analyzer") != self.analyzer \
                or data.get("files") != digests:
            return None
        return data

    def cached_entry(self, paths: List[str]) -> Optional[dict]:
        """The LAST full-run entry for ``paths`` regardless of hash
        freshness — ``--changed-only`` scopes its closure from its
        import graph and file list. Stale hashes are fine for the
        UNCHANGED side of the graph; the changed files' own imports are
        re-derived fresh (:meth:`fresh_imports`), so dependency edges
        the edit just added still pull their targets into scope."""
        try:
            with open(self._entry_path(paths), "r", encoding="utf-8") as f:
                data = json.load(f)
        except (OSError, json.JSONDecodeError):
            return None
        if data.get("schema") != CACHE_SCHEMA:
            return None
        if not data.get("imports"):
            return None
        return data

    def fresh_imports(self, changed: List[str],
                      all_rels: List[str]) -> Dict[str, List[str]]:
        """Re-parse just the CHANGED files and map their imports onto
        project files (``all_rels`` = cached file list ∪ changed), so an
        import added by the very edit under review scopes its target
        into the closure. Shares ``module_name_of``/``alias_modules``
        with ``AnalysisResult.project_imports`` — one derivation, two
        sides of the same graph."""
        from .model import SourceFile, alias_modules, module_name_of

        mod_to_rel = {module_name_of(r): r
                      for r in set(all_rels) | set(changed)}
        out: Dict[str, List[str]] = {}
        for rel in changed:
            try:
                sf = SourceFile(self.root, os.path.join(self.root, rel))
            except (OSError, SyntaxError):
                continue    # the full parse in analyze() will report it
            deps = set()
            for alias in sf.aliases.values():
                for m in alias_modules(alias):
                    got = mod_to_rel.get(m)
                    if got is not None and got != rel:
                        deps.add(got)
            out[rel] = sorted(deps)
        return out

    # ----------------------------------------------------------- store
    def store(self, paths: List[str], digests: Dict[str, str],
              findings: List[Finding], stats: dict, lock_graph: dict,
              imports: Dict[str, List[str]], timing: dict,
              lifecycle_graph: Optional[dict] = None) -> bool:
        """Best-effort: a cache write failure (read-only checkout, full
        disk) must never fail the lint that produced the result."""
        try:
            return self._store(paths, digests, findings, stats,
                               lock_graph, imports, timing,
                               lifecycle_graph or {})
        except OSError:
            return False

    def _store(self, paths: List[str], digests: Dict[str, str],
               findings: List[Finding], stats: dict, lock_graph: dict,
               imports: Dict[str, List[str]], timing: dict,
               lifecycle_graph: dict) -> bool:
        os.makedirs(self.dir, exist_ok=True)
        entry = {
            "schema": CACHE_SCHEMA,
            "analyzer": self.analyzer,
            "paths": sorted(paths),
            "files": digests,
            "findings": [f.as_dict() for f in findings],
            "stats": stats,
            "lock_graph": lock_graph,
            "lifecycle_graph": lifecycle_graph,
            "imports": imports,
            "timing": timing,
        }
        path = self._entry_path(paths)
        tmp = f"{path}.tmp{os.getpid()}"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(entry, f)
        os.replace(tmp, path)
        return True

    @staticmethod
    def findings_from(data: dict) -> List[Finding]:
        return [Finding.from_dict(d) for d in data.get("findings", ())]

    # --------------------------------------------------------- closure
    @staticmethod
    def closure(changed: List[str],
                imports: Dict[str, List[str]]) -> List[str]:
        """changed + direct imports + direct importers (one hop each
        way): enough context for cross-file trace roots, taint
        refinement, and lock edges touching the changed files."""
        importers: Dict[str, List[str]] = {}
        for src, deps in imports.items():
            for d in deps:
                importers.setdefault(d, []).append(src)
        out = set(changed)
        for rel in changed:
            out.update(imports.get(rel, ()))
            out.update(importers.get(rel, ()))
        return sorted(out)


def git_changed_files(root: str,
                      lint_paths: List[str]) -> Optional[List[str]]:
    """Project-relative changed .py files per git (diff vs HEAD plus
    untracked), restricted to the lint paths; None when git is
    unavailable (callers fall back to a full run)."""
    def run(args: List[str]) -> Optional[List[str]]:
        try:
            p = subprocess.run(["git", *args], cwd=root, timeout=30,
                               capture_output=True, text=True)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if p.returncode != 0:
            return None
        return [ln.strip() for ln in p.stdout.splitlines() if ln.strip()]

    diff = run(["diff", "--name-only", "HEAD", "--"])
    untracked = run(["ls-files", "--others", "--exclude-standard"])
    if diff is None or untracked is None:
        return None
    prefixes = tuple(p.rstrip("/") + "/" for p in lint_paths)
    out = []
    for rel in diff + untracked:
        if not rel.endswith(".py"):
            continue
        if rel in lint_paths or rel.startswith(prefixes):
            if os.path.exists(os.path.join(root, rel)):
                out.append(rel)
    return sorted(set(out))
