"""Call graph + trace/thread reachability for tpu_lint.

Trace entry points are DISCOVERED, not listed: any ``jax.jit`` /
``jax.pjit`` / ``framework.jit`` wrap site (call form, decorator form, or
``functools.partial(jax.jit, ...)``) names a wrapped function, possibly
through ``compile_cache.instrument`` / ``functools.partial`` / a local or
``self.<attr>`` assignment — that function is a *trace root*. This is what
seeds the repo's real entries (``TrainStep._step``,
``DistributedTrainStep._step``, the generation/serving prefill+decode
bodies, ``fleet.metrics``' reduce, the flash-attention kernels) without a
hand-maintained list that would rot.

From the roots, reachable-under-trace propagates along resolved call
edges. Resolution is deliberately approximate but sound for this
codebase's idioms:

- bare names -> module functions / imported project symbols / nested defs;
- ``self.m(...)`` -> MRO method, else methods named ``m`` on project
  subclasses (how ``Layer.__call__`` finds the concrete ``forward``);
- ``self.attr(...)`` where ``__init__`` did ``self.attr = SomeLayer(...)``
  -> that class's ``__call__``/``forward``;
- ``functional_call(model, ...)`` -> every project ``forward`` (the
  traced-model bridge);
- higher-order jax wrappers (``vmap``/``lax.scan``/``jax.tree.map``/...)
  -> their function-valued arguments.

The same machinery records, per jit site, the *compiled-callable
registry* — which ``self._compiled``-style attributes hold a compiled
program, with their donated argument positions and static argnames — so
rules can recognize dispatch sites (R1 lazy-value syncs, R3
donation-after-use) and thread entry points (``threading.Thread(target=
...)`` / ``Timer`` / ``Thread`` subclasses) for R5.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from .model import ClassInfo, FunctionInfo, Project

__all__ = ["CompiledInfo", "CallGraph", "build_callgraph", "dotted_path"]

_JIT_NAMES = {"jax.jit", "jax.pjit", "jax.experimental.pjit.pjit"}
_INSTRUMENT_NAMES = {"instrument"}
_HIGHER_ORDER = {"vmap", "pmap", "scan", "while_loop", "cond", "fori_loop",
                 "map", "tree_map", "checkpoint", "remat", "custom_vjp",
                 "custom_jvp", "grad", "value_and_grad", "shard_map"}


def dotted_path(node) -> Optional[Tuple[str, ...]]:
    """``a.b.c`` -> ("a", "b", "c"); None for non-name chains."""
    parts = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return tuple(reversed(parts))
    return None


@dataclass
class CompiledInfo:
    """One jit wrap site and where its compiled callable is stored."""

    target: Optional[FunctionInfo]     # the traced python body, if resolved
    donate: Set[int] = field(default_factory=set)
    statics: Set[str] = field(default_factory=set)
    site_file: str = ""
    site_line: int = 0
    decorator: bool = False    # @jit form: calling the NAME dispatches

    @property
    def site(self) -> str:
        return f"jit @ {self.site_file}:{self.site_line}"


@dataclass
class DispatchCall:
    node: ast.Call
    compiled: CompiledInfo


class CallGraph:
    def __init__(self, project: Project):
        self.project = project
        self.edges: Dict[str, List[FunctionInfo]] = {}
        # (caller, call node, callee) — rules use the arg lists to refine
        # which callee params actually receive traced values
        self.call_edges: List[Tuple[FunctionInfo, ast.Call, FunctionInfo]] = []
        self.trace_roots: List[Tuple[FunctionInfo, CompiledInfo]] = []
        self.thread_roots: List[FunctionInfo] = []
        # compiled-callable registry
        self.by_class_attr: Dict[Tuple[str, str], CompiledInfo] = {}
        self.by_local: Dict[Tuple[str, str], CompiledInfo] = {}
        self.accessor_methods: Dict[Tuple[str, str], CompiledInfo] = {}
        # decorator-jitted function qualname -> its CompiledInfo (calling
        # the bare name IS a dispatch of the compiled callable)
        self.by_name_root: Dict[str, CompiledInfo] = {}
        # per-file synthetic scope for module-level jit sites
        self._module_fis: Dict[str, FunctionInfo] = {}
        # per-function dispatch calls (calls of a known compiled callable)
        self.dispatch_calls: Dict[str, List[DispatchCall]] = {}
        # classes that start a thread somewhere in their methods
        self.threaded_classes: Set[str] = set()

    # --------------------------------------------------------- resolution
    def _local_assign_map(self, fi: FunctionInfo) -> Dict[str, ast.AST]:
        out: Dict[str, ast.AST] = {}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                out[node.targets[0].id] = node.value
        return out

    def _class_attr_assign(self, ci: ClassInfo, attr: str) -> Optional[ast.AST]:
        for m in ci.methods.values():
            for node in ast.walk(m.node):
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    if (isinstance(t, ast.Attribute) and t.attr == attr
                            and isinstance(t.value, ast.Name)
                            and t.value.id == "self"):
                        return node.value
        return None

    def _resolve_dotted(self, fi: FunctionInfo, path: Tuple[str, ...]) -> str:
        """Map a source name chain to a best-effort dotted module path
        (``jnp.dot`` -> ``jax.numpy.dot``) using the file's imports."""
        alias = fi.file.aliases.get(path[0])
        if alias is None:
            return ".".join(path)
        if alias[0] == "module":
            return ".".join((alias[1],) + path[1:])
        return ".".join((alias[1], alias[2]) + path[1:])

    def is_jit_callee(self, fi: FunctionInfo, func: ast.AST) -> bool:
        path = dotted_path(func)
        if path is None:
            return False
        dotted = self._resolve_dotted(fi, path)
        if dotted in _JIT_NAMES:
            return True
        # the framework's own jit() (paddle_tpu.framework.jit.jit)
        if path[-1] == "jit" and dotted.endswith("framework.jit.jit"):
            return True
        if len(path) == 1 and path[0] == "jit":
            target = self.project.resolve_symbol(fi.file, "jit")
            return isinstance(target, FunctionInfo)
        return False

    def _unwrap_target(self, fi: FunctionInfo, expr: ast.AST,
                       depth: int = 0) -> Optional[ast.AST]:
        """Peel instrument()/partial()/local- and self-assignments down to
        the expression naming the traced body."""
        if depth > 8 or expr is None:
            return None
        if isinstance(expr, ast.Call):
            path = dotted_path(expr.func)
            if path and (path[-1] in _INSTRUMENT_NAMES
                         or path[-1] == "partial"):
                if expr.args:
                    return self._unwrap_target(fi, expr.args[0], depth + 1)
                return None
            return None
        if isinstance(expr, ast.Name):
            # nested def or a local alias
            scope: Optional[FunctionInfo] = fi
            while scope is not None:
                if expr.id in scope.nested:
                    return expr
                scope = scope.parent
            local = self._local_assign_map(fi).get(expr.id)
            if local is not None and not isinstance(local, ast.Name):
                return self._unwrap_target(fi, local, depth + 1)
            return expr
        if isinstance(expr, ast.Attribute):
            if isinstance(expr.value, ast.Name) and expr.value.id == "self" \
                    and fi.cls is not None:
                if self.project.mro_method(fi.cls, expr.attr) is not None:
                    return expr
                assigned = self._class_attr_assign(fi.cls, expr.attr)
                if assigned is not None:
                    return self._unwrap_target(fi, assigned, depth + 1)
            return expr
        if isinstance(expr, ast.Lambda):
            return None
        return None

    def _target_function(self, fi: FunctionInfo,
                         expr: Optional[ast.AST]) -> Optional[FunctionInfo]:
        if expr is None:
            return None
        if isinstance(expr, ast.Name):
            scope: Optional[FunctionInfo] = fi
            while scope is not None:
                if expr.id in scope.nested:
                    return scope.nested[expr.id]
                scope = scope.parent
            got = self.project.resolve_symbol(fi.file, expr.id)
            return got if isinstance(got, FunctionInfo) else None
        if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and fi.cls is not None:
            return self.project.mro_method(fi.cls, expr.attr)
        return None

    # ------------------------------------------------------- jit scanning
    def _int_positions(self, fi: FunctionInfo, expr: ast.AST) -> Set[int]:
        """Every int constant inside tuple/constant literals reachable from
        ``expr`` (resolving one level of local names) — the union over
        conditional forms like ``(0, 1, 2, 3) if donate else ()``."""
        if isinstance(expr, ast.Name):
            expr = self._local_assign_map(fi).get(expr.id, expr)
        out: Set[int] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Constant) and isinstance(node.value, int) \
                    and not isinstance(node.value, bool):
                out.add(node.value)
        return out

    def _str_names(self, fi: FunctionInfo, expr: ast.AST) -> Set[str]:
        if isinstance(expr, ast.Name):
            expr = self._local_assign_map(fi).get(expr.id, expr)
        out: Set[str] = set()
        for node in ast.walk(expr):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                out.add(node.value)
        return out

    def _record_jit_call(self, fi: FunctionInfo, call: ast.Call,
                         store: Optional[ast.AST]) -> None:
        target_expr = self._unwrap_target(fi, call.args[0]) if call.args \
            else None
        target = self._target_function(fi, target_expr)
        info = CompiledInfo(target, site_file=fi.file.rel,
                            site_line=call.lineno)
        bound = isinstance(target_expr, ast.Attribute)
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                info.donate = self._int_positions(fi, kw.value)
            elif kw.arg == "static_argnames":
                info.statics |= self._str_names(fi, kw.value)
            elif kw.arg == "static_argnums" and target is not None:
                params = target.params
                if params[:1] in (["self"], ["cls"]) and bound:
                    params = params[1:]
                for i in self._int_positions(fi, kw.value):
                    if 0 <= i < len(params):
                        info.statics.add(params[i])
        if target is not None:
            target.trace_root = True
            target.statics |= info.statics
            self.trace_roots.append((target, info))
        # where is the compiled callable stored?
        if store is not None:
            if isinstance(store, ast.Name):
                self.by_local[(fi.qualname, store.id)] = info
            elif isinstance(store, ast.Attribute) \
                    and isinstance(store.value, ast.Name) \
                    and store.value.id == "self" and fi.cls is not None:
                self.by_class_attr[(fi.cls.qualname, store.attr)] = info

    def _scan_jit_sites(self) -> None:
        for fi in list(self.project.functions.values()):
            node = fi.node
            # decorator forms on the def itself
            for dec in getattr(node, "decorator_list", ()):
                d = dec
                if isinstance(d, ast.Call) and self.is_jit_callee(fi, d.func):
                    info = CompiledInfo(fi, site_file=fi.file.rel,
                                        site_line=d.lineno)
                    for kw in d.keywords:
                        if kw.arg == "static_argnames":
                            info.statics |= self._str_names(fi, kw.value)
                        elif kw.arg == "static_argnums":
                            for i in self._int_positions(fi, kw.value):
                                if 0 <= i < len(fi.params):
                                    info.statics.add(fi.params[i])
                        elif kw.arg == "donate_argnums":
                            info.donate = self._int_positions(fi, kw.value)
                    fi.trace_root = True
                    fi.statics |= info.statics
                    info.decorator = True
                    self.by_name_root.setdefault(fi.qualname, info)
                    self.trace_roots.append((fi, info))
                elif isinstance(d, ast.Call) and dotted_path(d.func) and \
                        dotted_path(d.func)[-1] == "partial" and d.args and \
                        self.is_jit_callee(fi, d.args[0]):
                    info = CompiledInfo(fi, site_file=fi.file.rel,
                                        site_line=d.lineno)
                    for kw in d.keywords:
                        if kw.arg == "static_argnames":
                            info.statics |= self._str_names(fi, kw.value)
                        elif kw.arg == "donate_argnums":
                            info.donate = self._int_positions(fi, kw.value)
                    fi.trace_root = True
                    fi.statics |= info.statics
                    info.decorator = True
                    self.by_name_root.setdefault(fi.qualname, info)
                    self.trace_roots.append((fi, info))
                elif not isinstance(d, ast.Call) and \
                        self.is_jit_callee(fi, d):
                    info = CompiledInfo(fi, site_file=fi.file.rel,
                                        site_line=d.lineno, decorator=True)
                    fi.trace_root = True
                    self.by_name_root.setdefault(fi.qualname, info)
                    self.trace_roots.append((fi, info))
            # call forms inside the body (own statements only — nested defs
            # are their own FunctionInfo)
            self._scan_jit_statements(fi, self._own_statements(fi))
        # module-level wrap sites (`run = jax.jit(body)` at file scope):
        # the body is a trace root exactly as if wrapped in a function
        for sf in self.project.files:
            mfi = self._module_fi(sf)
            self._scan_jit_statements(mfi, self._own_statements(mfi))

    def _module_fi(self, sf) -> FunctionInfo:
        """Synthetic FunctionInfo standing for a file's module scope (so
        alias/local-assign resolution works for module-level jit sites and
        their dispatch calls)."""
        fi = self._module_fis.get(sf.rel)
        if fi is None:
            fi = FunctionInfo("<module>", sf.tree, sf,
                              f"{sf.rel}::<module>")
            self._module_fis[sf.rel] = fi
        return fi

    def _scan_jit_statements(self, fi: FunctionInfo, stmts) -> None:
        for stmt in stmts:
            store = None
            call = None
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.value, ast.Call):
                store, call = stmt.targets[0], stmt.value
            elif isinstance(stmt, ast.Expr) and isinstance(stmt.value,
                                                           ast.Call):
                call = stmt.value
            if call is not None and self.is_jit_callee(fi, call.func):
                self._record_jit_call(fi, call, store)
            elif call is not None:
                # jit nested one level down: x = jax.jit(instrument(f))
                for sub in ast.walk(call):
                    if isinstance(sub, ast.Call) and sub is not call \
                            and self.is_jit_callee(fi, sub.func):
                        self._record_jit_call(fi, sub, store)
                        break

    def _scan_accessors(self) -> None:
        """Methods that just hand back a stored compiled callable
        (``return self._compiled_checked``) — lets ``self.m()(args)``
        dispatch sites resolve."""
        for fi in self.project.functions.values():
            if fi.cls is None:
                continue
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Return) \
                        and isinstance(node.value, ast.Attribute) \
                        and isinstance(node.value.value, ast.Name) \
                        and node.value.value.id == "self":
                    info = self.by_class_attr.get(
                        (fi.cls.qualname, node.value.attr))
                    if info is not None:
                        self.accessor_methods[
                            (fi.cls.qualname, fi.name)] = info

    # --------------------------------------------------------- call edges
    def _own_statements(self, fi: FunctionInfo):
        """Every statement of ``fi`` excluding nested function bodies."""
        out = []
        stack = list(fi.node.body)
        while stack:
            s = stack.pop(0)
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            out.append(s)
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.stmt):
                    stack.append(child)
        return out

    def own_calls(self, fi: FunctionInfo) -> List[ast.Call]:
        out = []
        for stmt in self._own_statements(fi):
            for node in ast.walk(stmt):
                if isinstance(node, ast.Call):
                    out.append(node)
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    break
        # dedupe (nested stmt flattening can visit a call twice)
        seen: Set[int] = set()
        uniq = []
        for c in out:
            if id(c) not in seen:
                seen.add(id(c))
                uniq.append(c)
        return uniq

    def resolve_call(self, fi: FunctionInfo,
                     call: ast.Call) -> List[FunctionInfo]:
        proj = self.project
        func = call.func
        out: List[FunctionInfo] = []
        if isinstance(func, ast.Name):
            scope: Optional[FunctionInfo] = fi
            while scope is not None:
                if func.id in scope.nested:
                    return [scope.nested[func.id]]
                scope = scope.parent
            got = proj.resolve_symbol(fi.file, func.id)
            if isinstance(got, FunctionInfo):
                out.append(got)
                if got.name == "functional_call":
                    out.extend(self._all_forwards())
            elif isinstance(got, ClassInfo):
                init = proj.mro_method(got, "__init__")
                if init is not None:
                    out.append(init)
        elif isinstance(func, ast.Attribute):
            base = func.value
            if isinstance(base, ast.Name) and base.id == "self" \
                    and fi.cls is not None:
                m = proj.mro_method(fi.cls, func.attr)
                if m is not None:
                    out.append(m)
                else:
                    out.extend(proj.subclass_methods(fi.cls, func.attr))
                    inst_cls = fi.cls.attr_types.get(func.attr)
                    if inst_cls:
                        out.extend(self._instance_call(inst_cls))
            elif isinstance(base, ast.Name):
                got = proj.resolve_module_attr(fi.file, base.id, func.attr)
                if isinstance(got, FunctionInfo):
                    out.append(got)
                    if got.name == "functional_call":
                        out.extend(self._all_forwards())
                elif isinstance(got, ClassInfo):
                    init = proj.mro_method(got, "__init__")
                    if init is not None:
                        out.append(init)
        # higher-order jax wrappers: their function-valued args run traced
        path = dotted_path(func)
        if path and path[-1] in _HIGHER_ORDER:
            for a in list(call.args)[:2]:
                t = self._target_function(fi, a) if isinstance(
                    a, (ast.Name, ast.Attribute)) else None
                if t is not None:
                    out.append(t)
        return out

    def _instance_call(self, class_name: str) -> List[FunctionInfo]:
        out = []
        for ci in self.project.classes_by_name.get(class_name, ()):
            for name in ("__call__", "forward"):
                if name in ci.methods:
                    out.append(ci.methods[name])
                    break
        return out

    _forwards_cache: Optional[List[FunctionInfo]] = None

    def _all_forwards(self) -> List[FunctionInfo]:
        if self._forwards_cache is None:
            self._forwards_cache = [
                f for f in self.project.functions.values()
                if f.name == "forward" and f.cls is not None]
        return self._forwards_cache

    def _build_edges(self) -> None:
        for fi in self.project.functions.values():
            callees: List[FunctionInfo] = []
            for call in self.own_calls(fi):
                resolved = self.resolve_call(fi, call)
                callees.extend(resolved)
                for callee in resolved:
                    self.call_edges.append((fi, call, callee))
                self._check_dispatch(fi, call)
                self._check_thread(fi, call)
            self.edges[fi.qualname] = callees

    # ------------------------------------------------- dispatch & threads
    def _compiled_for_call(self, fi: FunctionInfo,
                           call: ast.Call) -> Optional[CompiledInfo]:
        func = call.func
        if isinstance(func, ast.Name):
            scope: Optional[FunctionInfo] = fi
            while scope is not None:
                info = self.by_local.get((scope.qualname, func.id))
                if info is not None:
                    return info
                scope = scope.parent
            # module-level `run = jax.jit(body)` called by global name
            info = self.by_local.get((f"{fi.file.rel}::<module>", func.id))
            if info is not None:
                return info
            # decorator-jitted function: the bare name IS the compiled
            # callable
            got = self.project.resolve_symbol(fi.file, func.id)
            if isinstance(got, FunctionInfo):
                return self.by_name_root.get(got.qualname)
            return None
        if isinstance(func, ast.Attribute) and isinstance(func.value,
                                                          ast.Name) \
                and func.value.id == "self" and fi.cls is not None:
            ci: Optional[ClassInfo] = fi.cls
            seen = set()
            stack = [ci]
            while stack:
                c = stack.pop(0)
                if c is None or c.qualname in seen:
                    continue
                seen.add(c.qualname)
                info = self.by_class_attr.get((c.qualname, func.attr))
                if info is not None:
                    return info
                for bname in c.bases:
                    base = self.project.resolve_symbol(c.file, bname)
                    if isinstance(base, ClassInfo):
                        stack.append(base)
            # decorator-jitted method called as self.m(...)
            m = self.project.mro_method(fi.cls, func.attr)
            if m is not None:
                return self.by_name_root.get(m.qualname)
            return None
        if isinstance(func, ast.Call) and isinstance(func.func,
                                                     ast.Attribute) \
                and isinstance(func.func.value, ast.Name) \
                and func.func.value.id == "self" and fi.cls is not None:
            return self.accessor_methods.get((fi.cls.qualname,
                                              func.func.attr))
        return None

    def _check_dispatch(self, fi: FunctionInfo, call: ast.Call) -> None:
        info = self._compiled_for_call(fi, call)
        if info is not None:
            fi.dispatch = True
            self.dispatch_calls.setdefault(fi.qualname, []).append(
                DispatchCall(call, info))

    def _check_thread(self, fi: FunctionInfo, call: ast.Call) -> None:
        path = dotted_path(call.func)
        if not path or path[-1] not in ("Thread", "Timer"):
            return
        target_expr = None
        for kw in call.keywords:
            if kw.arg in ("target", "function"):
                target_expr = kw.value
        if target_expr is None and path[-1] == "Timer" \
                and len(call.args) >= 2:
            target_expr = call.args[1]
        target = self._target_function(fi, target_expr)
        if target is not None and not target.thread_root:
            target.thread_root = True
            self.thread_roots.append(target)
        if fi.cls is not None:
            self.threaded_classes.add(fi.cls.qualname)

    def _scan_thread_subclasses(self) -> None:
        for ci in self.project.classes.values():
            if any(b in ("Thread", "Timer") for b in ci.bases):
                self.threaded_classes.add(ci.qualname)
                run = ci.methods.get("run")
                if run is not None and not run.thread_root:
                    run.thread_root = True
                    self.thread_roots.append(run)

    # ------------------------------------------------------- reachability
    def _bfs_trace(self) -> None:
        from collections import deque

        q = deque()
        for root, info in self.trace_roots:
            label = f"{root.short} [{info.site}]"
            if not root.trace_reachable:
                root.trace_reachable = True
                root.trace_chain = (label,)
                q.append(root)
        while q:
            cur = q.popleft()
            for nxt in self.edges.get(cur.qualname, ()):
                if not nxt.trace_reachable:
                    nxt.trace_reachable = True
                    chain = cur.trace_chain
                    if len(chain) < 6:
                        nxt.trace_chain = chain + (nxt.short,)
                    else:
                        nxt.trace_chain = chain[:5] + ("...", nxt.short)
                    q.append(nxt)

    def _bfs_threads(self) -> None:
        from collections import deque

        q = deque()
        for root in self.thread_roots:
            root.thread_reachable = True
            root.thread_chain = (f"{root.short} [thread root]",)
            q.append(root)
        while q:
            cur = q.popleft()
            if cur.cls is not None:
                self.threaded_classes.add(cur.cls.qualname)
            for nxt in self.edges.get(cur.qualname, ()):
                if not nxt.thread_reachable:
                    nxt.thread_reachable = True
                    chain = cur.thread_chain
                    if len(chain) < 6:
                        nxt.thread_chain = chain + (nxt.short,)
                    else:
                        nxt.thread_chain = chain[:5] + ("...", nxt.short)
                    q.append(nxt)


def build_callgraph(project: Project) -> CallGraph:
    cg = CallGraph(project)
    cg._scan_jit_sites()
    cg._scan_accessors()
    cg._scan_thread_subclasses()
    cg._build_edges()
    cg._bfs_trace()
    cg._bfs_threads()
    return cg
