"""Checked-in finding baseline: accepted findings pass, NEW findings fail.

The baseline is a JSON map of finding *keys* (rule|path|symbol|snippet —
line numbers deliberately excluded, so unrelated edits don't churn it) to
occurrence counts. The gate semantics:

- a finding whose key count is within the baseline count is *accepted*
  (pre-existing, triaged);
- any finding beyond its baselined count is *new* and fails the build;
- baselined keys that no longer occur are *stale* — reported for hygiene
  but never failing (``--update-baseline`` prunes them).
"""
from __future__ import annotations

import json
import os
from typing import Dict, List, Tuple

from .model import Finding

__all__ = ["load_baseline", "save_baseline", "diff_baseline"]

# v2 (the R6/R7/R8 + incremental-engine release): same key schema, but
# every v1 entry was re-audited — fixed in-tree or converted to an
# inline reasoned suppression — so stale v1 entries cannot ride along.
# v3 (the R9/R10/R11 release): same key schema again, but the rule set
# a baseline was triaged against grew three families — a v2 baseline
# silently asserts "no R9–R11 findings were accepted" without anyone
# having looked, so it is re-keyed: re-triage and regenerate.
_VERSION = 3


def load_baseline(path: str) -> Dict[str, int]:
    if not path or not os.path.exists(path):
        return {}
    with open(path, "r", encoding="utf-8") as f:
        data = json.load(f)
    if data.get("version") != _VERSION:
        raise ValueError(
            f"baseline {path} has version {data.get('version')!r}; this "
            f"tool writes version {_VERSION} — re-triage every entry "
            f"(fix it or suppress it in-line with a reason), then "
            f"regenerate with --update-baseline (see MIGRATION.md)")
    return {str(k): int(v) for k, v in data.get("findings", {}).items()}


def save_baseline(path: str, findings: List[Finding]) -> Dict[str, int]:
    counts: Dict[str, int] = {}
    for f in findings:
        counts[f.key()] = counts.get(f.key(), 0) + 1
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": _VERSION,
                   "findings": dict(sorted(counts.items()))}, fh, indent=1,
                  sort_keys=False)
        fh.write("\n")
    return counts


def diff_baseline(findings: List[Finding],
                  baseline: Dict[str, int]) -> Tuple[List[Finding],
                                                     List[str]]:
    """``(new_findings, stale_keys)`` — new = beyond the baselined count
    for that key (R0 policy findings are never baselinable)."""
    seen: Dict[str, int] = {}
    new: List[Finding] = []
    for f in findings:
        k = f.key()
        seen[k] = seen.get(k, 0) + 1
        allowed = 0 if f.rule == "R0" else baseline.get(k, 0)
        if seen[k] > allowed:
            new.append(f)
    stale = [k for k, n in baseline.items() if seen.get(k, 0) < n]
    return new, stale
