"""R6/R7: interprocedural lock-order + blocking-under-lock analysis.

The serving/observability stack is a lock-heavy threaded system (the
server's condition variable, the scheduler/metrics/router/adapter-store
locks, the tracing/flight rings). Two whole classes of bug there are
invisible to R1–R5:

- **R6 lock-order / deadlock**: acquiring lock B while holding lock A
  fixes an order A→B; if any other path fixes B→A, two threads
  interleaving the paths deadlock. Same-lock *re-entry* through a
  non-reentrant ``threading.Lock`` is the single-thread special case —
  it deadlocks unconditionally. Both need the *interprocedural*
  acquisition graph: the second acquire is usually buried in a helper
  (or a property) called from inside the first ``with`` region.
- **R7 blocking-under-lock**: a sync (``device_get`` /
  ``block_until_ready`` / ``.item()``), a compiled-program dispatch, a
  device buffer update (``stack.at[i].set``), ``time.sleep``, an
  unbounded ``Condition.wait()``/``queue.get()``/``join()``, file I/O,
  or an rpc round-trip *inside a held-lock region*. Each is legal code —
  R1 has nothing to say — but every thread contending that lock stalls
  behind the slow operation: the classic serving latency cliff
  (placement probes blocked behind an adapter-page H2D, a metrics
  scrape blocked behind a disk write).

Lock identity is canonical: ``self._cv = threading.Condition(self._lock)``
collapses onto ``_lock`` (one lock, two names), locks defined on a base
class resolve through the MRO, and module-level locks (singleton guards)
are first-class nodes. The full graph — nodes, per-method acquisition
sites, and held→acquired order edges with call-chain evidence — is
exported in ``--json`` as ``lock_graph``.

Pure AST like every other rule: no jax import, no thread ever started.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, FrozenSet, List, Optional, Set, Tuple

from .callgraph import CallGraph, dotted_path
from .model import ClassInfo, Finding, FunctionInfo, Project

__all__ = ["LockAnalysis", "analyze_locks"]

_NONREENTRANT = {"Lock"}          # RLock/Semaphore re-entry is legal-ish
_SLEEP_PATHS = {("time", "sleep")}
_IO_NAME_CALLS = {"open"}
_IO_DOTTED = {"fsync", "replace", "rename", "makedirs", "remove",
              "unlink", "rmtree", "copyfile"}
_RPC_NAMES = {"rpc_sync", "rpc_async"}
_SYNC_TERMINALS = {"device_get", "block_until_ready"}
_BUFFER_UPDATES = {"set", "add", "multiply", "divide", "min", "max",
                   "apply"}


@dataclass
class LockNode:
    """One canonical lock: an instance attr (``file::Class.attr``) or a
    module-level name (``file::NAME``)."""

    id: str
    kind: str                      # Lock | RLock | Condition | Semaphore...
    file: str
    line: int
    aliases: List[str] = field(default_factory=list)

    def as_dict(self) -> dict:
        return {"id": self.id, "kind": self.kind, "file": self.file,
                "line": self.line, "aliases": list(self.aliases)}


@dataclass
class _Event:
    """One lock-relevant site inside a function (flow tracked by the
    region walker): an acquisition, or a call made while holding."""

    kind: str                      # "acquire" | "call" | "pcall"
    line: int
    held: FrozenSet[str]           # locks held BEFORE this event (local)
    lock: Optional[str] = None     # for acquire
    node: Optional[ast.Call] = None            # for call
    target: Optional[FunctionInfo] = None      # for pcall (property)


class LockAnalysis:
    """Builds the canonical lock set, the per-function region events, the
    interprocedural held-context fixpoint, and the R6/R7 findings."""

    def __init__(self, project: Project, cg: CallGraph):
        self.project = project
        self.cg = cg
        self.locks: Dict[str, LockNode] = {}
        # (file.rel, name) -> LockNode for module-level locks
        self._module_locks: Dict[Tuple[str, str], LockNode] = {}
        # (file.rel, name) -> ClassInfo for `X = SomeClass()` singletons
        self._module_instances: Dict[Tuple[str, str], ClassInfo] = {}
        self._events: Dict[str, List[_Event]] = {}
        self._resolved: Dict[int, List[FunctionInfo]] = {}
        # lock contexts a function may be ENTERED with, plus one sample
        # call chain per (function, lock) as evidence
        self.entry_held: Dict[str, Set[str]] = {}
        self.entry_chain: Dict[Tuple[str, str], Tuple[str, ...]] = {}
        self.acquisitions: List[dict] = []
        self.order_edges: List[dict] = []
        self.findings: List[Finding] = []

    # ------------------------------------------------------------ build
    def run(self) -> "LockAnalysis":
        self._collect_module_locks()
        self._collect_class_locks()
        for fi in self.project.functions.values():
            self._events[fi.qualname] = self._scan_regions(fi)
        self._fixpoint()
        self._emit_graph_and_r6()
        self._emit_r7()
        return self

    # ------------------------------------------------- lock collection
    @staticmethod
    def _ctor_kind(value: ast.AST) -> Optional[str]:
        if not isinstance(value, ast.Call):
            return None
        path = dotted_path(value.func)
        if path and path[-1] in ("Lock", "RLock", "Condition", "Semaphore",
                                 "BoundedSemaphore"):
            return path[-1]
        return None

    def _collect_module_locks(self) -> None:
        for sf in self.project.files:
            for stmt in sf.tree.body:
                if not (isinstance(stmt, ast.Assign)
                        and len(stmt.targets) == 1
                        and isinstance(stmt.targets[0], ast.Name)):
                    continue
                name = stmt.targets[0].id
                kind = self._ctor_kind(stmt.value)
                if kind is not None:
                    node = LockNode(f"{sf.rel}::{name}", kind, sf.rel,
                                    stmt.lineno)
                    self._module_locks[(sf.rel, name)] = node
                    self.locks[node.id] = node
                    continue
                if isinstance(stmt.value, ast.Call):
                    cname = None
                    f = stmt.value.func
                    if isinstance(f, ast.Name):
                        cname = f.id
                    elif isinstance(f, ast.Attribute):
                        cname = f.attr
                    if cname:
                        for ci in self.project.classes_by_name.get(
                                cname, ()):
                            if ci.file is sf or ci.lock_attrs:
                                self._module_instances[(sf.rel, name)] = ci
                                break

    def _collect_class_locks(self) -> None:
        for ci in self.project.classes.values():
            for attr in ci.lock_attrs:
                canon = self._canonical_attr(ci, attr)
                lid = f"{ci.qualname}.{canon}"
                node = self.locks.get(lid)
                if node is None:
                    node = LockNode(
                        lid, ci.lock_kinds.get(canon, "Lock"),
                        ci.file.rel, ci.lock_lines.get(canon, 0))
                    self.locks[lid] = node
                alias = f"{ci.name}.{attr}"
                if attr != canon and alias not in node.aliases:
                    node.aliases.append(alias)

    @staticmethod
    def _canonical_attr(ci: ClassInfo, attr: str) -> str:
        # `_cv = Condition(self._lock)` -> _lock (one hop is enough; a
        # Condition of a Condition is not a thing)
        target = ci.lock_aliases.get(attr)
        if target is not None and target in ci.lock_attrs:
            return target
        return attr

    def _class_lock_id(self, cls: Optional[ClassInfo],
                       attr: str) -> Optional[str]:
        """Resolve ``self.<attr>`` to a canonical lock id, walking the
        MRO so a lock constructed in a base class resolves from a
        subclass method."""
        seen: Set[str] = set()
        stack = [cls] if cls is not None else []
        while stack:
            c = stack.pop(0)
            if c is None or c.qualname in seen:
                continue
            seen.add(c.qualname)
            if attr in c.lock_attrs:
                return f"{c.qualname}.{self._canonical_attr(c, attr)}"
            for bname in c.bases:
                base = self.project.resolve_symbol(c.file, bname)
                if isinstance(base, ClassInfo):
                    stack.append(base)
        return None

    def _lock_for_expr(self, fi: FunctionInfo,
                       expr: ast.AST) -> Optional[str]:
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name):
            if expr.value.id == "self":
                return self._class_lock_id(fi.cls, expr.attr)
            # module singleton: `_buf.lock` where `_buf = _TraceBuffer()`
            inst = self._module_instances.get(
                (fi.file.rel, expr.value.id))
            if inst is not None:
                return self._class_lock_id(inst, expr.attr)
            return None
        if isinstance(expr, ast.Name):
            node = self._module_locks.get((fi.file.rel, expr.id))
            return node.id if node is not None else None
        return None

    # -------------------------------------------------- region walking
    def _scan_regions(self, fi: FunctionInfo) -> List[_Event]:
        events: List[_Event] = []

        def prop_target(node: ast.Attribute) -> Optional[FunctionInfo]:
            """``self.X.Y`` / ``MOD_INST.Y`` where Y is an @property of
            X's known class — an acquisition hidden behind an attribute
            read (``self.scheduler.depth`` takes the scheduler lock)."""
            base = node.value
            ci: Optional[ClassInfo] = None
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self" and fi.cls is not None:
                cname = fi.cls.attr_types.get(base.attr)
                if cname:
                    for cand in self.project.classes_by_name.get(
                            cname, ()):
                        ci = cand
                        break
            elif isinstance(base, ast.Name):
                ci = self._module_instances.get((fi.file.rel, base.id))
            if ci is None:
                return None
            m = self.project.mro_method(ci, node.attr)
            if m is None:
                return None
            for dec in getattr(m.node, "decorator_list", ()):
                if isinstance(dec, ast.Name) and dec.id == "property":
                    return m
            return None

        def walk(node: ast.AST, held: FrozenSet[str]) -> None:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fi.node:
                return
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner: Set[str] = set(held)
                for item in node.items:
                    for sub in ast.walk(item.context_expr):
                        if isinstance(sub, ast.Call):
                            events.append(_Event("call", sub.lineno,
                                                 frozenset(inner),
                                                 node=sub))
                    lid = self._lock_for_expr(fi, item.context_expr)
                    if lid is not None:
                        events.append(_Event("acquire",
                                             item.context_expr.lineno,
                                             frozenset(inner), lock=lid))
                        inner.add(lid)
                for st in node.body:
                    walk(st, frozenset(inner))
                return
            if isinstance(node, ast.Call):
                events.append(_Event("call", node.lineno, held, node=node))
            elif isinstance(node, ast.Attribute) and held \
                    and isinstance(node.ctx, ast.Load):
                t = prop_target(node)
                if t is not None:
                    events.append(_Event("pcall", node.lineno, held,
                                         target=t))
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        for st in (fi.node.body if not isinstance(fi.node, ast.Module)
                   else []):
            walk(st, frozenset())
        return events

    # --------------------------------------------- held-context fixpoint
    def _callees(self, fi: FunctionInfo,
                 call: ast.Call) -> List[FunctionInfo]:
        got = self._resolved.get(id(call))
        if got is None:
            got = list(self.cg.resolve_call(fi, call))
            # `self.X.Y()` / `MOD_INST.Y()` through the known attribute
            # type — the cross-OBJECT edges (server holding its cv while
            # poking the scheduler) are exactly what lock ordering is
            # about, so the lock analysis resolves one hop deeper than
            # the base callgraph
            f = call.func
            if not got and isinstance(f, ast.Attribute):
                base = f.value
                ci: Optional[ClassInfo] = None
                if isinstance(base, ast.Attribute) \
                        and isinstance(base.value, ast.Name) \
                        and base.value.id == "self" and fi.cls is not None:
                    cname = fi.cls.attr_types.get(base.attr)
                    if cname:
                        for cand in self.project.classes_by_name.get(
                                cname, ()):
                            ci = cand
                            break
                elif isinstance(base, ast.Name):
                    ci = self._module_instances.get(
                        (fi.file.rel, base.id))
                if ci is not None:
                    m = self.project.mro_method(ci, f.attr)
                    if m is not None:
                        got.append(m)
            self._resolved[id(call)] = got
        return got

    def _fixpoint(self) -> None:
        funcs = self.project.functions
        for _ in range(12):
            changed = False
            for qual, events in self._events.items():
                fi = funcs.get(qual)
                if fi is None:
                    continue
                inherited = self.entry_held.get(qual, set())
                for ev in events:
                    ctx = set(ev.held) | inherited
                    if not ctx:
                        continue
                    targets: List[FunctionInfo] = []
                    if ev.kind == "call" and ev.node is not None:
                        targets = self._callees(fi, ev.node)
                    elif ev.kind == "pcall" and ev.target is not None:
                        targets = [ev.target]
                    for t in targets:
                        cur = self.entry_held.setdefault(t.qualname, set())
                        new = ctx - cur
                        if new:
                            cur |= new
                            changed = True
                            for lid in new:
                                base = self.entry_chain.get((qual, lid))
                                if base is None:
                                    base = (f"{fi.short} [holds "
                                            f"{_short_lock(lid)} @ "
                                            f"{fi.file.rel}:{ev.line}]",)
                                chain = base + (t.short,) \
                                    if len(base) < 6 else base
                                self.entry_chain.setdefault(
                                    (t.qualname, lid), chain)
            if not changed:
                break

    # --------------------------------------------------- graph + R6
    def _emit_graph_and_r6(self) -> None:
        funcs = self.project.functions
        edge_seen: Set[Tuple[str, str]] = set()
        reentry_seen: Set[Tuple[str, str]] = set()
        graph: Dict[str, Set[str]] = {}
        edge_site: Dict[Tuple[str, str], dict] = {}
        for qual, events in self._events.items():
            fi = funcs.get(qual)
            if fi is None:
                continue
            inherited = self.entry_held.get(qual, set())
            for ev in events:
                if ev.kind != "acquire" or ev.lock is None:
                    continue
                self.acquisitions.append({
                    "lock": ev.lock, "function": fi.short,
                    "file": fi.file.rel, "line": ev.line})
                ctx = set(ev.held) | inherited
                if ev.lock in ctx:
                    kind = self.locks[ev.lock].kind \
                        if ev.lock in self.locks else "Lock"
                    if kind in _NONREENTRANT \
                            and (qual, ev.lock) not in reentry_seen:
                        reentry_seen.add((qual, ev.lock))
                        chain = self.entry_chain.get((qual, ev.lock), ())
                        self.findings.append(Finding(
                            "R6", fi.file.rel, ev.line,
                            f"re-enters non-reentrant {kind} "
                            f"`{_short_lock(ev.lock)}` already held on "
                            f"this path — unconditional self-deadlock",
                            symbol=fi.short,
                            snippet=fi.file.snippet(ev.line),
                            chain=chain,
                            hint="release before calling back in, make "
                                 "the helper lock-free (_locked suffix "
                                 "convention), or use an RLock "
                                 "deliberately"))
                    ctx = ctx - {ev.lock}
                for held_lock in sorted(ctx):
                    edge = {"held": held_lock, "acquired": ev.lock,
                            "function": fi.short, "file": fi.file.rel,
                            "line": ev.line,
                            "chain": list(self.entry_chain.get(
                                (qual, held_lock), ()))}
                    if (held_lock, ev.lock) not in edge_seen:
                        edge_seen.add((held_lock, ev.lock))
                        self.order_edges.append(edge)
                        edge_site[(held_lock, ev.lock)] = edge
                    graph.setdefault(held_lock, set()).add(ev.lock)
        # cycles over the order graph: every SCC of size >1 is a
        # deadlock knot. Report ONE finding per SCC naming EVERY
        # intra-SCC edge (each such edge provably lies on some cycle —
        # its endpoints are mutually reachable), not a synthetic walk
        # through the SCC in discovery order: overlapping cycles
        # (a<->b and b<->c share one SCC) must all surface.
        for scc in _sccs(graph):
            nodes = set(scc)
            edges_in = sorted((u, v) for (u, v) in edge_site
                              if u in nodes and v in nodes)
            if not edges_in:
                continue
            sites = [edge_site[p] for p in edges_in]
            anchor = sites[0]
            desc = "; ".join(
                f"{_short_lock(u)} -> {_short_lock(v)} at "
                f"{s['file']}:{s['line']} ({s['function']})"
                for (u, v), s in zip(edges_in, sites))
            names = ", ".join(sorted(_short_lock(n) for n in nodes))
            self.findings.append(Finding(
                "R6", anchor["file"], anchor["line"],
                f"lock-order cycle among {names}: {desc} — threads "
                f"interleaving these paths deadlock",
                symbol=anchor["function"],
                snippet="", hint="impose one global acquisition order "
                                 "(or drop to a single lock); every "
                                 "edge above sits on a cycle — break "
                                 "the set"))

    # ----------------------------------------------------------- R7
    def _blocking(self, fi: FunctionInfo,
                  call: ast.Call) -> Optional[Tuple[str, str]]:
        """(label, hint) when ``call`` can stall the holding thread."""
        f = call.func
        path = dotted_path(f)
        dotted = None
        if path:
            alias = fi.file.aliases.get(path[0])
            root = alias[1] if alias and alias[0] == "module" else path[0]
            dotted = (root,) + path[1:]
        # time.sleep
        if dotted and (dotted[0], dotted[-1]) in _SLEEP_PATHS:
            return ("`time.sleep` under a held lock",
                    "sleep outside the region, or poll with the lock "
                    "released")
        # explicit syncs
        if path and path[-1] in _SYNC_TERMINALS:
            return (f"`{'.'.join(path)}` (host sync) under a held lock",
                    "copy the refs out under the lock, sync outside")
        if isinstance(f, ast.Attribute) and f.attr == "item" \
                and not call.args and not call.keywords:
            return ("`.item()` (host sync) under a held lock",
                    "copy the refs out under the lock, sync outside")
        # compiled-program dispatch
        for dc in self.cg.dispatch_calls.get(fi.qualname, ()):
            if dc.node is call:
                return ("compiled-program dispatch under a held lock",
                        "dispatch outside; commit results under the "
                        "lock afterwards")
        # device buffer update: stack.at[i].set(...)
        if isinstance(f, ast.Attribute) and f.attr in _BUFFER_UPDATES \
                and isinstance(f.value, ast.Subscript) \
                and isinstance(f.value.value, ast.Attribute) \
                and f.value.value.attr == "at":
            return ("device buffer update (`.at[...].%s`) under a held "
                    "lock" % f.attr,
                    "stage the device write outside the metadata lock "
                    "(serialize writers with a dedicated staging lock), "
                    "commit the handle under it")
        # unbounded waits
        if isinstance(f, ast.Attribute) and f.attr == "wait" \
                and not call.args \
                and not any(kw.arg == "timeout" for kw in call.keywords):
            return ("unbounded `.wait()` under a held lock",
                    "pass a timeout and re-check the predicate — an "
                    "unbounded wait wedges shutdown/drain")
        if isinstance(f, ast.Attribute) and f.attr == "get" \
                and not call.args \
                and not any(kw.arg in ("timeout", "block")
                            for kw in call.keywords):
            return ("unbounded `queue.get()` under a held lock",
                    "use get(timeout=...) or get_nowait() + retry with "
                    "the lock released")
        if isinstance(f, ast.Attribute) and f.attr == "join" \
                and not call.args and not call.keywords:
            return ("unbounded `.join()` under a held lock",
                    "join with a timeout outside the lock — the joined "
                    "thread may need this very lock to finish")
        # file I/O
        if isinstance(f, ast.Name) and f.id in _IO_NAME_CALLS:
            return ("file I/O (`open`) under a held lock",
                    "snapshot under the lock, write outside (the flight "
                    "recorder's dump discipline)")
        if path and len(path) >= 2 and path[-1] in _IO_DOTTED \
                and path[0] in ("os", "shutil"):
            return (f"file I/O (`{'.'.join(path)}`) under a held lock",
                    "snapshot under the lock, write outside")
        # rpc round-trips
        if path and path[-1] in _RPC_NAMES:
            return ("rpc round-trip under a held lock",
                    "resolve the target under the lock, call outside")
        return None

    def _emit_r7(self) -> None:
        funcs = self.project.functions
        seen: Set[Tuple[str, int, str]] = set()
        for qual, events in self._events.items():
            fi = funcs.get(qual)
            if fi is None:
                continue
            inherited = self.entry_held.get(qual, set())
            for ev in events:
                if ev.kind != "call" or ev.node is None:
                    continue
                ctx = set(ev.held) | inherited
                if not ctx:
                    continue
                got = self._blocking(fi, ev.node)
                if got is None:
                    continue
                label, hint = got
                key = (qual, ev.line, label)
                if key in seen:
                    continue
                seen.add(key)
                lock_names = ", ".join(sorted(_short_lock(l)
                                              for l in ctx))
                chain = fi.thread_chain if fi.thread_reachable else ()
                if not chain:
                    for lid in sorted(ctx):
                        chain = self.entry_chain.get((qual, lid), ())
                        if chain:
                            break
                self.findings.append(Finding(
                    "R7", fi.file.rel, ev.line,
                    f"{label} (`{lock_names}`) — every thread "
                    f"contending the lock stalls behind it",
                    symbol=fi.short, snippet=fi.file.snippet(ev.line),
                    chain=chain, hint=hint))

    # ------------------------------------------------------------ export
    def lock_graph(self) -> dict:
        return {
            "locks": [n.as_dict() for n in
                      sorted(self.locks.values(), key=lambda n: n.id)],
            "acquisitions": sorted(
                self.acquisitions,
                key=lambda a: (a["file"], a["line"], a["lock"])),
            "edges": sorted(
                self.order_edges,
                key=lambda e: (e["file"], e["line"], e["acquired"])),
        }


def _short_lock(lid: str) -> str:
    # "paddle_tpu/serving/server.py::InferenceServer._cv" -> the tail
    return lid.split("::", 1)[-1]


def _sccs(graph: Dict[str, Set[str]]) -> List[List[str]]:
    """Strongly connected components of size >1 (Tarjan). Every edge
    between two nodes of one SCC lies on some cycle — the caller reports
    the full intra-SCC edge set, never a reconstructed single cycle."""
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    on_stack: Set[str] = set()
    stack: List[str] = []
    out: List[List[str]] = []
    counter = [0]

    def strongconnect(v: str) -> None:
        index[v] = low[v] = counter[0]
        counter[0] += 1
        stack.append(v)
        on_stack.add(v)
        for w in sorted(graph.get(v, ())):
            if w not in index:
                strongconnect(w)
                low[v] = min(low[v], low[w])
            elif w in on_stack:
                low[v] = min(low[v], index[w])
        if low[v] == index[v]:
            scc = []
            while True:
                w = stack.pop()
                on_stack.discard(w)
                scc.append(w)
                if w == v:
                    break
            if len(scc) > 1:
                out.append(list(reversed(scc)))

    for v in sorted(set(graph) | {w for ws in graph.values()
                                  for w in ws}):
        if v not in index:
            strongconnect(v)
    return out


def analyze_locks(project: Project, cg: CallGraph) -> LockAnalysis:
    return LockAnalysis(project, cg).run()
