"""R10: SPMD collective-divergence analysis.

A collective (``psum`` / ``all_gather`` / ``all_to_all`` / ... ) is a
*rendezvous*: every rank of the axis must issue the same collectives in
the same order, or the fleet deadlocks — silently, with every chip
spinning at 100% waiting for a peer that took the other branch. GSPMD
(arXiv:2105.04663) assumes program-order collective agreement as an
axiom; T3-style overlap scheduling makes the ordering even harder to
eyeball. This rule family checks it statically:

- **rank-divergent branch**: a Python ``if``/``while`` whose condition
  is tainted by a *rank source* (``jax.process_index()``,
  ``lax.axis_index``, ``get_rank()``, ``.rank`` attributes,
  ``os.environ["...RANK/TRAINER_ID..."]``, per-host data like
  ``local_device_count``/``gethostname``) and whose arms issue
  *different* collective sequences — some ranks enter the collective,
  others never arrive. A collective in BOTH arms in the SAME order is
  clean (every rank still rendezvouses);
- **rank-divergent loop**: a loop whose trip count is rank-tainted with
  a collective in the body — ranks disagree on HOW MANY collectives run;
- **asymmetric early exit**: a rank-tainted branch arm that returns
  while collectives follow later in the function — the returning ranks
  skip them.

Collective-bearing calls are discovered transitively over the project
call graph (the ``distributed/`` wrappers — ``all_reduce``,
``broadcast``, ``alltoall``, ``eager_all_reduce``, ``pcast`` — count
exactly like the ``lax`` primitives they wrap), so a branch arm that
calls a helper which psums deep inside still registers.

Rank taint is its own small engine (not R2's): rank values stay "rank"
through host casts (``int(os.environ["RANK"])`` is still rank-dependent
— precisely the kind of value R2's taint deliberately clears).
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, dotted_path
from .model import Finding, FunctionInfo, Project

__all__ = ["analyze_spmd", "COLLECTIVE_TAILS", "RANK_SOURCE_CALLS"]

# terminal collective primitives (jax.lax + the framework's compat shims)
COLLECTIVE_TAILS = frozenset({
    "psum", "pmean", "pmax", "pmin", "psum_scatter", "all_gather",
    "all_to_all", "ppermute", "pcast", "pshuffle", "all_reduce",
    "reduce_scatter", "alltoall", "allgather",
})
# rank / per-host data sources: tails of calls whose result differs
# per process or per shard-program instance
RANK_SOURCE_CALLS = frozenset({
    "process_index", "axis_index", "get_rank", "local_rank",
    "node_rank", "host_id", "process_count_local",
    "local_device_count", "local_devices", "gethostname", "getpid",
})
_RANK_ATTRS = frozenset({"rank", "process_index", "local_rank",
                         "node_rank"})
_RANK_PARAMS = frozenset({"rank", "process_index", "local_rank",
                          "node_rank", "trainer_id"})
_RANK_ENV_MARKERS = ("RANK", "TRAINER_ID", "PROCESS_INDEX")


def _is_rank_source(node: ast.AST) -> bool:
    if isinstance(node, ast.Call):
        path = dotted_path(node.func)
        if path and path[-1] in RANK_SOURCE_CALLS:
            return True
        # os.environ.get("PADDLE_TRAINER_ID") / os.getenv("RANK")
        if path and path[-1] in ("get", "getenv"):
            for a in node.args[:1]:
                if isinstance(a, ast.Constant) \
                        and isinstance(a.value, str) \
                        and any(m in a.value for m in _RANK_ENV_MARKERS):
                    return True
    elif isinstance(node, ast.Attribute) \
            and isinstance(getattr(node, "ctx", None), ast.Load) \
            and node.attr in _RANK_ATTRS:
        return True
    elif isinstance(node, ast.Subscript):
        # os.environ["PADDLE_TRAINER_ID"]
        sl = node.slice
        if isinstance(sl, ast.Constant) and isinstance(sl.value, str) \
                and any(m in sl.value for m in _RANK_ENV_MARKERS):
            return True
    return False


class _RankTaint:
    """Flow-insensitive rank-tainted-name set for one function. Unlike
    the R2 taint engine, host casts do NOT clear it: ``int(rank)`` is
    still rank-dependent."""

    def __init__(self, fi: FunctionInfo):
        self.fi = fi
        self.names: Set[str] = {p for p in fi.params if p in _RANK_PARAMS}
        self._propagate()

    def _propagate(self) -> None:
        for _ in range(6):
            changed = False
            for node in ast.walk(self.fi.node):
                targets = None
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.AugAssign):
                    targets, value = [node.target], node.value
                elif isinstance(node, ast.NamedExpr):
                    targets, value = [node.target], node.value
                if targets is None or not self.tainted(value):
                    continue
                for name in self._plain_names(targets):
                    if name not in self.names:
                        self.names.add(name)
                        changed = True
            if not changed:
                break

    @staticmethod
    def _plain_names(targets) -> List[str]:
        """Plain Name targets only — ``self.rank = ...`` must not taint
        ``self`` (that would rank-taint every later ``self.*`` read,
        the exact over-taint R2's engine fixed once already)."""
        out: List[str] = []
        stack = list(targets)
        while stack:
            t = stack.pop()
            if isinstance(t, ast.Name):
                out.append(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                stack.extend(t.elts)
            elif isinstance(t, ast.Starred):
                stack.append(t.value)
        return out

    def tainted(self, expr: Optional[ast.AST]) -> bool:
        if expr is None:
            return False
        for node in ast.walk(expr):
            if _is_rank_source(node):
                return True
            if isinstance(node, ast.Name) and node.id in self.names:
                return True
        return False


def _collective_tail(fi: FunctionInfo, call: ast.Call) -> Optional[str]:
    path = dotted_path(call.func)
    if path and path[-1] in COLLECTIVE_TAILS:
        return path[-1]
    return None


class SpmdAnalysis:
    def __init__(self, project: Project, cg: CallGraph):
        self.project = project
        self.cg = cg
        self.findings: List[Finding] = []
        # qualname -> flattened unconditional collective signature
        self._sigs: Dict[str, Tuple] = {}
        self._sig_stack: Set[str] = set()

    # -------------------------------------------------- call signatures
    def signature(self, fi: FunctionInfo, depth: int = 0) -> Tuple:
        """Ordered collective events ``fi`` issues when called — terminal
        collectives plus (recursively, depth-capped) project callees'.
        Conditional structure inside the callee collapses to a choice
        marker; an empty tuple means collective-free."""
        got = self._sigs.get(fi.qualname)
        if got is not None:
            return got
        if fi.qualname in self._sig_stack or depth > 3:
            return ()
        self._sig_stack.add(fi.qualname)
        try:
            events = _clean(self._seq(fi, fi.node.body, taint=None,
                                      depth=depth))
        finally:
            self._sig_stack.discard(fi.qualname)
        self._sigs[fi.qualname] = events
        return events

    def _call_events(self, fi: FunctionInfo, call: ast.Call,
                     depth: int) -> Tuple:
        tail = _collective_tail(fi, call)
        if tail is not None:
            return (tail,)
        out: List = []
        for callee in self.cg.resolve_call(fi, call):
            sub = self.signature(callee, depth + 1)
            if sub:
                out.extend(sub)
                break
        return tuple(out)

    # ------------------------------------------------ sequence modeling
    def _expr_events(self, fi: FunctionInfo, expr: Optional[ast.AST],
                     depth: int) -> Tuple:
        if expr is None:
            return ()
        out: List = []
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                out.extend(self._call_events(fi, node, depth))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                   ast.Lambda)):
                continue
        return tuple(out)

    def _seq(self, fi: FunctionInfo, stmts: Sequence[ast.stmt],
             taint: Optional[_RankTaint], depth: int,
             emit: bool = False) -> Tuple:
        """Collective-event sequence of a statement block. With
        ``taint``+``emit`` set this is the checking pass: rank-divergent
        constructs emit findings and contribute choice markers."""
        events: List = []
        for i, s in enumerate(stmts):
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.Return):
                events.extend(self._expr_events(fi, s.value, depth))
                events.append(("return",))
                break
            if isinstance(s, ast.Raise):
                events.append(("return",))
                break
            if isinstance(s, (ast.Break, ast.Continue)):
                break
            if isinstance(s, ast.If):
                events.extend(self._expr_events(fi, s.test, depth))
                a = self._seq(fi, s.body, taint, depth, emit)
                b = self._seq(fi, s.orelse, taint, depth, emit)
                divergent = (taint is not None
                             and taint.tainted(s.test))
                if divergent and emit:
                    if _terminates(a) != _terminates(b):
                        # one arm exits the function: its schedule must
                        # be compared against arm + the REST of this
                        # block, path-sensitively (a uniform branch in
                        # the suffix must not double-count)
                        rest = self._seq(fi, stmts[i + 1:], None, depth)
                        self._check_early_exit(fi, s, a, b, rest)
                    else:
                        self._check_branch(fi, s, a, b)
                if a == b:
                    events.extend(a)
                elif _has_collectives(a) or _has_collectives(b):
                    events.append(("choice", a, b))
                # a terminating arm truncates the block's suffix for
                # those paths — record it so callers comparing arms see
                # the asymmetry
                continue
            if isinstance(s, (ast.For, ast.While)):
                head = s.iter if isinstance(s, ast.For) else s.test
                events.extend(self._expr_events(fi, head, depth))
                body = self._seq(fi, s.body, taint, depth, emit)
                divergent = (taint is not None and taint.tainted(head))
                if divergent and emit and _has_collectives(body):
                    self.findings.append(self._finding(
                        fi, s.lineno,
                        f"loop trip count is rank-dependent and the "
                        f"body issues collective(s) "
                        f"{_names(body)} — ranks disagree on how many "
                        f"rendezvous to run, deadlocking the axis",
                        hint="make the trip count rank-invariant "
                             "(psum/broadcast the bound first), or "
                             "hoist the collective out of the loop"))
                if body:
                    events.append(("loop",) + body)
                events.extend(self._seq(fi, s.orelse, taint, depth, emit))
                continue
            if isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    events.extend(self._expr_events(
                        fi, item.context_expr, depth))
                events.extend(self._seq(fi, s.body, taint, depth, emit))
                continue
            if isinstance(s, ast.Try):
                events.extend(self._seq(fi, s.body, taint, depth, emit))
                for h in s.handlers:
                    self._seq(fi, h.body, taint, depth, emit)
                events.extend(self._seq(fi, s.finalbody, taint, depth,
                                        emit))
                continue
            for child in ast.iter_child_nodes(s):
                if isinstance(child, ast.expr):
                    events.extend(self._expr_events(fi, child, depth))
        return tuple(events)

    # ----------------------------------------------------------- checks
    def _check_branch(self, fi: FunctionInfo, node: ast.If,
                      a: Tuple, b: Tuple) -> None:
        a_coll = _clean(a)
        b_coll = _clean(b)
        if a_coll == b_coll:
            return      # same collectives, same order, same exits: clean
        self.findings.append(self._finding(
            fi, node.lineno,
            f"branch condition is rank-dependent and the arms issue "
            f"different collective sequences ({_names(a) or 'none'} vs "
            f"{_names(b) or 'none'}) — ranks taking different arms "
            f"never rendezvous and the whole axis deadlocks",
            hint="issue the same collectives in the same order on both "
                 "arms (mask the CONTRIBUTION, not the call: psum of a "
                 "zero is cheap, a missing psum is a hang), or hoist "
                 "the rank test inside the traced program as a "
                 "jnp.where"))

    def _check_early_exit(self, fi: FunctionInfo, node: ast.If,
                          a: Tuple, b: Tuple, rest: Tuple) -> None:
        """One arm of a rank-divergent branch exits the function. The
        exiting ranks' schedule (the arm's own collectives) must match
        SOME possible schedule of the continuing path (other arm +
        block suffix) — `if rank: return psum(x)` / `return psum(x)`
        is clean; skipping or adding a rendezvous is a deadlock."""
        a_term = _terminates(a)
        term_alts = _alts(a if a_term else b)
        cont_arm = _alts(b if a_term else a)
        rest_alts = _alts(rest)
        if term_alts is None or cont_arm is None or rest_alts is None:
            return      # path-alternative blowup: stay silent
        term_s = {sched for sched, _ in term_alts}
        cont_s: set = set()
        for sched, terminated in cont_arm:
            if terminated:
                cont_s.add(sched)
            else:
                cont_s |= {sched + r for r, _ in rest_alts}
        if term_s & cont_s:
            return      # a matching rendezvous schedule exists
        if all(not sched for sched in term_s) \
                and any(sched for sched in cont_s):
            self.findings.append(self._finding(
                fi, node.lineno,
                f"rank-dependent early exit skips the collective(s) "
                f"issued later in this function "
                f"({'/'.join(sorted(cont_s, key=len)[-1][:4])}) — the "
                f"exiting ranks never arrive at the rendezvous",
                hint="every rank must reach every collective: gate "
                     "the SIDE EFFECT on rank, not the collective "
                     "itself"))
        elif term_s != cont_s:
            t = sorted(term_s)[0] if term_s else ()
            c = sorted(cont_s)[0] if cont_s else ()
            self.findings.append(self._finding(
                fi, node.lineno,
                f"rank-dependent branch: the exiting arm issues "
                f"{'/'.join(t) or 'no collectives'} but the continuing "
                f"path issues {'/'.join(c) or 'none'} — the two rank "
                f"groups run different rendezvous schedules and "
                f"deadlock",
                hint="every rank must issue the same collectives in "
                     "the same order on every path out of this "
                     "function"))

    def _finding(self, fi: FunctionInfo, line: int, msg: str,
                 hint: str) -> Finding:
        return Finding("R10", fi.file.rel, line, msg, symbol=fi.short,
                       snippet=fi.file.snippet(line), hint=hint,
                       chain=fi.trace_chain if fi.trace_reachable else ())

    # -------------------------------------------------------------- run
    def run(self) -> "SpmdAnalysis":
        for fi in self.project.functions.values():
            taint = _RankTaint(fi)
            if not taint.names and not self._any_rank_source(fi):
                continue
            self._seq(fi, fi.node.body, taint, depth=0, emit=True)
        return self

    @staticmethod
    def _any_rank_source(fi: FunctionInfo) -> bool:
        return any(_is_rank_source(n) for n in ast.walk(fi.node))


def _clean(seq: Tuple) -> Tuple:
    """Normalize a raw event sequence down to its COLLECTIVE content:
    drop ``("return",)`` control markers, recursively clean choice/loop
    wrappers, and drop wrappers left empty — the comparison (and the
    "does this arm rendezvous at all" question) must see only the
    rendezvous structure, never the control scaffolding."""
    out: List = []
    for e in seq:
        if isinstance(e, str):
            out.append(e)
        elif isinstance(e, tuple) and e and e[0] == "choice":
            a, b = _clean(e[1]), _clean(e[2])
            if a or b:
                out.append(("choice", a, b))
        elif isinstance(e, tuple) and e and e[0] == "loop":
            body = _clean(e[1:])
            if body:
                out.append(("loop",) + body)
    return tuple(out)


def _terminates(seq: Tuple) -> bool:
    return bool(seq) and seq[-1] == ("return",)


def _flat(seq: Tuple) -> Tuple[str, ...]:
    """A sequence flattened to its collective names, in order
    (choice/loop wrappers contribute their contents; control markers
    dropped)."""
    out: List[str] = []

    def rec(s):
        for e in s:
            if isinstance(e, str):
                out.append(e)
            elif isinstance(e, tuple) and e and e[0] == "choice":
                rec(e[1])
                rec(e[2])
            elif isinstance(e, tuple) and e and e[0] == "loop":
                rec(e[1:])

    rec(seq)
    return tuple(out)


def _alts(seq: Tuple, cap: int = 16):
    """The set of possible (schedule, terminated) pairs a raw event
    sequence can realize — choice forks both ways, a loop body runs
    zero or one symbolic time, ``("return",)`` terminates the path.
    None when the alternative count exceeds ``cap`` (callers stay
    silent rather than guess)."""
    alts = {((), False)}
    for e in seq:
        new = set()
        for sched, term in alts:
            if term:
                new.add((sched, True))
                continue
            if isinstance(e, str):
                new.add((sched + (e,), False))
            elif e == ("return",):
                new.add((sched, True))
            elif isinstance(e, tuple) and e and e[0] == "choice":
                for branch in (e[1], e[2]):
                    sub = _alts(branch, cap)
                    if sub is None:
                        return None
                    for s2, t2 in sub:
                        new.add((sched + s2, t2))
            elif isinstance(e, tuple) and e and e[0] == "loop":
                sub = _alts(e[1:], cap)
                if sub is None:
                    return None
                new.add((sched, False))
                for s2, t2 in sub:
                    new.add((sched + s2, t2))
            else:
                new.add((sched, term))
        alts = new
        if len(alts) > cap:
            return None
    return alts


def _has_collectives(seq: Tuple) -> bool:
    return bool(_clean(seq))


def _names(seq: Tuple) -> str:
    flat = _flat(_clean(seq))
    return "/".join(flat[:4]) + ("..." if len(flat) > 4 else "")


def analyze_spmd(project: Project, cg: CallGraph) -> List[Finding]:
    return SpmdAnalysis(project, cg).run().findings
