"""Source model for tpu_lint: parsed files, symbols, imports, suppressions.

The analyzer never imports the code under analysis — everything is pure
``ast`` over the source tree, so it runs in milliseconds per file and can
lint code whose imports would initialize a backend. This module builds the
*project index* the call-graph layer (``callgraph.py``) and the rules
(``rules.py``) consume:

- :class:`SourceFile` — one parsed module: AST, dotted module name, the
  per-file import alias table, and the ``# tpu-lint:`` suppression map;
- :class:`FunctionInfo` / :class:`ClassInfo` — every def/class with a
  stable qualified name (``relpath::Class.method``), parameter lists, and
  the class attribute-type map (``self.embed = nn.Embedding(...)``) that
  lets ``self.embed(...)`` resolve to a forward;
- :class:`Project` — the whole tree plus lookup helpers.

Suppression grammar (the reason is MANDATORY — an empty one is itself a
finding, rule R0)::

    x = flag.item()   # tpu-lint: disable=R1(one-time init readback)
    # tpu-lint: disable=R2(bucketed by design), R4(keys derived per row)
    # tpu-lint: disable-file=R5(single-threaded CLI tool)
"""
from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = ["Finding", "SourceFile", "FunctionInfo", "ClassInfo", "Project",
           "load_project", "RULE_IDS", "module_name_of", "alias_modules"]


def module_name_of(rel: str) -> str:
    """Project-relative path -> dotted module name (the ONE place the
    ``__init__``-stripping rule lives; SourceFile and the incremental
    cache's import overlay must never disagree on it)."""
    parts = rel[:-3].split("/") if rel.endswith(".py") else rel.split("/")
    if parts and parts[-1] == "__init__":
        parts = parts[:-1]
    return ".".join(parts)


def alias_modules(alias: tuple) -> List[str]:
    """Candidate module names an import-alias entry may refer to —
    ``("module", m)`` is just m; ``("symbol", m, s)`` may be the symbol
    s in module m OR the submodule m.s."""
    mods = [alias[1]]
    if alias[0] == "symbol":
        mods.append(f"{alias[1]}.{alias[2]}")
    return mods

RULE_IDS = ("R0", "R1", "R2", "R3", "R4", "R5", "R6", "R7", "R8",
            "R9", "R10", "R11")

_SUPPRESS_RE = re.compile(
    r"#\s*tpu-lint:\s*(disable(?:-file)?)\s*=\s*(.*?)\s*$")
_RULE_REASON_RE = re.compile(r"(R\d+)\s*(?:\(([^)]*)\))?")


@dataclass
class Finding:
    """One analyzer result. ``key()`` is the baseline identity — it hangs
    on rule + file + enclosing symbol + the offending source line, so
    unrelated edits (line drift) don't churn the baseline."""

    rule: str
    path: str              # project-relative, '/'-separated
    line: int
    message: str
    symbol: str = ""       # qualified enclosing function, "" at module level
    snippet: str = ""      # stripped source line
    chain: Tuple[str, ...] = ()   # trace-entry chain (outermost first)
    hint: str = ""

    def key(self) -> str:
        snip = " ".join(self.snippet.split())
        return f"{self.rule}|{self.path}|{self.symbol}|{snip}"

    def as_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "symbol": self.symbol, "message": self.message,
                "snippet": self.snippet, "chain": list(self.chain),
                "hint": self.hint, "key": self.key()}

    @classmethod
    def from_dict(cls, d: dict) -> "Finding":
        return cls(rule=d["rule"], path=d["path"], line=int(d["line"]),
                   message=d["message"], symbol=d.get("symbol", ""),
                   snippet=d.get("snippet", ""),
                   chain=tuple(d.get("chain") or ()),
                   hint=d.get("hint", ""))

    def render(self) -> str:
        sym = f" [{self.symbol}]" if self.symbol else ""
        out = f"{self.rule} {self.path}:{self.line}{sym} {self.message}"
        if self.chain:
            out += "\n      trace chain: " + " -> ".join(self.chain)
        if self.hint:
            out += f"\n      hint: {self.hint}"
        return out


@dataclass
class Suppression:
    rule: str
    reason: str
    line: int
    file_level: bool = False
    used: bool = False


class SourceFile:
    def __init__(self, root: str, path: str):
        self.path = path
        self.rel = os.path.relpath(path, root).replace(os.sep, "/")
        with open(path, "r", encoding="utf-8") as f:
            self.text = f.read()
        self.lines = self.text.splitlines()
        self.tree = ast.parse(self.text, filename=path)
        self.module = module_name_of(self.rel)
        parts = self.module.split(".") if self.module else []
        self.package = ".".join(parts[:-1]) if parts else ""
        if self.rel.endswith("__init__.py"):
            self.package = self.module
        # alias -> ("module", dotted) | ("symbol", dotted_module, name)
        self.aliases: Dict[str, tuple] = {}
        self._collect_imports()
        # line -> [Suppression]; plus file-level entries
        self.suppressions: Dict[int, List[Suppression]] = {}
        self.file_suppressions: List[Suppression] = []
        self.bad_suppressions: List[Suppression] = []
        self._collect_suppressions()

    def snippet(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""

    # ------------------------------------------------------------ imports
    def _collect_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    name = a.asname or a.name.split(".")[0]
                    target = a.name if a.asname else a.name.split(".")[0]
                    self.aliases[name] = ("module", target)
            elif isinstance(node, ast.ImportFrom):
                if node.level:
                    base = self.package.split(".")
                    # level 1 = current package, 2 = parent, ...
                    if node.level > 1:
                        base = base[: len(base) - (node.level - 1)]
                    mod = ".".join(base + ([node.module] if node.module
                                           else []))
                else:
                    mod = node.module or ""
                for a in node.names:
                    if a.name == "*":
                        continue
                    name = a.asname or a.name
                    self.aliases[name] = ("symbol", mod, a.name)

    # ------------------------------------------------------- suppressions
    def _comment_tokens(self):
        """(line, text) of REAL comments only — a suppression example
        quoted in a docstring must not install an actual suppression."""
        try:
            toks = tokenize.generate_tokens(io.StringIO(self.text).readline)
            return [(t.start[0], t.string) for t in toks
                    if t.type == tokenize.COMMENT]
        except (tokenize.TokenError, IndentationError):
            return []

    def _collect_suppressions(self) -> None:
        for i, raw in self._comment_tokens():
            m = _SUPPRESS_RE.search(raw)
            if not m:
                continue
            file_level = m.group(1) == "disable-file"
            for rm in _RULE_REASON_RE.finditer(m.group(2)):
                rule, reason = rm.group(1), (rm.group(2) or "").strip()
                s = Suppression(rule, reason, i, file_level)
                if not reason:
                    self.bad_suppressions.append(s)
                    continue
                if file_level:
                    self.file_suppressions.append(s)
                else:
                    self.suppressions.setdefault(i, []).append(s)

    def suppressed(self, rule: str, line: int) -> bool:
        """A finding at ``line`` is suppressed by a comment on the same
        line, on the line directly above (a standalone comment), or by a
        file-level disable."""
        for s in self.file_suppressions:
            if s.rule == rule:
                s.used = True
                return True
        for cand in (line, line - 1):
            for s in self.suppressions.get(cand, ()):
                if s.rule == rule and (cand == line
                                       or self._comment_only(cand)):
                    s.used = True
                    return True
        return False

    def _comment_only(self, line: int) -> bool:
        return (1 <= line <= len(self.lines)
                and self.lines[line - 1].lstrip().startswith("#"))


@dataclass
class ClassInfo:
    name: str
    node: ast.ClassDef
    file: SourceFile
    qualname: str
    bases: List[str] = field(default_factory=list)   # source-level names
    methods: Dict[str, "FunctionInfo"] = field(default_factory=dict)
    # self.X = SomeClass(...) assignments anywhere in the class's methods
    attr_types: Dict[str, str] = field(default_factory=dict)  # attr -> class name
    # self.X = threading.Lock()/RLock()/Condition()
    lock_attrs: List[str] = field(default_factory=list)
    # lock attr -> ctor kind ("Lock"/"RLock"/"Condition"/...)
    lock_kinds: Dict[str, str] = field(default_factory=dict)
    # lock attr -> ctor line (the lock graph's node anchor)
    lock_lines: Dict[str, int] = field(default_factory=dict)
    # `self._cv = threading.Condition(self._lock)` — _cv IS _lock: the
    # two names must collapse onto one lock node or every cv use would
    # look like a second lock (and a false ordering edge)
    lock_aliases: Dict[str, str] = field(default_factory=dict)


@dataclass
class FunctionInfo:
    name: str
    node: ast.AST            # FunctionDef | AsyncFunctionDef
    file: SourceFile
    qualname: str
    cls: Optional[ClassInfo] = None
    params: List[str] = field(default_factory=list)
    # param name -> default AST node (None when no default)
    defaults: Dict[str, Optional[ast.AST]] = field(default_factory=dict)
    # names of params declared static at a jit wrap site (callgraph fills)
    statics: set = field(default_factory=set)
    trace_root: bool = False
    trace_reachable: bool = False
    trace_chain: Tuple[str, ...] = ()
    thread_root: bool = False
    thread_reachable: bool = False
    thread_chain: Tuple[str, ...] = ()
    dispatch: bool = False   # calls a known compiled callable
    nested: Dict[str, "FunctionInfo"] = field(default_factory=dict)
    parent: Optional["FunctionInfo"] = None

    @property
    def short(self) -> str:
        return (f"{self.cls.name}.{self.name}" if self.cls else self.name)


_LOCK_CTORS = {"Lock", "RLock", "Condition", "Semaphore", "BoundedSemaphore"}


def _param_names(node) -> Tuple[List[str], Dict[str, Optional[ast.AST]]]:
    a = node.args
    params = [p.arg for p in a.posonlyargs + a.args + a.kwonlyargs]
    defaults: Dict[str, Optional[ast.AST]] = {p: None for p in params}
    pos = a.posonlyargs + a.args
    for p, d in zip(pos[len(pos) - len(a.defaults):], a.defaults):
        defaults[p.arg] = d
    for p, d in zip(a.kwonlyargs, a.kw_defaults):
        defaults[p.arg] = d
    return params, defaults


class Project:
    """Every parsed file plus symbol lookup tables."""

    def __init__(self, root: str):
        self.root = root
        self.files: List[SourceFile] = []
        self.modules: Dict[str, SourceFile] = {}
        self.functions: Dict[str, FunctionInfo] = {}
        self.classes: Dict[str, ClassInfo] = {}          # qualname -> info
        self.classes_by_name: Dict[str, List[ClassInfo]] = {}
        self.by_bare_name: Dict[str, List[FunctionInfo]] = {}
        # module-level functions per file: name -> FunctionInfo
        self.module_funcs: Dict[str, Dict[str, FunctionInfo]] = {}

    # -------------------------------------------------------------- build
    def add_file(self, sf: SourceFile) -> None:
        self.files.append(sf)
        self.modules[sf.module] = sf
        self.module_funcs[sf.rel] = {}
        for node in sf.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(sf, node, cls=None, prefix="")
            elif isinstance(node, ast.ClassDef):
                self._add_class(sf, node)

    def _add_class(self, sf: SourceFile, node: ast.ClassDef) -> None:
        qual = f"{sf.rel}::{node.name}"
        ci = ClassInfo(node.name, node, sf, qual)
        for b in node.bases:
            if isinstance(b, ast.Name):
                ci.bases.append(b.id)
            elif isinstance(b, ast.Attribute):
                ci.bases.append(b.attr)
        self.classes[qual] = ci
        self.classes_by_name.setdefault(node.name, []).append(ci)
        for item in node.body:
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self._add_function(sf, item, cls=ci, prefix=node.name + ".")
        self._scan_attr_types(ci)

    def _scan_attr_types(self, ci: ClassInfo) -> None:
        for fi in ci.methods.values():
            for node in ast.walk(fi.node):
                if not (isinstance(node, ast.Assign) and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute)
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                v = node.value
                if isinstance(v, ast.Call):
                    cname = None
                    if isinstance(v.func, ast.Name):
                        cname = v.func.id
                    elif isinstance(v.func, ast.Attribute):
                        cname = v.func.attr
                    if cname in _LOCK_CTORS:
                        if t.attr not in ci.lock_attrs:
                            ci.lock_attrs.append(t.attr)
                        ci.lock_kinds.setdefault(t.attr, cname)
                        ci.lock_lines.setdefault(t.attr, node.lineno)
                        if cname == "Condition" and v.args \
                                and isinstance(v.args[0], ast.Attribute) \
                                and isinstance(v.args[0].value, ast.Name) \
                                and v.args[0].value.id == "self":
                            ci.lock_aliases[t.attr] = v.args[0].attr
                    elif cname and cname[:1].isupper():
                        ci.attr_types.setdefault(t.attr, cname)

    def _add_function(self, sf: SourceFile, node, cls, prefix,
                      parent: Optional[FunctionInfo] = None) -> FunctionInfo:
        qual = f"{sf.rel}::{prefix}{node.name}"
        if parent is not None:
            qual = f"{parent.qualname}.<locals>.{node.name}"
        params, defaults = _param_names(node)
        fi = FunctionInfo(node.name, node, sf, qual, cls=cls,
                          params=params, defaults=defaults, parent=parent)
        self.functions[qual] = fi
        self.by_bare_name.setdefault(node.name, []).append(fi)
        if cls is not None and parent is None:
            cls.methods[node.name] = fi
        if cls is None and parent is None:
            self.module_funcs[sf.rel][node.name] = fi
        # nested defs (closures passed to jit / Thread targets)
        for item in node.body:
            fi_child = None
            if isinstance(item, (ast.FunctionDef, ast.AsyncFunctionDef)):
                fi_child = self._add_function(sf, item, cls=cls,
                                              prefix=prefix, parent=fi)
            if fi_child is not None:
                fi.nested[fi_child.name] = fi_child
        return fi

    # ------------------------------------------------------------- lookup
    def resolve_symbol(self, sf: SourceFile, name: str):
        """Resolve a bare name used in ``sf`` to a FunctionInfo or
        ClassInfo (module-level def, or an imported project symbol)."""
        mf = self.module_funcs.get(sf.rel, {})
        if name in mf:
            return mf[name]
        for ci in self.classes_by_name.get(name, ()):
            if ci.file is sf:
                return ci
        alias = sf.aliases.get(name)
        if alias is None:
            return None
        if alias[0] == "symbol":
            mod, sym = alias[1], alias[2]
            target = self.modules.get(mod)
            if target is None:
                # "from a import b" where a.b is a module
                target = self.modules.get(f"{mod}.{sym}")
                return None if target is None else target
            got = self.module_funcs.get(target.rel, {}).get(sym)
            if got is not None:
                return got
            for ci in self.classes_by_name.get(sym, ()):
                if ci.file is target:
                    return ci
        return None

    def resolve_module_attr(self, sf: SourceFile, base: str, attr: str):
        alias = sf.aliases.get(base)
        if alias is None or alias[0] != "module":
            # "from x import y" where y is a submodule
            if alias is not None and alias[0] == "symbol":
                target = self.modules.get(f"{alias[1]}.{alias[2]}")
                if target is not None:
                    got = self.module_funcs.get(target.rel, {}).get(attr)
                    if got is not None:
                        return got
                    for ci in self.classes_by_name.get(attr, ()):
                        if ci.file is target:
                            return ci
            return None
        target = self.modules.get(alias[1])
        if target is None:
            return None
        got = self.module_funcs.get(target.rel, {}).get(attr)
        if got is not None:
            return got
        for ci in self.classes_by_name.get(attr, ()):
            if ci.file is target:
                return ci
        return None

    def mro_method(self, ci: ClassInfo, name: str) -> Optional[FunctionInfo]:
        seen = set()
        stack = [ci]
        while stack:
            c = stack.pop(0)
            if c.qualname in seen:
                continue
            seen.add(c.qualname)
            if name in c.methods:
                return c.methods[name]
            for bname in c.bases:
                base = self.resolve_symbol(c.file, bname)
                if isinstance(base, ClassInfo):
                    stack.append(base)
                else:
                    for cand in self.classes_by_name.get(bname, ()):
                        stack.append(cand)
        return None

    def subclass_methods(self, ci: ClassInfo, name: str) -> List[FunctionInfo]:
        """Methods named ``name`` on project classes that (transitively)
        name ``ci`` (by class name) among their bases."""
        out = []
        for cand in self.classes.values():
            if cand is ci:
                continue
            if self._derives_from(cand, ci.name, depth=0):
                if name in cand.methods:
                    out.append(cand.methods[name])
        return out

    def _derives_from(self, ci: ClassInfo, base_name: str, depth: int) -> bool:
        if depth > 6:
            return False
        for b in ci.bases:
            if b == base_name:
                return True
            for cand in self.classes_by_name.get(b, ()):
                if self._derives_from(cand, base_name, depth + 1):
                    return True
        return False


def iter_py_files(paths: List[str]) -> List[str]:
    out = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(os.path.abspath(p))
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", ".git")]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.abspath(os.path.join(dirpath, fn)))
    return out


def load_project(root: str, paths: List[str],
                 parse_times: Optional[Dict[str, float]] = None
                 ) -> Tuple[Project, List[Finding]]:
    """Parse every .py under ``paths``; returns the project plus parse/
    suppression-policy findings (R0). ``parse_times`` (rel -> seconds)
    feeds the ``--json`` timing block when provided."""
    import time as _time

    proj = Project(root)
    findings: List[Finding] = []
    for path in iter_py_files(paths):
        t0 = _time.perf_counter()
        try:
            sf = SourceFile(root, path)
        except SyntaxError as e:
            rel = os.path.relpath(path, root).replace(os.sep, "/")
            findings.append(Finding(
                "R0", rel, int(e.lineno or 1),
                f"file does not parse: {e.msg}"))
            continue
        if parse_times is not None:
            parse_times[sf.rel] = _time.perf_counter() - t0
        proj.add_file(sf)
        for s in sf.bad_suppressions:
            findings.append(Finding(
                "R0", sf.rel, s.line,
                f"suppression for {s.rule} carries no reason — "
                f"write `# tpu-lint: disable={s.rule}(why this is safe)`; "
                f"the bare disable is NOT honored",
                snippet=sf.snippet(s.line)))
    return proj, findings
