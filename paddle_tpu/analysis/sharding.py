"""R8: mesh-axis & sharding discipline.

GSPMD sharding is stringly-typed: a ``PartitionSpec("modle")`` typo, a
``shard_map`` whose ``in_specs`` doesn't match the wrapped signature, or
a resize path that quietly rewrites a frozen program axis all pass every
unit test that doesn't run on the exact failing topology. This rule
family checks the contracts statically:

- **undeclared axis**: every string axis inside a
  ``PartitionSpec``/``P(...)`` call must be an axis some mesh in the
  project actually declares (``init_mesh({...})`` /
  ``plan_mesh_shape({...})`` dict keys, ``Mesh(devs, ("a", ...))`` /
  ``axis_names=`` tuples), or one of the framework's reserved axis
  vocabulary (``dp``/``sdp``/``mp``/``sp``/``ep``/``pp`` — the
  ``elastic_mesh`` contract). A spec naming an axis no mesh carries is
  silently replicated — the worst kind of perf bug;
- **frozen-axis resize**: ``mp``/``sp``/``ep``/``pp`` partition the
  *program* — ``plan_mesh_shape`` freezes them across elastic resizes.
  A function that builds a mesh AND assigns a non-constant size to a
  frozen axis key (``axes["mp"] = n // 4``) is re-deriving a program
  axis from capacity — exactly the invariant violation the elastic
  shrink/grow path must never make;
- **shard_map arity**: a tuple-literal ``in_specs`` must have one spec
  per wrapped-function parameter, and a tuple-literal ``out_specs`` one
  spec per returned element (checked when every ``return`` is a literal
  tuple of consistent length). Mismatches raise at trace time — on the
  8-device suite, not the laptop;
- **donated-input resharding**: applying ``with_sharding_constraint`` /
  ``device_put`` to a parameter that the jit wrap site donates forces a
  copy of a buffer the caller just gave away — the donation saves
  nothing and the "in-place" update silently doubles peak memory.

Pure AST; axis declarations are collected project-wide in one pass.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, dotted_path
from .model import Finding, FunctionInfo, Project

__all__ = ["analyze_sharding", "RESERVED_AXES"]

# the framework's reserved mesh-axis vocabulary (elastic_mesh.FROZEN_AXES
# + the data axes it rescales) — always considered declared
RESERVED_AXES = ("dp", "sdp", "mp", "sp", "ep", "pp")
FROZEN_AXES = ("mp", "sp", "ep", "pp")

_MESH_BUILDERS = {"init_mesh", "plan_mesh_shape", "reshaped_mesh", "Mesh"}
_MESH_BUILDER_KWARGS_SKIP = {"devices", "shape", "frozen", "default_axes",
                             "checkpoint_dir"}
_SPEC_NAMES = {"PartitionSpec"}
_RESHARD_CALLS = {"with_sharding_constraint", "device_put"}


def _call_tail(node: ast.Call) -> Optional[str]:
    path = dotted_path(node.func)
    return path[-1] if path else None


def _is_spec_call(fi: FunctionInfo, node: ast.Call) -> bool:
    """``PartitionSpec(...)`` under any import form, including
    ``from jax.sharding import PartitionSpec as P``."""
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr in _SPEC_NAMES
    if isinstance(f, ast.Name):
        if f.id in _SPEC_NAMES:
            return True
        alias = fi.file.aliases.get(f.id)
        return bool(alias and alias[0] == "symbol"
                    and alias[2] in _SPEC_NAMES)
    return False


def _string_consts(node: ast.AST) -> List[Tuple[str, int]]:
    out = []
    for sub in ast.walk(node):
        if isinstance(sub, ast.Constant) and isinstance(sub.value, str):
            out.append((sub.value, getattr(sub, "lineno", 0)))
    return out


def _own_walk(fi: FunctionInfo):
    """Every node of ``fi`` excluding nested function subtrees (those
    are their own FunctionInfo — walking them twice would double every
    finding)."""
    stack: List[ast.AST] = list(ast.iter_child_nodes(fi.node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


@dataclass
class _Sites:
    """One-pass collection of every R8-relevant node in a function."""

    spec_calls: List[ast.Call] = None
    mesh_calls: List[ast.Call] = None
    shard_maps: List[ast.Call] = None
    frozen_stores: List[ast.Assign] = None

    def __post_init__(self):
        self.spec_calls = []
        self.mesh_calls = []
        self.shard_maps = []
        self.frozen_stores = []


def _collect_sites(fi: FunctionInfo) -> _Sites:
    s = _Sites()
    for node in _own_walk(fi):
        if isinstance(node, ast.Call):
            tail = _call_tail(node)
            if tail in _MESH_BUILDERS:
                s.mesh_calls.append(node)
            elif tail == "shard_map" and node.args:
                s.shard_maps.append(node)
            if _is_spec_call(fi, node):
                s.spec_calls.append(node)
        elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                and isinstance(node.targets[0], ast.Subscript):
            key = node.targets[0].slice
            if isinstance(key, ast.Constant) \
                    and isinstance(key.value, str) \
                    and key.value in FROZEN_AXES \
                    and not isinstance(node.value, ast.Constant):
                s.frozen_stores.append(node)
    return s


def _declared_axes_from(sites: _Sites) -> Set[str]:
    axes: Set[str] = set()
    for node in sites.mesh_calls:
        tail = _call_tail(node)
        # dict-literal shapes: keys are axis names
        cands: List[ast.AST] = list(node.args[:1])
        for kw in node.keywords:
            if kw.arg in ("shape", "default_axes", "saved_axes"):
                cands.append(kw.value)
            elif kw.arg == "axis_names":
                axes.update(s for s, _ in _string_consts(kw.value))
            elif kw.arg is not None \
                    and kw.arg not in _MESH_BUILDER_KWARGS_SKIP:
                # init_mesh(dp=2, mp=4) keyword form
                axes.add(kw.arg)
        for c in cands:
            if isinstance(c, ast.Dict):
                for k in c.keys:
                    if isinstance(k, ast.Constant) \
                            and isinstance(k.value, str):
                        axes.add(k.value)
        # Mesh(devs, ("dp", "mp")) positional axis names
        if tail == "Mesh" and len(node.args) >= 2:
            axes.update(s for s, _ in _string_consts(node.args[1]))
    return axes


def _finding(fi: FunctionInfo, line: int, msg: str, hint: str) -> Finding:
    return Finding("R8", fi.file.rel, line, msg, symbol=fi.short,
                   snippet=fi.file.snippet(line), hint=hint,
                   chain=fi.trace_chain if fi.trace_reachable else ())


def _check_specs(fi: FunctionInfo, sites: _Sites, declared: Set[str],
                 out: List[Finding]) -> None:
    for node in sites.spec_calls:
        exprs = list(node.args) + [kw.value for kw in node.keywords]
        for e in exprs:
            for s, line in _string_consts(e):
                if s not in declared:
                    out.append(_finding(
                        fi, line or node.lineno,
                        f"PartitionSpec names axis {s!r} that no "
                        f"mesh in the project declares — the "
                        f"dimension silently replicates (or the "
                        f"spec raises on a real mesh)",
                        hint=f"declare the axis in the mesh "
                             f"shape, or use one of "
                             f"{sorted(declared)[:8]}..."))


def _check_frozen_mutation(fi: FunctionInfo, sites: _Sites,
                           out: List[Finding]) -> None:
    if not sites.mesh_calls:
        return
    for node in sites.frozen_stores:
        key = node.targets[0].slice
        out.append(_finding(
            fi, node.lineno,
            f"frozen program axis {key.value!r} resized from a "
            f"computed value on a mesh-building path — "
            f"`plan_mesh_shape` freezes {FROZEN_AXES} across elastic "
            f"resizes (resizing them changes the partitioned "
            f"program, not the data layout)",
            hint="let plan_mesh_shape rescale the data axes "
                 "(dp/sdp) instead; a frozen-axis change is a "
                 "retrain-time decision, not a resize"))


def _wrapped_arity(project: Project, cg: CallGraph, fi: FunctionInfo,
                   expr: ast.AST) -> Optional[Tuple[int, int]]:
    """(required, total) POSITIONAL arity of the wrapped function —
    keyword-only params never receive an in_spec, and defaulted params
    are optional, so a spec count anywhere in the range is legal."""
    a = None
    if isinstance(expr, ast.Lambda):
        a = expr.args
    else:
        target = None
        if isinstance(expr, (ast.Name, ast.Attribute)):
            target = cg._target_function(fi, expr)
        if target is None:
            return None
        a = target.node.args
    if a.vararg or a.kwarg:
        return None
    pos = [p.arg for p in a.posonlyargs + a.args
           if p.arg not in ("self", "cls")]
    total = len(pos)
    required = total - len(a.defaults)
    return max(0, required), total


def _return_arities(target: FunctionInfo) -> Optional[int]:
    """Consistent literal-tuple return length, else None. Nested
    function subtrees are PRUNED (ast.walk + continue would skip only
    the def node, not its returns — a closure's `return a, b` must not
    masquerade as the wrapped function's)."""
    lens: Set[int] = set()
    for node in _own_walk(target):
        if isinstance(node, ast.Return) and node.value is not None:
            if isinstance(node.value, ast.Tuple):
                lens.add(len(node.value.elts))
            else:
                return None
    if len(lens) == 1:
        return lens.pop()
    return None


def _check_shard_map(fi: FunctionInfo, sites: _Sites, project: Project,
                     cg: CallGraph, out: List[Finding]) -> None:
    for node in sites.shard_maps:
        in_specs = next((kw.value for kw in node.keywords
                         if kw.arg == "in_specs"), None)
        out_specs = next((kw.value for kw in node.keywords
                          if kw.arg == "out_specs"), None)
        wrapped = node.args[0]
        arity = _wrapped_arity(project, cg, fi, wrapped)
        if arity is not None and isinstance(in_specs,
                                            (ast.Tuple, ast.List)):
            required, total = arity
            n = len(in_specs.elts)
            if not (required <= n <= total):
                want = (str(total) if required == total
                        else f"{required}..{total}")
                out.append(_finding(
                    fi, node.lineno,
                    f"shard_map in_specs has {n} spec(s) but the "
                    f"wrapped function takes {want} positional "
                    f"argument(s) — this raises at trace time on a "
                    f"real mesh",
                    hint="one PartitionSpec per wrapped positional "
                         "parameter, in order"))
        target = None
        if isinstance(wrapped, (ast.Name, ast.Attribute)):
            target = cg._target_function(fi, wrapped)
        if target is not None and isinstance(out_specs,
                                             (ast.Tuple, ast.List)):
            rets = _return_arities(target)
            if rets is not None and rets != len(out_specs.elts):
                out.append(_finding(
                    fi, node.lineno,
                    f"shard_map out_specs has {len(out_specs.elts)} "
                    f"spec(s) but `{target.short}` returns {rets} "
                    f"element(s)",
                    hint="one PartitionSpec per returned element"))


def _check_donated_reshard(project: Project, cg: CallGraph,
                           out: List[Finding]) -> None:
    for root, info in cg.trace_roots:
        if not info.donate:
            continue
        params = [p for p in root.params if p not in ("self", "cls")]
        donated = {params[i] for i in info.donate if 0 <= i < len(params)}
        if not donated:
            continue
        for node in ast.walk(root.node):
            if not isinstance(node, ast.Call):
                continue
            path = dotted_path(node.func)
            if not path or path[-1] not in _RESHARD_CALLS:
                continue
            if node.args and isinstance(node.args[0], ast.Name) \
                    and node.args[0].id in donated:
                out.append(_finding(
                    root, node.lineno,
                    f"`{path[-1]}` resharding `{node.args[0].id}`, which "
                    f"is DONATED at the wrap site ({info.site}) — the "
                    f"reshard copies a buffer the caller gave away "
                    f"(donation saves nothing, peak memory doubles)",
                    hint="reshard at the call boundary before donating, "
                         "or drop the argument from donate_argnums"))


def analyze_sharding(project: Project, cg: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    per_fi = [(fi, _collect_sites(fi))
              for fi in project.functions.values()]
    declared: Set[str] = set(RESERVED_AXES)
    for _, sites in per_fi:
        declared |= _declared_axes_from(sites)
    for fi, sites in per_fi:
        _check_specs(fi, sites, declared, out)
        _check_frozen_mutation(fi, sites, out)
        _check_shard_map(fi, sites, project, cg, out)
    _check_donated_reshard(project, cg, out)
    return out
