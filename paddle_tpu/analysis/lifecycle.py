"""R9: exception-flow resource-lifecycle (acquire/release leak) analysis.

The serving stack runs on ref-counted *protocols*: a ``BlockPool.lookup``
pins prefix-cache blocks until ``commit``/``abort``, an
``AdapterStore.acquire`` pins a device page row until ``release``, a
checkpoint publish stages a ``.tmp`` sibling that must reach
``os.replace``. Every one of them is an invariant the runtime can only
see when it is already violated (a pinned block that never unpins makes
the pool unevictable; a leaked adapter pin wedges tenant eviction
forever). This rule family checks the pairing *statically*, on every
path — including the raise paths ``try``/``except`` carve out:

- an acquired resource must reach a paired release (or be returned to
  the caller — ownership transfer — or stored/escaped into longer-lived
  state) on every normal exit;
- a call that can raise while the resource is held must sit inside a
  ``try`` whose handler or ``finally`` releases it (``abort``-in-except
  IS a release — the engine's admission discipline);
- an acquire whose result is discarded leaks immediately.

Acquirers are discovered one interprocedural hop deep (like R6): a
helper that acquires and *returns* the resource transfers ownership, so
its callers are treated as acquiring at the call site — this is exactly
how ``engine._plan_hit`` hands its pinned :class:`PrefixHit` to
``admit``.

Receiver typing is deliberately conservative: a method name like
``acquire`` only matches when the receiver resolves to a protocol class
(constructor scan, ``__init__`` parameter annotations, or a helper's
return annotation — ``self.pool = self._normalize_pool(...) ->
Optional[BlockPool]``) or carries a protocol receiver-name hint
(``self.pool`` / ``self.store``). ``threading.Lock.acquire`` never
matches (lock attrs are excluded), and passing a resource to an
unresolved call is an *escape*, not a leak — unknown callees may release
on the caller's behalf.

The full protocol graph — per-function acquire and release sites — is
exported in ``--json`` as ``lifecycle_graph`` alongside ``lock_graph``.
Pure AST like every other rule: no jax import, nothing is executed.
"""
from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, dotted_path
from .model import ClassInfo, Finding, FunctionInfo, Project

__all__ = ["LifecycleAnalysis", "analyze_lifecycle", "PROTOCOLS"]


@dataclass(frozen=True)
class Protocol:
    """One acquire/release pairing the analyzer enforces."""

    name: str
    acquire: frozenset            # method names that acquire
    release: frozenset            # method names that release
    neutral: frozenset = frozenset()   # protocol plumbing: keeps holding
    classes: frozenset = frozenset()   # owning class names (receiver type)
    hints: frozenset = frozenset()     # receiver attr/var name fallbacks
    raise_paths: bool = True      # also check exception edges
    what: str = "resource"
    fix: str = "pair the acquire with its release on every path"


PROTOCOLS: Tuple[Protocol, ...] = (
    Protocol(
        name="block-pin",
        acquire=frozenset({"lookup"}),
        release=frozenset({"commit", "abort"}),
        neutral=frozenset({"trim", "plan_store", "match",
                           "match_digests"}),
        classes=frozenset({"BlockPool"}),
        hints=frozenset({"pool", "prefix_cache", "block_pool"}),
        what="pinned prefix-cache blocks",
        fix="commit() on success, abort() on EVERY failure path (an "
            "abort in the except handler counts) — a leaked pin makes "
            "the block unevictable forever"),
    Protocol(
        name="adapter-pin",
        acquire=frozenset({"acquire"}),
        release=frozenset({"release", "release_all"}),
        classes=frozenset({"AdapterStore"}),
        hints=frozenset({"store", "adapter_store", "adapters"}),
        what="pinned adapter page row",
        fix="release() the row on every path that does not hand it to "
            "a live slot — a leaked pin blocks tenant eviction"),
    Protocol(
        name="pin",
        acquire=frozenset({"pin"}),
        release=frozenset({"unpin"}),
        what="pinned entry",
        fix="unpin on every path, including the raise paths"),
)

# staged-file protocol: `tmp = f"{path}.tmp..."` must reach os.replace
# (publish) or a cleanup on every NORMAL exit. Raise paths are exempt by
# design: the checkpoint layer is crash-safe precisely because a SIGKILL
# leaves only the staging file, which orphan sweeps reap.
_STAGED_RELEASE = {"replace", "rename", "remove", "unlink", "rmtree"}
_STAGED_PROTO = Protocol(
    name="staged-file",
    acquire=frozenset(), release=frozenset(_STAGED_RELEASE),
    raise_paths=False,
    what="staged .tmp file",
    fix="publish with os.replace (tmp, final) or clean it up before "
        "returning — a staged file that never publishes is a silent "
        "lost write")


@dataclass
class _Resource:
    proto: Protocol
    names: Set[str]
    receiver: str                 # dotted repr of the receiver ("" unknown)
    line: int
    chain: Tuple[str, ...] = ()
    reported: bool = False        # one raise-path finding per resource
    maybe: bool = False           # held on only some merged branches


@dataclass
class _TryGuard:
    """Release capability of an enclosing try. ``exc_*`` = released on
    the exception path (handlers OR finally); ``fin_*`` = released on
    EVERY path out (finally only) — a `return` inside the try is
    covered only by the latter."""

    exc_protocols: Set[str]
    exc_names: Set[str]
    exc_receivers: Set[str]
    fin_protocols: Set[str]
    fin_names: Set[str]
    fin_receivers: Set[str]


class LifecycleAnalysis:
    def __init__(self, project: Project, cg: CallGraph):
        self.project = project
        self.cg = cg
        self.findings: List[Finding] = []
        self.acquires: List[dict] = []
        self.releases: List[dict] = []
        # qualname -> Protocol for helpers that acquire-and-return
        self._transfer_fns: Dict[str, Protocol] = {}
        self._local_maps: Dict[str, Dict[str, ast.AST]] = {}

    # ------------------------------------------------------------ build
    def run(self) -> "LifecycleAnalysis":
        self._scan_transfer_helpers()
        for fi in self.project.functions.values():
            _Scanner(self, fi).run()
        return self

    # -------------------------------------------------- receiver typing
    def _local_map(self, fi: FunctionInfo) -> Dict[str, ast.AST]:
        got = self._local_maps.get(fi.qualname)
        if got is None:
            got = self._local_maps[fi.qualname] = \
                self.cg._local_assign_map(fi)
        return got

    @staticmethod
    def _annot_classes(node: Optional[ast.AST]) -> Set[str]:
        """Class names inside a return/param annotation —
        ``Optional[BlockPool]`` / ``"BlockPool"`` / ``BlockPool``."""
        out: Set[str] = set()
        if node is None:
            return out
        for sub in ast.walk(node):
            if isinstance(sub, ast.Name):
                out.add(sub.id)
            elif isinstance(sub, ast.Attribute):
                out.add(sub.attr)
            elif isinstance(sub, ast.Constant) \
                    and isinstance(sub.value, str):
                out.add(sub.value.split(".")[-1].split("[")[0])
        return out

    def _self_attr_class(self, cls: ClassInfo, attr: str) -> Optional[str]:
        """Best-effort class name of ``self.<attr>``: the constructor
        scan, then ``self.X = self._helper(...)`` return annotations,
        then ``self.X = <param>`` with an annotated ``__init__`` param."""
        got = cls.attr_types.get(attr)
        if got is not None:
            return got
        for m in cls.methods.values():
            for node in ast.walk(m.node):
                if not (isinstance(node, ast.Assign)
                        and len(node.targets) == 1):
                    continue
                t = node.targets[0]
                if not (isinstance(t, ast.Attribute) and t.attr == attr
                        and isinstance(t.value, ast.Name)
                        and t.value.id == "self"):
                    continue
                v = node.value
                if isinstance(v, ast.Call) \
                        and isinstance(v.func, ast.Attribute) \
                        and isinstance(v.func.value, ast.Name) \
                        and v.func.value.id == "self":
                    helper = cls.methods.get(v.func.attr)
                    if helper is not None:
                        for cname in self._annot_classes(
                                getattr(helper.node, "returns", None)):
                            if cname in self.project.classes_by_name:
                                return cname
                elif isinstance(v, ast.Name):
                    for arg in (m.node.args.posonlyargs + m.node.args.args
                                + m.node.args.kwonlyargs):
                        if arg.arg == v.id:
                            for cname in self._annot_classes(
                                    arg.annotation):
                                if cname in self.project.classes_by_name:
                                    return cname
        return None

    def _receiver_info(self, fi: FunctionInfo,
                       base: ast.AST) -> Tuple[Optional[str], str]:
        """(class name or None, dotted receiver repr) for ``base`` in
        ``base.method(...)``."""
        path = dotted_path(base)
        repr_ = ".".join(path) if path else ""
        if isinstance(base, ast.Attribute) \
                and isinstance(base.value, ast.Name) \
                and base.value.id == "self" and fi.cls is not None:
            if base.attr in fi.cls.lock_attrs:
                return ("__lock__", repr_)
            return (self._self_attr_class(fi.cls, base.attr), repr_)
        if isinstance(base, ast.Name):
            val = self._local_map(fi).get(base.id)
            if isinstance(val, ast.Call):
                cname = None
                if isinstance(val.func, ast.Name):
                    cname = val.func.id
                elif isinstance(val.func, ast.Attribute):
                    cname = val.func.attr
                if cname and cname in self.project.classes_by_name:
                    return (cname, repr_)
            # annotated parameter: def admit(pool: BlockPool)
            for arg in (fi.node.args.posonlyargs + fi.node.args.args
                        + fi.node.args.kwonlyargs):
                if arg.arg == base.id:
                    for cname in self._annot_classes(arg.annotation):
                        if cname in self.project.classes_by_name:
                            return (cname, repr_)
        return (None, repr_)

    def _match_protocol(self, fi: FunctionInfo, call: ast.Call,
                        method_sets: str) -> Optional[Tuple[Protocol, str]]:
        """(protocol, receiver repr) when ``call`` is a protocol method
        of kind ``method_sets`` ("acquire" | "release" | "neutral")."""
        f = call.func
        if not isinstance(f, ast.Attribute):
            return None
        for proto in PROTOCOLS:
            if f.attr not in getattr(proto, method_sets):
                continue
            cname, repr_ = self._receiver_info(fi, f.value)
            if cname == "__lock__":
                continue
            if cname is not None:
                if proto.classes and cname in proto.classes:
                    return (proto, repr_)
                if not proto.classes:
                    return (proto, repr_)
                continue    # typed to a different class: not this proto
            # untyped receiver: the name-hint fallback
            tail = repr_.split(".")[-1] if repr_ else ""
            if tail in proto.hints or (not proto.classes
                                       and not proto.hints):
                return (proto, repr_)
        return None

    # ------------------------------------------- one-hop acquire helpers
    def _scan_transfer_helpers(self) -> None:
        """A function that acquires a protocol resource and *returns* it
        transfers ownership — its callers acquire at the call site."""
        for fi in self.project.functions.values():
            bound: Dict[str, Protocol] = {}
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    got = self._match_protocol(fi, node.value, "acquire")
                    if got is None:
                        continue
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                bound[n.id] = got[0]
            if not bound:
                continue
            for node in ast.walk(fi.node):
                if isinstance(node, ast.Return) and node.value is not None:
                    for n in ast.walk(node.value):
                        if isinstance(n, ast.Name) and n.id in bound:
                            self._transfer_fns[fi.qualname] = bound[n.id]
                            break

    def transfer_protocol(self, fi: FunctionInfo,
                          call: ast.Call) -> Optional[Protocol]:
        for callee in self.cg.resolve_call(fi, call):
            proto = self._transfer_fns.get(callee.qualname)
            if proto is not None:
                return proto
        return None

    # ------------------------------------------------------------ export
    def lifecycle_graph(self) -> dict:
        return {
            "protocols": [{
                "name": p.name, "classes": sorted(p.classes),
                "acquire": sorted(p.acquire),
                "release": sorted(p.release)}
                for p in PROTOCOLS + (_STAGED_PROTO,)],
            "acquires": sorted(self.acquires, key=lambda a: (
                a["file"], a["line"], a["protocol"])),
            "releases": sorted(self.releases, key=lambda a: (
                a["file"], a["line"], a["protocol"])),
        }


# calls that can never meaningfully raise mid-protocol (builtins, numpy
# constructors, clock reads) — risky-call analysis skips them so correct
# code like `t0 = time.time()` between acquire and try stays clean
_SAFE_TAILS = {
    "len", "int", "float", "bool", "str", "repr", "min", "max", "abs",
    "sum", "any", "all", "round", "sorted", "list", "dict", "tuple",
    "set", "frozenset", "range", "enumerate", "zip", "isinstance",
    "hasattr", "getattr", "format", "print", "id", "time", "monotonic",
    "perf_counter", "asarray", "array", "zeros", "ones", "append",
    "items", "keys", "values", "get", "setdefault", "pop", "update",
    "copy", "join", "split", "strip", "encode", "decode", "ravel",
    "device_get", "int32", "float32", "bool_", "uint32",
}


class _Scanner:
    """Path-aware acquire/release scan of one function (modeled on the
    R4 scanner: branch states fork and merge, loops run two symbolic
    iterations, try handlers grant exception protection)."""

    def __init__(self, an: LifecycleAnalysis, fi: FunctionInfo):
        self.an = an
        self.fi = fi
        self._serial = 0
        self._emitted: Set[Tuple[int, str]] = set()

    def run(self) -> None:
        state: Dict[int, _Resource] = {}
        fell_through = self._scan(self.fi.node.body, state, guards=[])
        if fell_through:
            for res in state.values():
                self._leak(res, getattr(self.fi.node, "end_lineno",
                                        self.fi.node.lineno),
                           "function exits")

    # ------------------------------------------------------------ emit
    def _emit(self, line: int, msg: str, res: _Resource) -> None:
        key = (line, res.proto.name)
        if key in self._emitted:
            return
        self._emitted.add(key)
        chain = res.chain or (
            f"{self.fi.short} [acquires {res.proto.what} @ "
            f"{self.fi.file.rel}:{res.line}]",)
        self.an.findings.append(Finding(
            "R9", self.fi.file.rel, line, msg, symbol=self.fi.short,
            snippet=self.fi.file.snippet(line), chain=chain,
            hint=res.proto.fix))

    def _leak(self, res: _Resource, line: int, how: str) -> None:
        maybe = " on some branch paths" if res.maybe else ""
        names = "/".join(sorted(res.names)) or "<discarded>"
        self._emit(line, f"{how} while `{names}` still holds "
                         f"{res.proto.what} acquired at line {res.line}"
                         f"{maybe} — the release is unreachable from "
                         f"here", res)

    # ------------------------------------------------------ call logic
    def _call_names(self, call: ast.Call) -> Set[str]:
        out: Set[str] = set()
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(a):
                if isinstance(n, ast.Name):
                    out.add(n.id)
        return out

    def _risky(self, call: ast.Call) -> bool:
        """Can this call raise in a way the protocol must survive?
        Project functions and unresolved self/instance-attribute calls
        are risky; builtins/numpy/clock reads are not."""
        f = call.func
        path = dotted_path(f)
        if path and path[-1] in _SAFE_TAILS:
            return False
        if self.an.cg.resolve_call(self.fi, call):
            return True
        if isinstance(f, ast.Attribute):
            base = f.value
            if isinstance(base, ast.Name) and base.id == "self":
                return True
            if isinstance(base, ast.Attribute) \
                    and isinstance(base.value, ast.Name) \
                    and base.value.id == "self":
                return True
        return False

    def _protected(self, res: _Resource, guards: List[_TryGuard],
                   on_exit: bool = False) -> bool:
        """Is ``res`` released by an enclosing try — on the exception
        path (default), or on EVERY exit (``on_exit``: finally-only,
        the coverage a `return` inside the try needs)?"""
        for g in guards:
            protos = g.fin_protocols if on_exit else g.exc_protocols
            names = g.fin_names if on_exit else g.exc_names
            recvs = g.fin_receivers if on_exit else g.exc_receivers
            if res.proto.name not in protos:
                continue
            if res.names & names:
                return True
            if res.receiver and res.receiver in recvs:
                return True
            if not res.names:      # discarded-result resources
                return True
        return False

    def _handle_call(self, call: ast.Call, state: Dict[int, _Resource],
                     guards: List[_TryGuard]) -> None:
        an = self.an
        got = an._match_protocol(self.fi, call, "release")
        arg_names = self._call_names(call)
        if got is not None:
            proto, recv = got
            an.releases.append({
                "protocol": proto.name, "function": self.fi.short,
                "file": self.fi.file.rel, "line": call.lineno,
                "method": call.func.attr})
            for rid, res in list(state.items()):
                if res.proto.name != proto.name:
                    continue
                if (res.names & arg_names) or res.receiver == recv \
                        or not res.names:
                    del state[rid]
            return
        if an._match_protocol(self.fi, call, "neutral") is not None:
            return
        # escape: an unresolved/any call that RECEIVES the resource may
        # release it downstream — stop tracking, never flag
        escaped = [rid for rid, res in state.items()
                   if res.names & arg_names]
        for rid in escaped:
            del state[rid]
        # risky call while holding: the exception edge leaks unless an
        # enclosing try releases
        if not state or not self._risky(call):
            return
        for res in state.values():
            if not res.proto.raise_paths or res.reported:
                continue
            if self._protected(res, guards):
                continue
            res.reported = True
            names = "/".join(sorted(res.names)) or "<resource>"
            self._emit(call.lineno,
                       f"call can raise while `{names}` holds "
                       f"{res.proto.what} acquired at line {res.line} "
                       f"and no enclosing try releases it — the "
                       f"exception path leaks the {res.proto.what}",
                       res)

    def _bind(self, targets: Sequence[ast.AST], proto: Protocol,
              call: ast.Call, state: Dict[int, _Resource],
              recv: str, via: str = "") -> None:
        names: Set[str] = set()
        escaped = False
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    names.add(n.id)
                elif isinstance(n, (ast.Attribute, ast.Subscript)):
                    escaped = True
        self.an.acquires.append({
            "protocol": proto.name, "function": self.fi.short,
            "file": self.fi.file.rel, "line": call.lineno,
            "names": sorted(names), "via": via})
        if escaped and not names:
            return      # stored straight into longer-lived state
        self._serial += 1
        chain: Tuple[str, ...] = ()
        if via:
            chain = (f"{via} [acquires {proto.what}]",
                     f"{self.fi.short} @ {self.fi.file.rel}:{call.lineno}")
        res = _Resource(proto, names, recv, call.lineno, chain=chain)
        if not names:
            self._leak(res, call.lineno, "acquire result is discarded")
            return
        state[self._serial] = res

    # --------------------------------------------------------- staged
    def _staged_acquire(self, stmt: ast.Assign,
                        state: Dict[int, _Resource]) -> bool:
        """``tmp = <path-building expr with a ".tmp" component>`` starts
        the staged-file protocol for the bound name. Only PATH-BUILDING
        forms register (f-strings, string concat/%%-format): a
        conditional (``x if atomic else path``) or an arbitrary call
        whose source merely mentions ".tmp" is not a staging site."""
        if len(stmt.targets) != 1 \
                or not isinstance(stmt.targets[0], ast.Name):
            return False
        if not isinstance(stmt.value, (ast.JoinedStr, ast.BinOp)):
            return False
        has_tmp = any(
            isinstance(n, ast.Constant) and isinstance(n.value, str)
            and ".tmp" in n.value for n in ast.walk(stmt.value))
        if not has_tmp:
            return False
        name = stmt.targets[0].id
        self._serial += 1
        state[self._serial] = _Resource(
            _STAGED_PROTO, {name}, "", stmt.lineno)
        self.an.acquires.append({
            "protocol": "staged-file", "function": self.fi.short,
            "file": self.fi.file.rel, "line": stmt.lineno,
            "names": [name], "via": ""})
        return True

    def _staged_release(self, call: ast.Call,
                        state: Dict[int, _Resource]) -> None:
        path = dotted_path(call.func)
        if not path or path[-1] not in _STAGED_RELEASE:
            return
        arg_names = self._call_names(call)
        for rid, res in list(state.items()):
            if res.proto.name == "staged-file" and res.names & arg_names:
                self.an.releases.append({
                    "protocol": "staged-file", "function": self.fi.short,
                    "file": self.fi.file.rel, "line": call.lineno,
                    "method": path[-1]})
                del state[rid]

    def _staged_escape(self, call: ast.Call,
                       state: Dict[int, _Resource]) -> None:
        """Passing the staged path to a PROJECT call escapes it (the
        helper may publish); ``open``/``fsync`` do not."""
        if not self.an.cg.resolve_call(self.fi, call):
            return
        arg_names = self._call_names(call)
        for rid, res in list(state.items()):
            if res.proto.name == "staged-file" and res.names & arg_names:
                del state[rid]

    # ----------------------------------------------------------- scan
    def _split_staged(self, state: Dict[int, _Resource]):
        staged = {k: v for k, v in state.items()
                  if v.proto.name == "staged-file"}
        live = {k: v for k, v in state.items() if k not in staged}
        return live, staged

    def _process(self, expr: Optional[ast.AST],
                 state: Dict[int, _Resource],
                 guards: List[_TryGuard]) -> None:
        """Run release/escape/risky logic for every call in ``expr``,
        keeping the staged-file protocol's gentler escape rules."""
        if expr is None:
            return
        live, staged = self._split_staged(state)
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            self._handle_call(node, live, guards)
            self._staged_release(node, staged)
            self._staged_escape(node, staged)
        state.clear()
        state.update(live)
        state.update(staged)

    def _try_guard(self, stmt: ast.Try) -> _TryGuard:
        g = _TryGuard(set(), set(), set(), set(), set(), set())
        blocks = [(h.body, False) for h in stmt.handlers]
        blocks.append((stmt.finalbody, True))
        for block, is_final in blocks:
            for s in block:
                for node in ast.walk(s):
                    if not isinstance(node, ast.Call):
                        continue
                    got = self.an._match_protocol(self.fi, node, "release")
                    if got is None:
                        continue
                    g.exc_protocols.add(got[0].name)
                    g.exc_receivers.add(got[1])
                    g.exc_names |= self._call_names(node)
                    if is_final:
                        g.fin_protocols.add(got[0].name)
                        g.fin_receivers.add(got[1])
                        g.fin_names |= self._call_names(node)
        return g

    def _rebind(self, targets: Sequence[ast.AST],
                state: Dict[int, _Resource]) -> None:
        plain = set()
        for t in targets:
            if isinstance(t, ast.Name):
                plain.add(t.id)
            elif isinstance(t, (ast.Tuple, ast.List)):
                for e in t.elts:
                    if isinstance(e, ast.Name):
                        plain.add(e.id)
        if not plain:
            return
        for rid, res in list(state.items()):
            lost = res.names & plain
            if not lost:
                continue
            res.names -= lost
            if not res.names:
                del state[rid]
                res.names = lost      # report the name it leaked under
                self._leak(res, min(t.lineno for t in targets),
                           "name is rebound")

    def _scan(self, stmts: Sequence[ast.stmt],
              state: Dict[int, _Resource],
              guards: List[_TryGuard]) -> bool:
        """Returns False when the block terminates (return/raise/...)."""
        an = self.an
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, ast.Return):
                transferred: Set[str] = set()
                if s.value is not None:
                    for n in ast.walk(s.value):
                        if isinstance(n, ast.Name):
                            transferred.add(n.id)
                    self._process(s.value, state, guards)
                for rid, res in list(state.items()):
                    if res.names & transferred:
                        # ownership transfer to the caller — and OUT of
                        # this scan's state, so a loop's second symbolic
                        # iteration doesn't resurrect it as a leak
                        del state[rid]
                        continue
                    if self._protected(res, guards, on_exit=True):
                        continue    # an enclosing finally releases it
                    self._leak(res, s.lineno, "returns")
                return False
            if isinstance(s, ast.Raise):
                self._process(s.exc, state, guards)
                for res in state.values():
                    if not res.proto.raise_paths:
                        continue
                    if self._protected(res, guards):
                        continue
                    self._leak(res, s.lineno, "raises")
                return False
            if isinstance(s, (ast.Break, ast.Continue)):
                return False
            if isinstance(s, ast.Assign):
                handled = False
                if isinstance(s.value, ast.Call):
                    # neutral protocol call returning the SAME resource
                    # (`hit = pool.trim(hit, n)`): the rebind continues
                    # the hold, it neither releases nor leaks
                    neut = an._match_protocol(self.fi, s.value, "neutral")
                    if neut is not None:
                        args = self._call_names(s.value)
                        for res in state.values():
                            if res.proto.name == neut[0].name \
                                    and res.names & args:
                                for t in s.targets:
                                    for n in ast.walk(t):
                                        if isinstance(n, ast.Name):
                                            res.names.add(n.id)
                                handled = True
                        if handled:
                            continue
                    got = an._match_protocol(self.fi, s.value, "acquire")
                    via = ""
                    if got is None:
                        proto = an.transfer_protocol(self.fi, s.value)
                        if proto is not None:
                            got = (proto, "")
                            via = ast.unparse(s.value.func) \
                                if hasattr(ast, "unparse") else "helper"
                    if got is not None:
                        # args of the acquire itself still release/escape
                        for sub in ast.walk(s.value):
                            if isinstance(sub, ast.Call) \
                                    and sub is not s.value:
                                self._handle_call(sub, state, guards)
                        self._rebind(s.targets, state)
                        self._bind(s.targets, got[0], s.value, state,
                                   got[1], via=via)
                        handled = True
                if not handled and self._staged_acquire(s, state):
                    handled = True
                if not handled:
                    self._process(s.value, state, guards)
                    self._rebind(s.targets, state)
            elif isinstance(s, ast.AugAssign):
                self._process(s.value, state, guards)
            elif isinstance(s, ast.AnnAssign):
                if s.value is not None:
                    self._process(s.value, state, guards)
                    self._rebind([s.target], state)
            elif isinstance(s, ast.Expr):
                if isinstance(s.value, ast.Call):
                    got = an._match_protocol(self.fi, s.value, "acquire")
                    if got is not None:
                        self._bind([], got[0], s.value, state, got[1])
                        continue
                self._process(s.value, state, guards)
            elif isinstance(s, ast.If):
                self._process(s.test, state, guards)
                s1 = {k: _Resource(v.proto, set(v.names), v.receiver,
                                   v.line, v.chain, v.reported, v.maybe)
                      for k, v in state.items()}
                s2 = {k: _Resource(v.proto, set(v.names), v.receiver,
                                   v.line, v.chain, v.reported, v.maybe)
                      for k, v in state.items()}
                f1 = self._scan(s.body, s1, guards)
                f2 = self._scan(s.orelse, s2, guards)
                state.clear()
                if f1 and f2:
                    for k in set(s1) | set(s2):
                        r = s1.get(k) or s2.get(k)
                        if k in s1 and k in s2:
                            state[k] = r
                        else:
                            r.maybe = True
                            state[k] = r
                elif f1:
                    state.update(s1)
                elif f2:
                    state.update(s2)
                else:
                    return False
            elif isinstance(s, (ast.For, ast.While)):
                if isinstance(s, ast.For):
                    self._process(s.iter, state, guards)
                else:
                    self._process(s.test, state, guards)
                # two symbolic iterations: an acquire in the body whose
                # name is rebound on pass 2 without a release is a
                # loop-carried leak. A body that TERMINATES on every
                # path (`while True: ... return`) has no iteration 2.
                if self._scan(s.body, state, guards):
                    self._scan(s.body, state, guards)
                self._scan(s.orelse, state, guards)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    self._process(item.context_expr, state, guards)
                if not self._scan(s.body, state, guards):
                    return False
            elif isinstance(s, ast.Try):
                g = self._try_guard(s)
                if not self._scan(s.body, state, guards + [g]):
                    # the body terminated on every path; only handlers
                    # that complete normally continue the function — the
                    # post-try state is the UNION of their states (a
                    # handler's release must actually remove the
                    # resource here, or correct release-in-handler code
                    # reads as a leak)
                    survivors = []
                    for h in s.handlers:
                        hs = dict(state)
                        if self._scan(h.body, hs, guards):
                            survivors.append(hs)
                    if not survivors:
                        self._scan(s.finalbody, dict(state), guards)
                        return False
                    merged: Dict[int, _Resource] = {}
                    for hs in survivors:
                        for k, r in hs.items():
                            if any(k not in o for o in survivors):
                                r.maybe = True
                            merged[k] = r
                    state.clear()
                    state.update(merged)
                    if not self._scan(s.finalbody, state, guards):
                        return False
                    continue
                for h in s.handlers:
                    self._scan(h.body, dict(state), guards)
                if not self._scan(s.finalbody, state, guards):
                    return False
            elif isinstance(s, ast.Assert):
                self._process(s.test, state, guards)
            elif isinstance(s, ast.Delete):
                pass
            else:
                for child in ast.iter_child_nodes(s):
                    if isinstance(child, ast.expr):
                        self._process(child, state, guards)
        return True


def analyze_lifecycle(project: Project, cg: CallGraph) -> LifecycleAnalysis:
    return LifecycleAnalysis(project, cg).run()
