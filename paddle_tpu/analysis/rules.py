"""tpu_lint rules R1–R5.

Every rule is a pure function over the :class:`~.model.Project` +
:class:`~.callgraph.CallGraph`; findings carry the trace-entry chain that
makes the site reachable and a fix hint. The shared *taint* machinery
marks values that are traced (function parameters of reachable-under-trace
code, minus jit statics and config-flag defaults, propagated through
assignments) or *lazy* (results of dispatching a compiled program, which
are device futures until something forces them).

- **R1 host-sync**: explicit sync primitives (``jax.device_get`` /
  ``jax.block_until_ready`` / ``.item()``) anywhere — every one is either
  a bug or deserves a written justification; plus implicit syncs on
  traced values in trace-reachable code (``int()``/``float()``/``bool()``
  / ``np.asarray`` / ``print``) and on lazy dispatch results in hot paths.
- **R2 retrace hazard**: Python branching on traced values, formatting a
  tracer into a string, re-jitting inside hot code or loops, and
  unhashable literals fed to static jit parameters.
- **R3 donation-after-use**: an argument at a donated position of a
  compiled call read again afterwards (or reused across loop iterations
  without being reassigned from the call's results).
- **R4 PRNG key reuse**: one key consumed by ≥2 random ops (or by one
  random op across loop iterations) without an interleaving
  ``split``/``fold_in`` rebind. Branch-exclusive consumption (an ``if``
  arm that returns) does not count twice.
- **R5 unguarded shared state**: in classes that own threads, attributes
  guarded by a lock at most sites but accessed bare at others
  (majority-use lock inference, with lock context inherited by private
  helpers only ever called under the lock).
"""
from __future__ import annotations

import ast
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from .callgraph import CallGraph, dotted_path
from .model import ClassInfo, Finding, FunctionInfo, Project

__all__ = ["run_rules", "RulesOutput", "FileTimer", "RULE_DOCS"]

RULE_DOCS = {
    "R0": "suppression policy / parse errors (reasons are mandatory)",
    "R1": "host sync in trace-reachable or hot dispatch code",
    "R2": "retrace hazard (branch on traced value, tracer formatting, "
          "jit in hot code, unhashable static)",
    "R3": "donated buffer read after the donating call",
    "R4": "PRNG key consumed by >=2 random ops without split/fold_in",
    "R5": "shared attribute bypassing its majority-use lock in a "
          "threaded class",
    "R6": "lock-order cycle across the interprocedural acquisition "
          "graph, or re-entry through a non-reentrant Lock",
    "R7": "blocking operation (host sync, compiled dispatch, buffer "
          "update, sleep, unbounded wait/get/join, file I/O, rpc) "
          "inside a held-lock region",
    "R8": "mesh-axis/sharding discipline (undeclared PartitionSpec "
          "axis, frozen program-axis resize, shard_map arity, "
          "donated-input reshard)",
    "R9": "resource-lifecycle leak: an acquire (BlockPool lookup, "
          "AdapterStore acquire, pin, staged .tmp file) with an "
          "unreachable release on some path (incl. raise paths)",
    "R10": "SPMD collective divergence: collective under a "
           "rank-tainted branch/loop, or branch-asymmetric collective "
           "sequences — a cross-rank deadlock",
    "R11": "rpc discipline: unbounded rpc call, non-idempotent fn "
           "under transport retry, or a swallowed transport error",
}


class FileTimer:
    """Per-file wall-clock accounting for the ``--json`` timing block.

    ``parse`` is exact (one entry per file parse); ``lint`` accumulates
    the per-function/per-class rule passes attributed to the defining
    file (the dominant cost — whole-project passes like the callgraph
    BFS are reported in the rule totals instead)."""

    def __init__(self):
        self.parse: Dict[str, float] = {}
        self.lint: Dict[str, float] = {}

    def add(self, rel: str, dt: float) -> None:
        self.lint[rel] = self.lint.get(rel, 0.0) + dt

    def timed(self, items, rel_of):
        for x in items:
            t0 = time.perf_counter()
            yield x
            self.add(rel_of(x), time.perf_counter() - t0)

    def files_ms(self) -> Dict[str, dict]:
        out: Dict[str, dict] = {}
        for rel, dt in self.parse.items():
            out.setdefault(rel, {})["parse_ms"] = round(dt * 1e3, 3)
        for rel, dt in self.lint.items():
            out.setdefault(rel, {})["lint_ms"] = round(dt * 1e3, 3)
        return out

_SYNC_TERMINALS = {"device_get", "block_until_ready"}
_HOST_CASTS = {"int", "float", "bool"}
_STATIC_ATTRS = {"shape", "dtype", "ndim", "size", "sharding", "device",
                 "aval", "weak_type"}
# params with these names are config plumbing, never traced arrays
# (padding/stride/kernel geometry joined the set in the PR-7 baseline
# re-audit: `_pool`'s ceil-mode branch was a taint FP on them)
_UNTAINTED_PARAM_NAMES = {"dtype", "name", "data_format", "mode",
                          "padding", "pad", "kernel_size", "stride",
                          "dilation", "groups"}
_HOST_RESULT_CALLS = {"asarray", "array", "device_get", "item", "int",
                      "float", "bool", "len", "isinstance", "hasattr",
                      "getattr", "repr", "str", "format"}
_RANDOM_DERIVERS = {"split", "fold_in", "PRNGKey", "key", "key_data",
                    "wrap_key_data", "clone", "key_impl", "random_seed"}


def _numpy_rooted(fi: FunctionInfo, path: Tuple[str, ...]) -> bool:
    if path is None or len(path) < 2:
        return False
    alias = fi.file.aliases.get(path[0])
    root = alias[1] if alias and alias[0] == "module" else path[0]
    return root == "numpy" or path[0] in ("np", "numpy")


def _jax_rooted(fi: FunctionInfo, path: Tuple[str, ...]) -> bool:
    if not path:
        return False
    alias = fi.file.aliases.get(path[0])
    root = alias[1] if alias and alias[0] == "module" else path[0]
    return root.split(".")[0] == "jax"


# =========================================================== taint engine
class Taint:
    """Flow-insensitive tainted-name set for ONE function."""

    def __init__(self, fi: FunctionInfo, seeds: Set[str]):
        self.fi = fi
        self.names: Set[str] = set(seeds)
        # name -> line of an `isinstance(x, ...Tracer)` guard that raises
        self.tracer_guards: Dict[str, int] = {}
        # (name, start_line, end_line) regions where name is PROVEN
        # concrete by a `not isinstance(x, Tracer)` test
        self.concrete_regions: List[Tuple[str, int, int]] = []
        self._propagate()

    def _assignments(self):
        for node in ast.walk(self.fi.node):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not self.fi.node:
                continue
            if isinstance(node, ast.Assign):
                yield node.value, node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                yield node.value, [node.target]
            elif isinstance(node, ast.AugAssign):
                yield node.value, [node.target]
            elif isinstance(node, ast.For):
                yield node.iter, [node.target]
            elif isinstance(node, ast.withitem) and node.optional_vars:
                yield node.context_expr, [node.optional_vars]
            elif isinstance(node, ast.NamedExpr):
                yield node.value, [node.target]

    def _target_names(self, t) -> List[str]:
        """Plain names a tainted RHS taints. Attribute/Subscript targets
        (``self.x = v``, ``d[k] = v``) taint NOTHING — the base object is
        a container, not the value (tainting `self` here poisoned every
        ``self.*`` read)."""
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            out = []
            for e in t.elts:
                out.extend(self._target_names(e))
            return out
        if isinstance(t, ast.Starred):
            return self._target_names(t.value)
        return []

    def _propagate(self) -> None:
        self._find_guards()
        for _ in range(10):
            changed = False
            for value, targets in self._assignments():
                names: List[str] = []
                for t in targets:
                    names.extend(self._target_names(t))
                if not names or not self.expr(value):
                    continue
                # `for k, v in tainted.items():` — the KEYS are strings
                if len(names) == 2 and isinstance(value, ast.Call) \
                        and isinstance(value.func, ast.Attribute) \
                        and value.func.attr == "items":
                    names = names[1:]
                for n in names:
                    if n not in self.names:
                        self.names.add(n)
                        changed = True
            if not changed:
                break

    @staticmethod
    def _isinstance_tracer(e) -> Optional[str]:
        """Name N when ``e`` is ``isinstance(N, ...Tracer)``."""
        if isinstance(e, ast.Call) and isinstance(e.func, ast.Name) \
                and e.func.id == "isinstance" and len(e.args) == 2 \
                and isinstance(e.args[0], ast.Name):
            types = dotted_path(e.args[1]) or ()
            if types and types[-1] == "Tracer":
                return e.args[0].id
        return None

    def _find_guards(self) -> None:
        """Tracer guards prove a value concrete: after an
        ``isinstance(x, Tracer): raise/return``, inside the body of
        ``if not isinstance(x, Tracer):`` (also as an ``and`` operand),
        and in the ``else`` of ``if isinstance(x, Tracer):``."""
        for node in ast.walk(self.fi.node):
            if not isinstance(node, ast.If):
                continue
            t = node.test
            end = getattr(node, "end_lineno", node.lineno)
            n = self._isinstance_tracer(t)
            if n is not None:
                if node.body and isinstance(node.body[-1],
                                            (ast.Raise, ast.Return)):
                    self.tracer_guards.setdefault(n, node.lineno)
                if node.orelse:
                    self.concrete_regions.append(
                        (n, node.orelse[0].lineno, end))
                continue
            neg = []
            if isinstance(t, ast.UnaryOp) and isinstance(t.op, ast.Not):
                n = self._isinstance_tracer(t.operand)
                if n is not None:
                    neg.append(n)
            elif isinstance(t, ast.BoolOp) and isinstance(t.op, ast.And):
                for v in t.values:
                    if isinstance(v, ast.UnaryOp) \
                            and isinstance(v.op, ast.Not):
                        n = self._isinstance_tracer(v.operand)
                        if n is not None:
                            neg.append(n)
            if neg and node.body:
                body_end = getattr(node.body[-1], "end_lineno", end)
                for n in neg:
                    self.concrete_regions.append(
                        (n, node.body[0].lineno, body_end))

    def guarded(self, name: str, line: int) -> bool:
        g = self.tracer_guards.get(name)
        if g is not None and g < line:
            return True
        return any(n == name and s <= line <= e
                   for n, s, e in self.concrete_regions)

    # ------------------------------------------------------------- expr
    def expr(self, e: Optional[ast.AST]) -> bool:
        if e is None or isinstance(e, (ast.Constant, ast.Lambda)):
            return False
        if isinstance(e, ast.BoolOp):
            # `isinstance(x, int) and x == 0` — the guard proves x is a
            # host scalar for the rest of the chain (classic static/traced
            # dispatch idiom, e.g. prefill-vs-decode on position_offset)
            guarded: Set[str] = set()
            for v in e.values:
                if isinstance(v, ast.Call) and isinstance(v.func, ast.Name) \
                        and v.func.id == "isinstance" and v.args \
                        and isinstance(v.args[0], ast.Name):
                    guarded.add(v.args[0].id)
                    continue
                removed = guarded & self.names
                self.names -= removed
                try:
                    if self.expr(v):
                        return True
                finally:
                    self.names |= removed
            return False
        if isinstance(e, ast.Name):
            return e.id in self.names
        if isinstance(e, ast.Attribute):
            if e.attr in _STATIC_ATTRS:
                return False
            return self.expr(e.value)
        if isinstance(e, ast.Compare):
            if all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
                return False
            return self.expr(e.left) or any(self.expr(c)
                                            for c in e.comparators)
        if isinstance(e, ast.Call):
            f = e.func
            if isinstance(f, ast.Name) and f.id in _HOST_RESULT_CALLS:
                return False
            path = dotted_path(f)
            if path and path[-1] in ("asarray", "array", "device_get",
                                     "item", "stack", "tolist") \
                    and _numpy_rooted(self.fi, path):
                return False
            if path and path[-1] in _SYNC_TERMINALS:
                return False
            return (any(self.expr(a) for a in e.args)
                    or any(self.expr(k.value) for k in e.keywords)
                    or self.expr(f))
        return any(self.expr(c) for c in ast.iter_child_nodes(e)
                   if isinstance(c, ast.expr))


def _default_seeds(fi: FunctionInfo) -> Set[str]:
    out: Set[str] = set()
    for p in fi.params:
        if p in ("self", "cls") or p in fi.statics \
                or p in _UNTAINTED_PARAM_NAMES:
            continue
        d = fi.defaults.get(p)
        if isinstance(d, ast.Constant) and isinstance(d.value, (bool, str)):
            continue
        out.add(p)
    return out


def _map_call_args(call: ast.Call, callee: FunctionInfo,
                   bound: bool) -> Optional[Dict[str, ast.AST]]:
    """Positional+keyword call args mapped onto callee param names.
    ``bound``: the call was ``self.m(...)`` / ``obj.m(...)`` so the
    callee's leading ``self`` is not in the arg list. None when *args
    makes the mapping unreliable."""
    params = callee.params
    if params[:1] in (["self"], ["cls"]):
        if not bound:
            return None
        params = params[1:]
    out: Dict[str, ast.AST] = {}
    for i, a in enumerate(call.args):
        if isinstance(a, ast.Starred):
            break
        if i < len(params):
            out[params[i]] = a
    for kw in call.keywords:
        if kw.arg is not None:
            out[kw.arg] = kw.value
    return out


def build_taints(project: Project, cg: CallGraph) -> Dict[str, Taint]:
    """Taint for every trace-reachable function, with one round of
    interprocedural refinement: a non-root callee param that every
    resolved traced caller feeds an untraced value (e.g. ``top_k``
    threaded down from a jit static) is cleared."""
    reach = [f for f in project.functions.values() if f.trace_reachable]
    seeds = {f.qualname: _default_seeds(f) for f in reach}
    taints = {f.qualname: Taint(f, seeds[f.qualname]) for f in reach}
    for _ in range(2):
        passed_tainted: Dict[str, Set[str]] = {}
        passed_any: Dict[str, Set[str]] = {}
        for caller, call, callee in cg.call_edges:
            if not (caller.trace_reachable and callee.trace_reachable
                    and not callee.trace_root):
                continue
            bound = isinstance(call.func, ast.Attribute)
            mapping = _map_call_args(call, callee, bound)
            if mapping is None:
                # unknown mapping: keep every default-tainted param tainted
                passed_tainted.setdefault(callee.qualname, set()).update(
                    seeds[callee.qualname])
                passed_any.setdefault(callee.qualname, set()).update(
                    seeds[callee.qualname])
                continue
            t = taints[caller.qualname]
            for p, expr in mapping.items():
                passed_any.setdefault(callee.qualname, set()).add(p)
                if t.expr(expr):
                    passed_tainted.setdefault(callee.qualname,
                                              set()).add(p)
        changed = False
        for f in reach:
            if f.trace_root or f.qualname not in passed_any:
                continue
            base = _default_seeds(f)
            new = {p for p in base
                   if p in passed_tainted.get(f.qualname, set())
                   or p not in passed_any[f.qualname]}
            if new != seeds[f.qualname]:
                seeds[f.qualname] = new
                taints[f.qualname] = Taint(f, new)
                changed = True
        if not changed:
            break
    return taints


def _dispatch_seeds(fi: FunctionInfo, cg: CallGraph) -> Set[str]:
    """Names assigned from a compiled-program call — lazy device values."""
    calls = {id(dc.node) for dc in cg.dispatch_calls.get(fi.qualname, ())}
    out: Set[str] = set()
    if not calls:
        return out
    def names(t) -> List[str]:
        # plain Name targets only — `self.attr = call()` must NOT taint
        # `self` (that poisoned every later `self.*` read in the function)
        if isinstance(t, ast.Name):
            return [t.id]
        if isinstance(t, (ast.Tuple, ast.List)):
            return [n for e in t.elts for n in names(e)]
        if isinstance(t, ast.Starred):
            return names(t.value)
        return []

    for node in ast.walk(fi.node):
        if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call) \
                and id(node.value) in calls:
            for t in node.targets:
                out.update(names(t))
    return out


def _finding(rule: str, fi: FunctionInfo, line: int, msg: str,
             hint: str = "", chain: Tuple[str, ...] = ()) -> Finding:
    return Finding(rule, fi.file.rel, line, msg, symbol=fi.short,
                   snippet=fi.file.snippet(line), chain=chain, hint=hint)


# ================================================================== R1
def run_r1(project: Project, cg: CallGraph,
           taints: Dict[str, Taint]) -> List[Finding]:
    out: List[Finding] = []
    for fi in _timed_functions(project):
        chain = fi.trace_chain if fi.trace_reachable else ()
        ctx = ("inside trace-reachable code — this would sync (or fail) "
               "at trace time" if fi.trace_reachable
               else "in a compiled-dispatch hot path"
               if fi.dispatch else "host sync")
        # --- explicit sync primitives, everywhere
        for call in cg.own_calls(fi):
            path = dotted_path(call.func)
            if path and path[-1] in _SYNC_TERMINALS \
                    and _jax_rooted(fi, path):
                out.append(_finding(
                    "R1", fi, call.lineno,
                    f"`{'.'.join(path)}` {ctx}",
                    hint="move the sync out of the hot path, batch it "
                         "with other reads, or suppress with a reason",
                    chain=chain))
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "item" and not call.args \
                    and not call.keywords:
                out.append(_finding(
                    "R1", fi, call.lineno,
                    f"`.item()` {ctx} — one scalar per round-trip",
                    hint="batch reads via one jax.device_get, or "
                         "suppress with a reason", chain=chain))
            elif isinstance(call.func, ast.Attribute) \
                    and call.func.attr == "block_until_ready":
                # method form `arr.block_until_ready()` — same sync as
                # the jax.block_until_ready function form
                out.append(_finding(
                    "R1", fi, call.lineno,
                    f"`.block_until_ready()` {ctx}",
                    hint="move the sync out of the hot path, batch it "
                         "with other reads, or suppress with a reason",
                    chain=chain))
        # --- implicit syncs on traced values
        if fi.trace_reachable:
            t = taints.get(fi.qualname)
            if t is not None:
                out.extend(_implicit_syncs(fi, t, chain, traced=True))
        elif cg.dispatch_calls.get(fi.qualname):
            lazy = _dispatch_seeds(fi, cg)
            if lazy:
                t = Taint(fi, lazy)
                out.extend(_implicit_syncs(fi, t, (), traced=False))
    return out


def _implicit_syncs(fi: FunctionInfo, t: Taint, chain, traced: bool):
    out: List[Finding] = []
    what = "traced value" if traced else "lazy value from a compiled call"
    for call in cg_own_calls_cached(fi):
        f = call.func
        args_tainted = [a for a in call.args if t.expr(a)]
        # every tainted NAME reaching the call proven concrete by a Tracer
        # guard (`int(jnp.max(lengths))` under `if not isinstance(lengths,
        # Tracer):` — the tainted arg is a Call, the guarded name inside)
        names_tainted = [n.id for a in args_tainted for n in ast.walk(a)
                         if isinstance(n, ast.Name) and n.id in t.names]
        if names_tainted and all(t.guarded(n, call.lineno)
                                 for n in names_tainted):
            continue
        if isinstance(f, ast.Name) and f.id in _HOST_CASTS and args_tainted:
            out.append(_finding(
                "R1", fi, call.lineno,
                f"`{f.id}()` on {what} `{ast.unparse(args_tainted[0])}` "
                f"forces a host sync",
                hint="keep the value on device (jnp ops / jnp.where), or "
                     "read it lazily in a batched device_get",
                chain=chain))
            continue
        path = dotted_path(f)
        if path and path[-1] in ("asarray", "array") \
                and _numpy_rooted(fi, path) and args_tainted:
            out.append(_finding(
                "R1", fi, call.lineno,
                f"`{'.'.join(path)}` on {what} "
                f"`{ast.unparse(args_tainted[0])}` forces a host transfer",
                hint="use jnp.asarray under trace; for dispatch results "
                     "batch all reads into ONE jax.device_get",
                chain=chain))
            continue
        if traced and isinstance(f, ast.Name) and f.id == "print" \
                and args_tainted:
            out.append(_finding(
                "R1", fi, call.lineno,
                "`print` of a traced value runs at trace time (or syncs); "
                "use jax.debug.print",
                hint="jax.debug.print(\"{x}\", x=...) stays in-graph",
                chain=chain))
    return out


_OWN_CALLS_CACHE: Dict[str, List[ast.Call]] = {}
_CG_REF: Optional[CallGraph] = None
_TIMER: Optional[FileTimer] = None


def cg_own_calls_cached(fi: FunctionInfo) -> List[ast.Call]:
    got = _OWN_CALLS_CACHE.get(fi.qualname)
    if got is None:
        got = _OWN_CALLS_CACHE[fi.qualname] = _CG_REF.own_calls(fi)
    return got


def _timed_functions(project: Project):
    items = project.functions.values()
    if _TIMER is None:
        return iter(items)
    return _TIMER.timed(items, lambda fi: fi.file.rel)


# ================================================================== R2
def run_r2(project: Project, cg: CallGraph,
           taints: Dict[str, Taint]) -> List[Finding]:
    out: List[Finding] = []
    for fi in _timed_functions(project):
        t = taints.get(fi.qualname)
        if fi.trace_reachable and t is not None:
            out.extend(_branch_hazards(fi, t))
        out.extend(_jit_in_hot_code(fi, cg))
        out.extend(_unhashable_statics(fi, cg))
    return out


def _branch_hazards(fi: FunctionInfo, t: Taint) -> List[Finding]:
    out: List[Finding] = []
    chain = fi.trace_chain

    def tainted_names(e) -> List[str]:
        return [n.id for n in ast.walk(e) if isinstance(n, ast.Name)
                and n.id in t.names]

    def ok(e, line) -> bool:
        names = tainted_names(e)
        return bool(names) and all(t.guarded(n, line) for n in names)

    for node in ast.walk(fi.node):
        if isinstance(node, (ast.If, ast.While)) and t.expr(node.test) \
                and not ok(node.test, node.lineno):
            kind = "if" if isinstance(node, ast.If) else "while"
            out.append(_finding(
                "R2", fi, node.lineno,
                f"Python `{kind}` branches on a traced value — every "
                f"distinct value retraces (or fails to trace at all)",
                hint="use jnp.where / lax.cond / lax.select, or hoist the "
                     "decision to a static argument", chain=chain))
        elif isinstance(node, ast.IfExp) and t.expr(node.test) \
                and not ok(node.test, node.lineno):
            out.append(_finding(
                "R2", fi, node.lineno,
                "conditional expression branches on a traced value",
                hint="jnp.where(cond, a, b)", chain=chain))
        elif isinstance(node, ast.Assert) and t.expr(node.test):
            out.append(_finding(
                "R2", fi, node.lineno,
                "assert on a traced value concretizes it at trace time",
                hint="use checkify / debug.check, or assert on .shape",
                chain=chain))
        elif isinstance(node, (ast.ListComp, ast.SetComp, ast.DictComp,
                               ast.GeneratorExp)):
            for gen in node.generators:
                for cond in gen.ifs:
                    if t.expr(cond):
                        out.append(_finding(
                            "R2", fi, cond.lineno,
                            "comprehension filters on a traced value",
                            hint="mask with jnp.where instead of "
                                 "filtering", chain=chain))
        elif isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue) and t.expr(v.value):
                    out.append(_finding(
                        "R2", fi, node.lineno,
                        "f-string formats a traced value (concretizes at "
                        "trace time; bakes ONE traced repr per compile)",
                        hint="format after a device_get outside the "
                             "traced code, or use jax.debug.print",
                        chain=chain))
                    break
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "format" \
                and isinstance(node.func.value, (ast.Constant,
                                                 ast.JoinedStr)) \
                and any(t.expr(a) for a in node.args):
            out.append(_finding(
                "R2", fi, node.lineno,
                "str.format of a traced value concretizes it",
                chain=chain))
    return out


def _jit_in_hot_code(fi: FunctionInfo, cg: CallGraph) -> List[Finding]:
    out: List[Finding] = []

    def walk(node, loop_depth):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            d = loop_depth + (1 if isinstance(child, (ast.For, ast.While))
                              else 0)
            if isinstance(child, ast.Call) \
                    and cg.is_jit_callee(fi, child.func):
                if loop_depth > 0:
                    out.append(_finding(
                        "R2", fi, child.lineno,
                        "jax.jit called inside a loop — a fresh compiled "
                        "callable (and cache entry) per iteration",
                        hint="hoist the jit() out of the loop and reuse "
                             "the compiled callable"))
                elif fi.trace_reachable:
                    out.append(_finding(
                        "R2", fi, child.lineno,
                        "jax.jit called inside trace-reachable code",
                        hint="compile once at construction time",
                        chain=fi.trace_chain))
            walk(child, d)

    walk(fi.node, 0)
    return out


def _unhashable_statics(fi: FunctionInfo, cg: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    for dc in cg.dispatch_calls.get(fi.qualname, ()):
        info = dc.compiled
        if not info.statics:
            continue
        target = info.target
        mapping = None
        if target is not None:
            mapping = _map_call_args(dc.node, target, bound=True)
        if mapping is None:
            mapping = {kw.arg: kw.value for kw in dc.node.keywords
                       if kw.arg}
        for name, expr in mapping.items():
            if name in info.statics and isinstance(
                    expr, (ast.List, ast.Dict, ast.Set)):
                out.append(_finding(
                    "R2", fi, expr.lineno,
                    f"unhashable literal passed for static jit arg "
                    f"`{name}` — raises (or defeats the compile cache)",
                    hint="pass a tuple / frozen value"))
    return out


# ================================================================== R3
def run_r3(project: Project, cg: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    for qual, dcalls in cg.dispatch_calls.items():
        fi = project.functions[qual]
        donating = [dc for dc in dcalls if dc.compiled.donate]
        if donating:
            out.extend(_donation_scan(fi, donating))
    return out


@dataclass
class _VarUse:
    line: int
    write: bool


def _var_id(expr) -> Optional[Tuple[str, str]]:
    if isinstance(expr, ast.Name):
        return ("local", expr.id)
    if isinstance(expr, ast.Attribute) and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return ("attr", expr.attr)
    return None


def _collect_uses(fi: FunctionInfo) -> Dict[Tuple[str, str], List[_VarUse]]:
    uses: Dict[Tuple[str, str], List[_VarUse]] = {}
    for node in ast.walk(fi.node):
        vid = _var_id(node) if isinstance(node, (ast.Name,
                                                 ast.Attribute)) else None
        if vid is None:
            continue
        if isinstance(node, ast.Attribute) and not isinstance(
                node.ctx, (ast.Load, ast.Store, ast.Del)):
            continue
        write = isinstance(node.ctx, (ast.Store, ast.Del))
        uses.setdefault(vid, []).append(_VarUse(node.lineno, write))
    return uses


def _donation_scan(fi: FunctionInfo, dcalls) -> List[Finding]:
    out: List[Finding] = []
    uses = _collect_uses(fi)
    # map call node id -> (enclosing stmt, loop ancestors)
    ctx: Dict[int, Tuple[ast.stmt, List[ast.stmt]]] = {}

    def walk(node, stmt, loops):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            s = child if isinstance(child, ast.stmt) else stmt
            lp = loops + ([child] if isinstance(child,
                                                (ast.For, ast.While)) else [])
            if isinstance(child, ast.Call):
                ctx[id(child)] = (s, loops)
            walk(child, s, lp)

    walk(fi.node, None, [])
    for dc in dcalls:
        call = dc.node
        stmt, loops = ctx.get(id(call), (None, []))
        if stmt is None:
            continue
        stored: Set[Tuple[str, str]] = set()
        if isinstance(stmt, ast.Assign):
            for t in stmt.targets:
                for n in ast.walk(t):
                    vid = _var_id(n)
                    if vid:
                        stored.add(vid)
        end = getattr(stmt, "end_lineno", stmt.lineno)
        for pos in sorted(dc.compiled.donate):
            if pos >= len(call.args):
                continue
            vid = _var_id(call.args[pos])
            if vid is None:
                continue
            later = [u for u in uses.get(vid, ()) if u.line > end]
            reads = [u.line for u in later if not u.write]
            writes = [u.line for u in later if u.write]
            if vid not in stored and reads and (
                    not writes or min(writes) > min(reads)):
                out.append(_finding(
                    "R3", fi, min(reads),
                    f"`{vid[1]}` was donated to the compiled call at line "
                    f"{call.lineno} (donate_argnums={sorted(dc.compiled.donate)}, "
                    f"{dc.compiled.site}) and is read again here — the "
                    f"buffer may already be overwritten",
                    hint="rebind the name from the call's results, or "
                         "drop it from donate_argnums"))
            if loops and vid not in stored:
                innermost = loops[-1]
                loop_stores = False
                for n in ast.walk(innermost):
                    if isinstance(n, (ast.Name, ast.Attribute)) \
                            and isinstance(getattr(n, "ctx", None),
                                           ast.Store) \
                            and _var_id(n) == vid:
                        loop_stores = True
                        break
                if not loop_stores:
                    out.append(_finding(
                        "R3", fi, call.lineno,
                        f"`{vid[1]}` is donated inside a loop but never "
                        f"reassigned in the loop body — iteration 2 "
                        f"dispatches a donated (dead) buffer",
                        hint="rebind it from the call results each "
                             "iteration"))
    return out


# ================================================================== R4
def _random_consumer_arg(fi: FunctionInfo, call: ast.Call):
    """The key expr if ``call`` is a jax.random sampling op. Recognizes
    every import form: ``jax.random.normal``, ``from jax import random;
    random.normal``, and ``from jax.random import normal; normal``."""
    path = dotted_path(call.func)
    if not path:
        return None
    alias = fi.file.aliases.get(path[0])
    if alias is None:
        head = (path[0],)
    elif alias[0] == "module":
        head = (alias[1],)
    else:   # ("symbol", module, name)
        head = (alias[1], alias[2])
    dotted = ".".join(head + path[1:])
    if not dotted.startswith("jax.random."):
        return None
    name = path[-1]
    if name in _RANDOM_DERIVERS:
        return None
    for kw in call.keywords:
        if kw.arg == "key":
            return kw.value
    if call.args:
        return call.args[0]
    return None


def _consuming_params(project: Project, cg: CallGraph) -> Dict[str, Set[str]]:
    consuming: Dict[str, Set[str]] = {}
    for _ in range(4):
        changed = False
        for fi in project.functions.values():
            mine = consuming.setdefault(fi.qualname, set())
            for call in cg_own_calls_cached(fi):
                arg = _random_consumer_arg(fi, call)
                if isinstance(arg, ast.Name) and arg.id in fi.params \
                        and arg.id not in mine:
                    mine.add(arg.id)
                    changed = True
        for caller, call, callee in cg.call_edges:
            callee_cons = consuming.get(callee.qualname)
            if not callee_cons:
                continue
            bound = isinstance(call.func, ast.Attribute)
            mapping = _map_call_args(call, callee, bound)
            if not mapping:
                continue
            mine = consuming.setdefault(caller.qualname, set())
            for p, expr in mapping.items():
                if p in callee_cons and isinstance(expr, ast.Name) \
                        and expr.id in caller.params \
                        and expr.id not in mine:
                    mine.add(expr.id)
                    changed = True
        if not changed:
            break
    return consuming


class _R4Scanner:
    """Path-aware consumption counting for one function."""

    def __init__(self, fi: FunctionInfo, project: Project, cg: CallGraph,
                 consuming: Dict[str, Set[str]]):
        self.fi = fi
        self.project = project
        self.cg = cg
        self.consuming = consuming
        self.findings: List[Finding] = []
        self._emitted: Set[Tuple[int, str]] = set()

    def run(self) -> List[Finding]:
        self._scan(self.fi.node.body, {})
        return self.findings

    # state: name -> (count, first_line)
    def _consumptions(self, expr) -> List[Tuple[str, int]]:
        out = []
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            arg = _random_consumer_arg(self.fi, node)
            if isinstance(arg, ast.Name):
                out.append((arg.id, node.lineno))
                continue
            # project calls whose params are (transitively) key-consuming
            callees = self.cg.resolve_call(self.fi, node)
            for callee in callees:
                cons = self.consuming.get(callee.qualname) or set()
                if not cons:
                    continue
                mapping = _map_call_args(
                    node, callee, isinstance(node.func, ast.Attribute))
                if not mapping:
                    continue
                for p, e in mapping.items():
                    if p in cons and isinstance(e, ast.Name):
                        out.append((e.id, node.lineno))
        return out

    def _consume(self, expr, state, in_loop: bool) -> None:
        if expr is None:
            return
        for name, line in self._consumptions(expr):
            count, first = state.get(name, (0, None))
            count += 1
            if count == 1:
                state[name] = (1, line)
                continue
            state[name] = (count, first)
            if (line, name) in self._emitted:
                continue
            self._emitted.add((line, name))
            if first == line and in_loop:
                msg = (f"PRNG key `{name}` is consumed inside a loop "
                       f"without being split/folded per iteration — every "
                       f"iteration draws the SAME randomness")
            else:
                msg = (f"PRNG key `{name}` already consumed at line "
                       f"{first} is consumed again without an "
                       f"interleaving split/fold_in — the two draws "
                       f"correlate")
            self.findings.append(_finding(
                "R4", self.fi, line, msg,
                hint="key, sub = jax.random.split(key) (or fold_in a "
                     "step/row index) before each use",
                chain=self.fi.trace_chain))

    def _rebind(self, targets, state) -> None:
        for t in targets:
            for n in ast.walk(t):
                if isinstance(n, ast.Name):
                    state[n.id] = (0, None)

    def _scan(self, stmts: Sequence[ast.stmt], state,
              in_loop: bool = False) -> bool:
        """Returns False when the block terminates (return/raise/...)."""
        for s in stmts:
            if isinstance(s, (ast.FunctionDef, ast.AsyncFunctionDef,
                              ast.ClassDef)):
                continue
            if isinstance(s, (ast.Return, ast.Raise)):
                self._consume(getattr(s, "value", None) or
                              getattr(s, "exc", None), state, in_loop)
                return False
            if isinstance(s, (ast.Break, ast.Continue)):
                return False
            if isinstance(s, ast.Assign):
                self._consume(s.value, state, in_loop)
                self._rebind(s.targets, state)
            elif isinstance(s, ast.AugAssign):
                self._consume(s.value, state, in_loop)
                self._rebind([s.target], state)
            elif isinstance(s, ast.AnnAssign):
                if s.value is not None:
                    self._consume(s.value, state, in_loop)
                    self._rebind([s.target], state)
            elif isinstance(s, ast.Expr):
                self._consume(s.value, state, in_loop)
            elif isinstance(s, ast.If):
                self._consume(s.test, state, in_loop)
                s1 = dict(state)
                s2 = dict(state)
                f1 = self._scan(s.body, s1, in_loop)
                f2 = self._scan(s.orelse, s2, in_loop)
                if f1 and f2:
                    merged = {}
                    for k in set(s1) | set(s2):
                        c1, l1 = s1.get(k, (0, None))
                        c2, l2 = s2.get(k, (0, None))
                        merged[k] = (max(c1, c2), l1 if c1 >= c2 else l2)
                    state.clear()
                    state.update(merged)
                elif f1:
                    state.clear()
                    state.update(s1)
                elif f2:
                    state.clear()
                    state.update(s2)
                else:
                    return False
            elif isinstance(s, (ast.For, ast.While)):
                if isinstance(s, ast.For):
                    self._consume(s.iter, state, in_loop)
                    self._rebind([s.target], state)
                else:
                    self._consume(s.test, state, in_loop)
                # two symbolic iterations: a key consumed but not rebound
                # inside the body trips the counter on pass 2
                self._scan(s.body, state, in_loop=True)
                self._scan(s.body, state, in_loop=True)
            elif isinstance(s, (ast.With, ast.AsyncWith)):
                for item in s.items:
                    self._consume(item.context_expr, state, in_loop)
                    if item.optional_vars is not None:
                        self._rebind([item.optional_vars], state)
                if not self._scan(s.body, state, in_loop):
                    return False
            elif isinstance(s, ast.Try):
                self._scan(s.body, state, in_loop)
                for h in s.handlers:
                    self._scan(h.body, dict(state), in_loop)
                self._scan(s.finalbody, state, in_loop)
            else:
                for child in ast.iter_child_nodes(s):
                    if isinstance(child, ast.expr):
                        self._consume(child, state, in_loop)
        return True


def run_r4(project: Project, cg: CallGraph) -> List[Finding]:
    consuming = _consuming_params(project, cg)
    out: List[Finding] = []
    for fi in _timed_functions(project):
        out.extend(_R4Scanner(fi, project, cg, consuming).run())
    return out


# ================================================================== R5
@dataclass
class _Access:
    attr: str
    method: FunctionInfo
    line: int
    write: bool
    locks: frozenset


def _method_accesses(ci: ClassInfo, fi: FunctionInfo):
    """(accesses, intra-class calls with held locks) for one method."""
    accesses: List[_Access] = []
    calls: List[Tuple[str, frozenset]] = []

    def walk_stmt(node, held):
        # one statement subtree under a lock context
        if isinstance(node, ast.With):
            locks = set(held)
            for item in node.items:
                e = item.context_expr
                if isinstance(e, ast.Attribute) \
                        and isinstance(e.value, ast.Name) \
                        and e.value.id == "self" \
                        and e.attr in ci.lock_attrs:
                    locks.add(e.attr)
            for st in node.body:
                walk_stmt(st, frozenset(locks))
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return
        if isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id == "self" \
                and node.func.attr in ci.methods:
            calls.append((node.func.attr, held))
        if isinstance(node, ast.Attribute) \
                and isinstance(node.value, ast.Name) \
                and node.value.id == "self" \
                and node.attr not in ci.lock_attrs \
                and node.attr not in ci.methods \
                and not node.attr.isupper():
            accesses.append(_Access(
                node.attr, fi, node.lineno,
                isinstance(node.ctx, (ast.Store, ast.Del)), held))
        for child in ast.iter_child_nodes(node):
            walk_stmt(child, held)

    for st in fi.node.body:
        walk_stmt(st, frozenset())
    return accesses, calls


def run_r5(project: Project, cg: CallGraph) -> List[Finding]:
    out: List[Finding] = []
    for ci in project.classes.values():
        if not ci.lock_attrs:
            continue
        involved = ci.qualname in cg.threaded_classes or any(
            m.thread_reachable for m in ci.methods.values())
        if not involved:
            continue
        per_method: Dict[str, Tuple[List[_Access], list]] = {}
        for name, fi in ci.methods.items():
            if name == "__init__":
                continue
            per_method[name] = _method_accesses(ci, fi)
        # lock context inherited by private helpers only ever called
        # (intra-class) with the lock held
        inherited: Dict[str, frozenset] = {m: frozenset()
                                           for m in per_method}
        for _ in range(3):
            call_locks: Dict[str, List[frozenset]] = {}
            for caller, (_, calls) in per_method.items():
                for callee, held in calls:
                    eff = held | inherited.get(caller, frozenset())
                    call_locks.setdefault(callee, []).append(eff)
            new = dict(inherited)
            for m, sites in call_locks.items():
                fi = ci.methods.get(m)
                if fi is None or not m.startswith("_") or fi.thread_root:
                    continue
                ctx = frozenset.intersection(*[frozenset(s)
                                               for s in sites])
                new[m] = ctx
            if new == inherited:
                break
            inherited = new
        # verdicts per attribute
        by_attr: Dict[str, List[_Access]] = {}
        for m, (accesses, _) in per_method.items():
            extra = inherited.get(m, frozenset())
            for a in accesses:
                a = _Access(a.attr, a.method, a.line, a.write,
                            a.locks | extra)
                by_attr.setdefault(a.attr, []).append(a)
        for attr, sites in by_attr.items():
            methods = {a.method.name for a in sites}
            if len(methods) < 2 or not any(a.write for a in sites):
                continue
            for lock in ci.lock_attrs:
                guarded = [a for a in sites if lock in a.locks]
                unguarded = [a for a in sites if lock not in a.locks]
                if len(guarded) < 2 or len(guarded) <= len(unguarded):
                    continue
                for a in unguarded:
                    out.append(Finding(
                        "R5", ci.file.rel, a.line,
                        f"`self.{attr}` is accessed under `self.{lock}` "
                        f"at {len(guarded)} site(s) in {ci.name} but "
                        f"without it here, and {ci.name} runs a "
                        f"background thread — torn read/lost update risk",
                        symbol=f"{ci.name}.{a.method.name}",
                        snippet=ci.file.snippet(a.line),
                        hint=f"take `with self.{lock}:` around this "
                             f"access (majority-use lock inference)"))
                break
    return out


# ============================================================== driver
@dataclass
class RulesOutput:
    findings: List[Finding] = field(default_factory=list)
    lock_graph: dict = field(default_factory=dict)
    lifecycle_graph: dict = field(default_factory=dict)
    rule_ms: Dict[str, float] = field(default_factory=dict)


def run_rules(project: Project, cg: CallGraph,
              timer: Optional[FileTimer] = None) -> RulesOutput:
    from .lifecycle import analyze_lifecycle
    from .locks import analyze_locks
    from .rpccheck import analyze_rpc
    from .sharding import analyze_sharding
    from .spmd import analyze_spmd

    global _CG_REF, _TIMER
    _CG_REF = cg
    _TIMER = timer
    _OWN_CALLS_CACHE.clear()
    out = RulesOutput()

    def staged(rule: str, fn):
        t0 = time.perf_counter()
        got = fn()
        out.rule_ms[rule] = round(
            out.rule_ms.get(rule, 0.0)
            + (time.perf_counter() - t0) * 1e3, 3)
        return got

    taints = staged("taint", lambda: build_taints(project, cg))
    out.findings.extend(staged("R1", lambda: run_r1(project, cg, taints)))
    out.findings.extend(staged("R2", lambda: run_r2(project, cg, taints)))
    out.findings.extend(staged("R3", lambda: run_r3(project, cg)))
    out.findings.extend(staged("R4", lambda: run_r4(project, cg)))
    out.findings.extend(staged("R5", lambda: run_r5(project, cg)))
    locks = staged("R6+R7", lambda: analyze_locks(project, cg))
    out.findings.extend(locks.findings)
    out.lock_graph = locks.lock_graph()
    out.findings.extend(staged("R8",
                               lambda: analyze_sharding(project, cg)))
    life = staged("R9", lambda: analyze_lifecycle(project, cg))
    out.findings.extend(life.findings)
    out.lifecycle_graph = life.lifecycle_graph()
    out.findings.extend(staged("R10", lambda: analyze_spmd(project, cg)))
    out.findings.extend(staged("R11", lambda: analyze_rpc(project, cg)))
    _TIMER = None
    return out
