"""R11: rpc deadline / idempotence / transport-error discipline.

PR 13's cross-host fleet stays correct only because three invariants are
hand-enforced at every rpc surface. This rule family machine-checks
them:

- **deadline-bounded calls**: every direct ``rpc_sync`` / ``rpc_async``
  / ``_invoke`` must carry an explicit ``timeout=`` /
  ``connect_deadline=`` (or thread a ``resilience.Deadline`` /
  caller-supplied ``timeout`` into one) — a call riding the transport's
  120s default holds a crashed peer's failure for two minutes, blowing
  every caller's classification budget. A ``Deadline`` threaded through
  a helper parameter counts as bounded;
- **non-idempotent calls never transport-retried**: a submit-shaped rpc
  (name registry + ``# tpu-lint: rpc-non-idempotent`` annotations) whose
  lost RESPONSE is indistinguishable from a lost REQUEST must never run
  under a ``RetryPolicy`` with more than one attempt or inside a
  hand-rolled retry loop — a retried submit double-admits
  undecidably. ``# tpu-lint: rpc-idempotent`` on the def line clears a
  name the registry would otherwise flag;
- **transport errors never swallowed**: an ``except`` catching
  ``RpcTransportError`` / ``ReplicaUnreachable`` (or ``ConnectionError``
  in a function that itself makes rpc calls) must re-raise or classify —
  a ``pass``-only handler hides a dead peer from every failure detector
  above it.

Scoped deliberately: the handler check only fires on the rpc-specific
exception types (or bare ``ConnectionError`` in rpc-calling functions),
so the KV-store/socket layers' intentional best-effort handlers stay
out of scope unless they name the rpc types.
"""
from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from .callgraph import CallGraph, dotted_path
from .model import Finding, FunctionInfo, Project

__all__ = ["analyze_rpc", "RPC_PRIMITIVES", "NON_IDEMPOTENT_MARKERS"]

RPC_PRIMITIVES = frozenset({"rpc_sync", "rpc_async", "_invoke"})
# name substrings that default a remote fn to NON-idempotent (a lost
# response makes re-execution undecidable); override per-def with
# `# tpu-lint: rpc-idempotent`
NON_IDEMPOTENT_MARKERS = ("submit",)
_TRANSPORT_TYPES = frozenset({"RpcTransportError", "ReplicaUnreachable"})
_TRANSPORT_GENERIC = frozenset({"ConnectionError"})
_BOUND_KWARGS = frozenset({"timeout", "connect_deadline", "deadline",
                           "rpc_timeout"})
_DEADLINEY_PARAMS = ("timeout", "deadline", "budget")

_IDEMPOTENT_RE = re.compile(r"#\s*tpu-lint:\s*rpc-idempotent\b")
_NON_IDEMPOTENT_RE = re.compile(r"#\s*tpu-lint:\s*rpc-non-idempotent\b")


def _line_has(sf, line: int, rx) -> bool:
    for cand in (line, line - 1):
        if 1 <= cand <= len(sf.lines) and rx.search(sf.lines[cand - 1]):
            return True
    return False


class RpcAnalysis:
    def __init__(self, project: Project, cg: CallGraph):
        self.project = project
        self.cg = cg
        self.findings: List[Finding] = []
        self._idempotence: Dict[str, bool] = {}   # fn name -> idempotent?
        self._collect_annotations()

    # --------------------------------------------------------- registry
    def _collect_annotations(self) -> None:
        """The annotation registry: every project def annotated
        ``rpc-idempotent`` / ``rpc-non-idempotent`` on (or directly
        above) its ``def`` line."""
        for fi in self.project.functions.values():
            line = fi.node.lineno
            if _line_has(fi.file, line, _IDEMPOTENT_RE):
                self._idempotence[fi.name] = True
            elif _line_has(fi.file, line, _NON_IDEMPOTENT_RE):
                self._idempotence[fi.name] = False

    def _non_idempotent(self, name: str) -> bool:
        got = self._idempotence.get(name)
        if got is not None:
            return not got
        return any(m in name.lower() for m in NON_IDEMPOTENT_MARKERS)

    # ------------------------------------------------------------ utils
    def _is_rpc_call(self, fi: FunctionInfo, call: ast.Call) -> bool:
        path = dotted_path(call.func)
        return bool(path) and path[-1] in RPC_PRIMITIVES

    @staticmethod
    def _fn_arg_name(call: ast.Call) -> Optional[str]:
        """The remote-fn argument of an rpc primitive call: arg 1 of
        ``rpc_sync(to, fn, ...)`` / ``_invoke(to, fn, ...)``."""
        args = call.args
        if len(args) >= 2:
            a = args[1]
            if isinstance(a, ast.Name):
                return a.id
            if isinstance(a, ast.Attribute):
                return a.attr
        for kw in call.keywords:
            if kw.arg == "fn":
                if isinstance(kw.value, ast.Name):
                    return kw.value.id
                if isinstance(kw.value, ast.Attribute):
                    return kw.value.attr
        return None

    def _bounded(self, fi: FunctionInfo, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg in _BOUND_KWARGS:
                return True
        # positional timeout: rpc_sync(to, fn, args, kwargs, timeout)
        if len(call.args) >= 5:
            return True
        # an argument derived from a Deadline / caller timeout in scope
        deadline_names = self._deadline_names(fi)
        for a in list(call.args) + [kw.value for kw in call.keywords]:
            for n in ast.walk(a):
                if isinstance(n, ast.Name) and n.id in deadline_names:
                    return True
        return False

    def _deadline_names(self, fi: FunctionInfo) -> Set[str]:
        names = {p for p in fi.params
                 if any(p.startswith(d) or p.endswith(d)
                        for d in _DEADLINEY_PARAMS)}
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call):
                path = dotted_path(node.value.func)
                if path and path[-1] in ("Deadline", "remaining"):
                    for t in node.targets:
                        for n in ast.walk(t):
                            if isinstance(n, ast.Name):
                                names.add(n.id)
        return names

    # -------------------------------------------------- retry resolution
    def _policy_attempts(self, fi: FunctionInfo,
                         expr: ast.AST) -> Optional[int]:
        """``max_attempts`` of the RetryPolicy ``expr`` resolves to, or
        None when unresolvable. Resolves locals and ``self._x``
        assignments anywhere in the class."""
        def from_call(call: ast.Call) -> Optional[int]:
            path = dotted_path(call.func)
            if not path or path[-1] != "RetryPolicy":
                return None
            if call.args:       # positional max_attempts
                a0 = call.args[0]
                if isinstance(a0, ast.Constant) \
                        and isinstance(a0.value, int):
                    return int(a0.value)
                return None     # present but not a literal: unresolvable
            for kw in call.keywords:
                if kw.arg == "max_attempts":
                    if isinstance(kw.value, ast.Constant) \
                            and isinstance(kw.value.value, int):
                        return int(kw.value.value)
                    return None  # present but not a literal
            return 0    # genuinely uncapped: deadline-bounded retries
        if isinstance(expr, ast.Call):
            return from_call(expr)
        if isinstance(expr, ast.Name):
            val = self.cg._local_assign_map(fi).get(expr.id)
            if isinstance(val, ast.Call):
                return from_call(val)
            return None
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id == "self" and fi.cls is not None:
            assigned = self.cg._class_attr_assign(fi.cls, expr.attr)
            if isinstance(assigned, ast.Call):
                return from_call(assigned)
        return None

    # -------------------------------------------------------------- run
    def run(self) -> "RpcAnalysis":
        for fi in self.project.functions.values():
            self._check_function(fi)
        return self

    def _finding(self, fi: FunctionInfo, line: int, msg: str,
                 hint: str) -> Finding:
        return Finding("R11", fi.file.rel, line, msg, symbol=fi.short,
                       snippet=fi.file.snippet(line), hint=hint,
                       chain=fi.thread_chain if fi.thread_reachable
                       else ())

    def _check_function(self, fi: FunctionInfo) -> None:
        rpc_calls = [c for c in self.cg.own_calls(fi)
                     if self._is_rpc_call(fi, c)]
        for call in rpc_calls:
            # R11a: deadline discipline
            if not self._bounded(fi, call):
                name = dotted_path(call.func)[-1]
                self.findings.append(self._finding(
                    fi, call.lineno,
                    f"`{name}` call rides the transport's default "
                    f"timeout — a dead peer holds this caller for the "
                    f"full 120s default instead of ITS deadline",
                    hint="pass timeout= (or thread the caller's "
                         "resilience.Deadline: "
                         "timeout=deadline.remaining())"))
            # R11b: idempotence vs retry (hand-rolled loop form)
            fn_name = self._fn_arg_name(call)
            if fn_name and self._non_idempotent(fn_name):
                loop = self._retry_loop_around(fi, call)
                if loop is not None:
                    self.findings.append(self._finding(
                        fi, call.lineno,
                        f"non-idempotent rpc fn `{fn_name}` is retried "
                        f"by the loop at line {loop} that swallows "
                        f"transport errors — a lost RESPONSE "
                        f"re-executes the submit (double admission is "
                        f"undecidable)",
                        hint="never transport-retry a submit: fail "
                             "over/raise instead, or annotate the fn "
                             "`# tpu-lint: rpc-idempotent` if "
                             "re-execution is truly safe"))
        # R11b: retry-policy forms
        self._check_retry_policies(fi)
        # R11c: swallowed transport errors
        self._check_handlers(fi, bool(rpc_calls))

    # ---- hand-rolled retry loop: rpc in a loop whose body swallows
    # transport errors (except ConnectionError-ish without raise)
    def _retry_loop_around(self, fi: FunctionInfo,
                           call: ast.Call) -> Optional[int]:
        loops: List[ast.stmt] = []

        def walk(node, stack):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                st = stack + ([child] if isinstance(
                    child, (ast.For, ast.While)) else [])
                if child is call:
                    loops.extend(stack)
                    return True
                if walk(child, st):
                    return True
            return False

        walk(fi.node, [])
        for loop in loops:
            for node in ast.walk(loop):
                if not isinstance(node, ast.ExceptHandler):
                    continue
                caught = _caught_names(node)
                if not (caught & (_TRANSPORT_TYPES | _TRANSPORT_GENERIC
                                  | {"OSError", "Exception"})):
                    continue
                if not any(isinstance(n, (ast.Raise, ast.Return))
                           for n in ast.walk(node)):
                    return loop.lineno
        return None

    # ---- RetryPolicy forms: policy.call(fn)/until(fn) where fn rpc's a
    # non-idempotent target, and helper(..., retry=<multi-attempt>)
    def _check_retry_policies(self, fi: FunctionInfo) -> None:
        for call in self.cg.own_calls(fi):
            f = call.func
            # helper(..., non_idempotent_fn, ..., retry=policy)
            retry_kw = next((kw.value for kw in call.keywords
                             if kw.arg in ("retry", "policy")), None)
            if retry_kw is not None:
                fn_names = [a.attr if isinstance(a, ast.Attribute)
                            else a.id for a in call.args
                            if isinstance(a, (ast.Name, ast.Attribute))]
                bad = [n for n in fn_names if self._non_idempotent(n)]
                if bad:
                    attempts = self._policy_attempts(fi, retry_kw)
                    if attempts is None or attempts == 1:
                        continue    # single attempt (or unresolvable)
                    self.findings.append(self._finding(
                        fi, call.lineno,
                        f"non-idempotent rpc fn `{bad[0]}` runs under a "
                        f"RetryPolicy with "
                        f"{'no attempt cap' if attempts == 0 else f'max_attempts={attempts}'}"
                        f" — a transport blip re-submits it",
                        hint="use a max_attempts=1 policy for submits "
                             "(classification only, no re-send) and "
                             "fail over at the router instead"))
                continue
            # policy.call(fn) / policy.until(fn)
            if not (isinstance(f, ast.Attribute)
                    and f.attr in ("call", "until") and call.args):
                continue
            attempts = self._policy_attempts(fi, f.value)
            if attempts is None or attempts == 1:
                continue
            target = None
            a0 = call.args[0]
            if isinstance(a0, (ast.Name, ast.Attribute)):
                target = self.cg._target_function(fi, a0)
            body = target.node if target is not None else (
                a0 if isinstance(a0, ast.Lambda) else None)
            if body is None:
                continue
            for node in ast.walk(body):
                if isinstance(node, ast.Call) \
                        and self._is_rpc_call(fi, node):
                    fn_name = self._fn_arg_name(node)
                    if fn_name and self._non_idempotent(fn_name):
                        self.findings.append(self._finding(
                            fi, call.lineno,
                            f"non-idempotent rpc fn `{fn_name}` is "
                            f"dispatched inside a retried callable "
                            f"(RetryPolicy "
                            f"{'without attempt cap' if attempts == 0 else f'max_attempts={attempts}'}"
                            f") — a lost response double-submits",
                            hint="run submits single-attempt; retry "
                                 "only idempotent calls (poll/probe/"
                                 "snapshot)"))
                        break

    # ---- swallowed transport errors
    def _check_handlers(self, fi: FunctionInfo, makes_rpc: bool) -> None:
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.ExceptHandler):
                continue
            caught = _caught_names(node)
            specific = caught & _TRANSPORT_TYPES
            generic = caught & _TRANSPORT_GENERIC
            if not specific and not (generic and makes_rpc):
                continue
            if not _swallows(node):
                continue
            names = ", ".join(sorted(specific or generic))
            self.findings.append(self._finding(
                fi, node.lineno,
                f"`except {names}` swallows a transport failure "
                f"(pass-only handler) — the dead peer disappears from "
                f"every failure detector above this frame",
                hint="re-raise, classify (wrap/mark the replica), or "
                     "record the miss; if this site is truly "
                     "best-effort, suppress with a reason"))


def _caught_names(h: ast.ExceptHandler) -> Set[str]:
    out: Set[str] = set()
    t = h.type
    exprs = []
    if isinstance(t, ast.Tuple):
        exprs = list(t.elts)
    elif t is not None:
        exprs = [t]
    for e in exprs:
        path = dotted_path(e)
        if path:
            out.add(path[-1])
    return out


def _swallows(h: ast.ExceptHandler) -> bool:
    """True when the handler body does NOTHING (pass/continue/ellipsis
    only) — anything else (a call, an assignment, a return value, a
    raise) counts as classifying."""
    for s in h.body:
        if isinstance(s, ast.Pass) or isinstance(s, ast.Continue):
            continue
        if isinstance(s, ast.Expr) and isinstance(s.value, ast.Constant):
            continue    # docstring / ellipsis
        return False
    return True


def analyze_rpc(project: Project, cg: CallGraph) -> List[Finding]:
    return RpcAnalysis(project, cg).run().findings
