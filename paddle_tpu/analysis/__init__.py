"""tpu_lint: trace-discipline static analysis for the TPU-native stack.

Runtime guards (``retrace_guard``, the numerics watchdog, the serving
compile counters) catch compile-discipline violations *after* the
recompile/sync already burned time. This package catches the same classes
of bug at review time, from source alone — no jax import, no backend:

==== =================================================================
R1   host sync in trace-reachable or hot dispatch code
R2   retrace hazards (branch on tracer, tracer formatting, jit-in-loop)
R3   donation-after-use of a donated buffer
R4   PRNG key reuse without split/fold_in
R5   shared state bypassing its majority-use lock in threaded classes
R6   lock-order cycles / non-reentrant re-entry (interprocedural)
R7   blocking work (sync/dispatch/sleep/wait/IO/rpc) under a held lock
R8   mesh-axis & sharding discipline (axes, frozen resize, shard_map)
R9   resource-lifecycle leaks on exception paths (pin/commit/abort,
     adapter pins, staged .tmp publishes)
R10  SPMD collective divergence (rank-tainted branches, asymmetric
     collective sequences)
R11  rpc deadline/idempotence discipline (unbounded calls, retried
     submits, swallowed transport errors)
==== =================================================================

Entry point::

    from paddle_tpu.analysis import analyze
    result = analyze("/repo", ["paddle_tpu", "tools"])
    for f in result.findings: print(f.render())
    result.lock_graph     # nodes + acquisition sites + order edges
    result.timing         # per-file parse/lint ms, per-rule totals

CLI: ``tools/tpu_lint.py`` (human + ``--json``, baseline gate, the
``.tpu_lint_cache/`` incremental engine and ``--changed-only``). See the
README's "Static analysis (tpu_lint)" section for the rule catalog and
the suppression / baseline-update policy.
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from typing import Dict, List

from .baseline import diff_baseline, load_baseline, save_baseline
from .callgraph import CallGraph, build_callgraph
from .model import Finding, Project, load_project
from .rules import FileTimer, RULE_DOCS, run_rules

__all__ = ["analyze", "AnalysisResult", "Finding", "RULE_DOCS",
           "load_baseline", "save_baseline", "diff_baseline"]


@dataclass
class AnalysisResult:
    project: Project
    callgraph: CallGraph
    findings: List[Finding] = field(default_factory=list)
    lock_graph: dict = field(default_factory=dict)
    lifecycle_graph: dict = field(default_factory=dict)
    timing: dict = field(default_factory=dict)

    @property
    def by_rule(self) -> Dict[str, List[Finding]]:
        out: Dict[str, List[Finding]] = {}
        for f in self.findings:
            out.setdefault(f.rule, []).append(f)
        return out

    def stats(self) -> dict:
        fns = self.project.functions.values()
        return {
            "files": len(self.project.files),
            "functions": len(self.project.functions),
            "trace_roots": len(self.callgraph.trace_roots),
            "trace_reachable": sum(f.trace_reachable for f in fns),
            "thread_roots": len(self.callgraph.thread_roots),
            "thread_reachable": sum(f.thread_reachable for f in fns),
            "locks": len(self.lock_graph.get("locks", ())),
            "lock_edges": len(self.lock_graph.get("edges", ())),
            "findings": {r: len(v) for r, v in sorted(
                self.by_rule.items())},
        }

    def project_imports(self) -> Dict[str, List[str]]:
        """rel -> rels of project files it imports (the incremental
        engine's one-hop closure input). Uses the same
        ``alias_modules`` derivation as the cache's fresh-parse overlay
        so the two sides of the ``--changed-only`` graph can't drift."""
        from .model import alias_modules

        out: Dict[str, List[str]] = {}
        for sf in self.project.files:
            deps = set()
            for alias in sf.aliases.values():
                for m in alias_modules(alias):
                    target = self.project.modules.get(m)
                    if target is not None and target is not sf:
                        deps.add(target.rel)
            out[sf.rel] = sorted(deps)
        return out


def analyze(root: str, paths: List[str]) -> AnalysisResult:
    """Run every rule over the .py files under ``paths`` (relative to
    ``root``). Suppressed findings are dropped here; baseline filtering is
    the caller's second stage (``diff_baseline``)."""
    t_start = time.perf_counter()
    abs_paths = [p if os.path.isabs(p) else os.path.join(root, p)
                 for p in paths]
    timer = FileTimer()
    project, findings = load_project(root, abs_paths,
                                     parse_times=timer.parse)
    t_parsed = time.perf_counter()
    cg = build_callgraph(project)
    out = run_rules(project, cg, timer=timer)
    kept = list(findings)   # R0 policy findings are never suppressible
    for f in out.findings:
        sf = next((s for s in project.files if s.rel == f.path), None)
        if sf is not None and sf.suppressed(f.rule, f.line):
            continue
        kept.append(f)
    kept.sort(key=lambda f: (f.path, f.line, f.rule))
    total = time.perf_counter() - t_start
    timing = {
        "total_ms": round(total * 1e3, 3),
        "parse_ms": round((t_parsed - t_start) * 1e3, 3),
        "lint_ms": round((total - (t_parsed - t_start)) * 1e3, 3),
        "rules": out.rule_ms,
        "files": timer.files_ms(),
    }
    return AnalysisResult(project, cg, kept, lock_graph=out.lock_graph,
                          lifecycle_graph=out.lifecycle_graph,
                          timing=timing)
