"""Autocast context (reference ``python/paddle/amp/auto_cast.py``).

O1: matmul/conv-class ops run in low precision (white list), numerically
sensitive ops stay f32 (black list) — implemented by casting *inputs* at the
layer boundary via a thread-local autocast state consulted by the compute
layers. O2: cast the whole model to bf16 (``decorate``).

On TPU the low dtype defaults to bfloat16; float16 is honored if asked.
"""
from __future__ import annotations

import contextlib
import threading
from typing import Optional

import jax.numpy as jnp

from ..framework.dtype import convert_dtype

# ops that benefit from low precision (MXU-bound) — the O1 white list
WHITE_OPS = {"matmul", "linear", "conv", "einsum", "attention"}
# numerically sensitive — always f32 accumulation (the O1 black list)
BLACK_OPS = {"softmax", "log_softmax", "layer_norm", "batch_norm", "reduce",
             "cross_entropy", "exp", "log", "norm"}


class _AutocastState(threading.local):
    def __init__(self):
        self.enabled = False
        self.dtype = jnp.bfloat16
        self.level = "O1"


_state = _AutocastState()


def is_autocast_enabled() -> bool:
    return _state.enabled


def get_autocast_dtype():
    return _state.dtype


@contextlib.contextmanager
def auto_cast(enable: bool = True, custom_white_list=None, custom_black_list=None,
              level: str = "O1", dtype: str = "bfloat16"):
    prev = (_state.enabled, _state.dtype, _state.level)
    _state.enabled = enable
    _state.dtype = convert_dtype(dtype)
    _state.level = level
    try:
        yield
    finally:
        _state.enabled, _state.dtype, _state.level = prev


amp_guard = auto_cast  # legacy alias (fluid.dygraph.amp.amp_guard)


def autocast_call(op_kind: str, *tensors):
    """Cast tensors per the active autocast policy; used by compute layers.

    Returns tensors cast to the autocast dtype when ``op_kind`` is
    white-listed, f32 when black-listed, unchanged otherwise.
    """
    if not _state.enabled:
        return tensors
    if op_kind in WHITE_OPS:
        tgt = _state.dtype
    elif op_kind in BLACK_OPS:
        tgt = jnp.float32
    else:
        return tensors
    out = tuple(t.astype(tgt) if t is not None and hasattr(t, "astype")
                and jnp.issubdtype(jnp.asarray(t).dtype, jnp.floating) else t
                for t in tensors)
    return out


def decorate(models, optimizers=None, level: str = "O2", dtype: str = "bfloat16",
             master_weight: Optional[bool] = None, save_dtype=None):
    """O2 ("pure" low precision): cast model floating params to ``dtype``;
    optimizers should enable multi_precision (f32 master weights) — done here
    when the optimizer supports it (reference ``amp.decorate``)."""
    d = convert_dtype(dtype)
    single = not isinstance(models, (list, tuple))
    model_list = [models] if single else list(models)
    for m in model_list:
        m.to(d)
    if optimizers is not None:
        opt_single = not isinstance(optimizers, (list, tuple))
        opt_list = [optimizers] if opt_single else list(optimizers)
        for opt in opt_list:
            if master_weight is not False:
                opt.multi_precision = True
        if models is None:
            return opt_list[0] if opt_single else opt_list
        return (model_list[0] if single else model_list,
                opt_list[0] if opt_single else opt_list)
    return model_list[0] if single else model_list
