"""Dynamic loss scaling (reference: ``python/paddle/amp/grad_scaler.py:26``
over ``AmpScaler`` ``loss_scaler.py:44``; device kernels
``check_finite_and_unscale_op.cu`` and ``update_loss_scaling_op.cu``).

Functional core: ``scale_state`` is a small pytree carried through the jitted
step; ``unscale_and_update`` checks grads for inf/nan, skips the step on
overflow, and grows/backs off the scale — all inside the compiled program
(no host sync, unlike the reference's found_inf readback).

bf16 training does not need this; it exists for fp16 parity.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def init_scale_state(init_loss_scaling=2.0 ** 15, incr_ratio=2.0, decr_ratio=0.5,
                     incr_every_n_steps=1000, decr_every_n_nan_or_inf=2):
    return {
        "scale": jnp.asarray(init_loss_scaling, jnp.float32),
        "good_steps": jnp.zeros((), jnp.int32),
        "bad_steps": jnp.zeros((), jnp.int32),
        "incr_ratio": incr_ratio,
        "decr_ratio": decr_ratio,
        "incr_every_n_steps": incr_every_n_steps,
        "decr_every_n_nan_or_inf": decr_every_n_nan_or_inf,
    }


def scale_loss(loss, state):
    return loss * state["scale"]


def unscale_and_check(grads, state):
    """Returns (unscaled_grads, found_inf)."""
    inv = 1.0 / state["scale"]
    unscaled = jax.tree.map(lambda g: None if g is None else g * inv, grads,
                            is_leaf=lambda x: x is None)
    leaves = [g for g in jax.tree.leaves(unscaled) if g is not None]
    found = jnp.zeros((), jnp.bool_)
    for g in leaves:
        found = found | ~jnp.all(jnp.isfinite(g))
    return unscaled, found


def update_scale(state, found_inf):
    """Grow/backoff schedule, traced (reference update_loss_scaling)."""
    good = jnp.where(found_inf, 0, state["good_steps"] + 1)
    bad = jnp.where(found_inf, state["bad_steps"] + 1, 0)
    grow = good >= state["incr_every_n_steps"]
    shrink = bad >= state["decr_every_n_nan_or_inf"]
    scale = state["scale"]
    scale = jnp.where(grow, scale * state["incr_ratio"], scale)
    scale = jnp.where(shrink, jnp.maximum(scale * state["decr_ratio"], 1.0), scale)
    return {**state,
            "scale": scale,
            "good_steps": jnp.where(grow, 0, good),
            "bad_steps": jnp.where(shrink, 0, bad)}


class GradScaler:
    """Paddle-shaped wrapper. In a jitted TrainStep, prefer the functional
    helpers; this class packages them for the eager/hapi path and provides
    ``minimize``-style semantics."""

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 use_dynamic_loss_scaling=True):
        self.enable = enable
        self.use_dynamic = use_dynamic_loss_scaling
        self.state = init_scale_state(init_loss_scaling, incr_ratio, decr_ratio,
                                      incr_every_n_steps, decr_every_n_nan_or_inf)

    def scale(self, loss):
        if not self.enable:
            return loss
        return scale_loss(loss, self.state)

    def unscale_(self, grads):
        if not self.enable:
            return grads, jnp.zeros((), jnp.bool_)
        return unscale_and_check(grads, self.state)

    def step(self, optimizer, params, grads):
        """Unscale, skip-on-overflow, update scale. Returns (params, opt_state_updated?)"""
        if not self.enable:
            return optimizer.step(params, grads)
        unscaled, found = unscale_and_check(grads, self.state)
        new_params = optimizer.step(params, unscaled)
        # roll back if overflow: keep old params
        rolled = jax.tree.map(lambda old, new: jnp.where(found, old, new), params, new_params)
        if self.use_dynamic:
            self.state = update_scale(self.state, found)
        return rolled

    def is_enable(self):
        return self.enable

    def get_loss_scaling(self):
        return float(self.state["scale"])

    def state_dict(self):
        return dict(self.state)

    def set_state_dict(self, sd):
        self.state.update(sd)


AmpScaler = GradScaler
