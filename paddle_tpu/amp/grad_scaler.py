"""Dynamic loss scaling (reference: ``python/paddle/amp/grad_scaler.py:26``
over ``AmpScaler`` ``loss_scaler.py:44``; device kernels
``check_finite_and_unscale_op.cu`` and ``update_loss_scaling_op.cu``).

Functional core: ``scale_state`` is a small pytree carried through the jitted
step; ``unscale_and_update`` checks grads for inf/nan, skips the step on
overflow, and grows/backs off the scale — all inside the compiled program
(no host sync, unlike the reference's found_inf readback).

bf16 training does not need this; it exists for fp16 parity.
"""
from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp


def init_scale_state(init_loss_scaling=2.0 ** 15, incr_ratio=2.0, decr_ratio=0.5,
                     incr_every_n_steps=1000, decr_every_n_nan_or_inf=2):
    return {
        "scale": jnp.asarray(init_loss_scaling, jnp.float32),
        "good_steps": jnp.zeros((), jnp.int32),
        "bad_steps": jnp.zeros((), jnp.int32),
        "incr_ratio": incr_ratio,
        "decr_ratio": decr_ratio,
        "incr_every_n_steps": incr_every_n_steps,
        "decr_every_n_nan_or_inf": decr_every_n_nan_or_inf,
    }


def scale_loss(loss, state):
    return loss * state["scale"]


def unscale_and_check(grads, state):
    """Returns (unscaled_grads, found_inf)."""
    inv = 1.0 / state["scale"]
    unscaled = jax.tree.map(lambda g: None if g is None else g * inv, grads,
                            is_leaf=lambda x: x is None)
    leaves = [g for g in jax.tree.leaves(unscaled) if g is not None]
    found = jnp.zeros((), jnp.bool_)
    for g in leaves:
        found = found | ~jnp.all(jnp.isfinite(g))
    return unscaled, found


def update_scale(state, found_inf):
    """Grow/backoff schedule, traced (reference update_loss_scaling)."""
    good = jnp.where(found_inf, 0, state["good_steps"] + 1)
    bad = jnp.where(found_inf, state["bad_steps"] + 1, 0)
    grow = good >= state["incr_every_n_steps"]
    shrink = bad >= state["decr_every_n_nan_or_inf"]
    scale = state["scale"]
    scale = jnp.where(grow, scale * state["incr_ratio"], scale)
    scale = jnp.where(shrink, jnp.maximum(scale * state["decr_ratio"], 1.0), scale)
    return {**state,
            "scale": scale,
            "good_steps": jnp.where(grow, 0, good),
            "bad_steps": jnp.where(shrink, 0, bad)}


class GradScaler:
    """Paddle-shaped wrapper. In a jitted TrainStep, prefer the functional
    helpers (or pass the scaler to ``TrainStep(scaler=...)`` /
    ``Model.prepare(amp_configs={"scaler": ...})`` which fuses them); this
    class packages them for the eager path and provides ``minimize``-style
    semantics.

    Skip accounting: :attr:`skipped_step_count` / :attr:`last_overflow_step`
    report how many optimizer updates the scaler suppressed on overflow and
    the 1-based index of the latest one — so user code and the numerics
    watchdog can tell ordinary scaler inf-skips from watchdog anomaly
    skips. A fused TrainStep records its overflow flags LAZILY (device
    scalars, no per-step host sync); reading either property forces the
    pending flags.
    """

    def __init__(self, enable=True, init_loss_scaling=2.0 ** 15, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
                 use_dynamic_loss_scaling=True):
        self.enable = enable
        self.use_dynamic = use_dynamic_loss_scaling
        self.state = init_scale_state(init_loss_scaling, incr_ratio, decr_ratio,
                                      incr_every_n_steps, decr_every_n_nan_or_inf)
        self._step_counter = 0     # update steps observed (eager or fused)
        self._skipped = 0
        self._last_overflow = None
        self._pending = []         # [(step_idx, lazy found_inf flag)]

    def scale(self, loss):
        if not self.enable:
            return loss
        return scale_loss(loss, self.state)

    def unscale_(self, grads):
        if not self.enable:
            return grads, jnp.zeros((), jnp.bool_)
        return unscale_and_check(grads, self.state)

    def step(self, optimizer, params, grads):
        """Unscale, skip-on-overflow, update scale. Returns (params, opt_state_updated?)"""
        if not self.enable:
            return optimizer.step(params, grads)
        unscaled, found = unscale_and_check(grads, self.state)
        new_params = optimizer.step(params, unscaled)
        # roll back if overflow: keep old params
        rolled = jax.tree.map(lambda old, new: jnp.where(found, old, new), params, new_params)
        if self.use_dynamic:
            self.state = update_scale(self.state, found)
        self._note_step(found)
        return rolled

    # ------------------------------------------------------ skip accounting
    # bounded: a long run that never reads the counters must not retain one
    # device scalar per step — past this many pending flags they are forced
    # (one host sync per _PENDING_MAX update steps, negligible)
    _PENDING_MAX = 256

    def _note_step(self, found_inf) -> None:
        """Record one update step's overflow flag (may be a lazy device
        scalar; forced when the counters are read or the buffer fills)."""
        self._step_counter += 1
        self._pending.append((self._step_counter, found_inf))
        if len(self._pending) >= self._PENDING_MAX:
            self._sync_pending()

    def _sync_pending(self) -> None:
        if not self._pending:
            return
        # one transfer for the whole buffer, not one round-trip per flag
        # tpu-lint: disable=R1(deliberate batched flush — one device_get per _PENDING_MAX update steps, only when counters are read)
        flags = jax.device_get([flag for _, flag in self._pending])
        for (idx, _), flag in zip(self._pending, flags):
            if bool(flag):
                self._skipped += 1
                self._last_overflow = idx
        self._pending.clear()

    @property
    def skipped_step_count(self) -> int:
        """Optimizer updates suppressed because unscaled grads overflowed."""
        self._sync_pending()
        return self._skipped

    @property
    def last_overflow_step(self):
        """1-based index of the most recent overflow-skipped step (None if
        no step has ever overflowed)."""
        self._sync_pending()
        return self._last_overflow

    def is_enable(self):
        return self.enable

    def get_loss_scaling(self):
        return float(self.state["scale"])

    def state_dict(self):
        return dict(self.state)

    def set_state_dict(self, sd):
        self.state.update(sd)


AmpScaler = GradScaler
