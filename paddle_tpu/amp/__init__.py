"""Automatic mixed precision.

Reference parity: ``python/paddle/amp/`` — ``auto_cast`` (O1 white/black
lists, O2 pure-fp16) and ``GradScaler`` over dynamic loss scaling
(``python/paddle/fluid/dygraph/amp/loss_scaler.py:44``).

TPU-native stance: bfloat16 is the native half type (MXU) and needs NO loss
scaling — ``auto_cast`` defaults to bf16 and GradScaler becomes a pass-through
unless fp16 is requested explicitly. The dynamic-scale machinery
(found_inf detection, scale growth/backoff — reference
``check_finite_and_unscale_op.cu`` / ``update_loss_scaling_op.cu``) is
implemented functionally so it jits into the train step.
"""
from .auto_cast import amp_guard, auto_cast, autocast_call, decorate, is_autocast_enabled  # noqa: F401
from .grad_scaler import AmpScaler, GradScaler  # noqa: F401
