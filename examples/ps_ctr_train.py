"""CTR-style training with a parameter-server SparseEmbedding.

Feature ids are arbitrary int64 hashes (no vocab bound); rows live in a
host-side C++ sparse table and update via the lookup's custom-vjp push —
the HeterPS/PGLBox regime. The dense tower trains as normal jax params in
the SAME jitted step.

    python examples/ps_ctr_train.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401,E402  (cpu-pinned runs skip accelerator discovery)

import numpy as np

import jax
import jax.numpy as jnp


def main():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    from paddle_tpu.distributed.ps import SparseEmbedding
    from paddle_tpu.nn.layer import buffer_state, functional_call, param_state

    class CTRModel(nn.Layer):
        def __init__(self, dim=16):
            super().__init__()
            self.emb = SparseEmbedding(dim, optimizer="adagrad",
                                       learning_rate=0.1, seed=0)
            self.fc1 = nn.Linear(2 * dim, 32)
            self.fc2 = nn.Linear(32, 1)

        def forward(self, user_ids, item_ids):
            u = self.emb(user_ids)
            v = self.emb(item_ids)
            h = jax.nn.relu(self.fc1(jnp.concatenate([u, v], -1)))
            return self.fc2(h)[:, 0]

    pt.seed(0)
    model = CTRModel()
    params = param_state(model)
    buffers = buffer_state(model)

    @jax.jit
    def train_step(params, user_ids, item_ids, labels):
        def loss_fn(p):
            logits, _ = functional_call(model, p, buffers, user_ids, item_ids)
            return jnp.mean(
                jnp.maximum(logits, 0) - logits * labels
                + jnp.log1p(jnp.exp(-jnp.abs(logits))))  # bce-with-logits
        loss, grads = jax.value_and_grad(loss_fn)(params)
        # dense tower SGD; the sparse rows already updated via push
        new_params = jax.tree.map(lambda p, g: p - 0.05 * g, params, grads)
        return loss, new_params

    rng = np.random.default_rng(0)
    for step in range(60):
        # ids are hashes — sparse, unbounded, int64 (bucketed here so the
        # demo's table stays small)
        users = (rng.integers(0, 2**40, 512) % 500).astype(np.int64)
        items = (rng.integers(0, 2**40, 512) % 500).astype(np.int64)
        # synthetic click rule each id's embedding can encode directly
        labels = ((users % 3 == 0) & (items % 2 == 0)).astype(np.float32)
        loss, params = train_step(params, users, items, labels)
        if step % 10 == 0 or step == 59:
            print(f"step {step:3d}  loss {float(loss):.4f}  "
                  f"table rows {len(model.emb.table)}")


if __name__ == "__main__":
    main()
