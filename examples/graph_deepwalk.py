"""DeepWalk node embeddings on the native graph engine.

Builds a CSR graph in the C++ store, generates random-walk skip-gram
batches with negative samples on a host thread (the reference's
``GraphDataGenerator``/``pre_build_thread`` overlap pattern), and trains
embeddings with a jitted step.

    python examples/graph_deepwalk.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401,E402  (cpu-pinned runs skip accelerator discovery)

import numpy as np

import jax
import jax.numpy as jnp


def main():
    from paddle_tpu.distributed.ps.graph import (GraphDataGenerator,
                                                 GraphTable)

    # ring-of-cliques graph: 8 cliques of 16 nodes, ring-linked
    rng = np.random.default_rng(0)
    src, dst = [], []
    n_cliques, k = 8, 16
    for c in range(n_cliques):
        base = c * k
        for i in range(k):
            for j in range(i + 1, k):
                src += [base + i, base + j]
                dst += [base + j, base + i]
        nxt = ((c + 1) % n_cliques) * k
        src += [base, nxt]
        dst += [nxt, base]
    g = GraphTable()
    g.add_edges(np.asarray(src, np.int64), np.asarray(dst, np.int64))
    g.build()
    n = n_cliques * k
    print(f"graph: {n} nodes, {len(src)} edges")

    dim = 32
    emb = jnp.asarray(rng.normal(size=(n, dim), scale=0.1), jnp.float32)

    @jax.jit
    def step(emb, centers, contexts, negatives):
        def loss_fn(e):
            ce, xe, ne = e[centers], e[contexts], e[negatives]
            pos = jnp.sum(ce * xe, -1)
            neg = jnp.einsum("bd,bkd->bk", ce, ne)
            return (jnp.mean(jax.nn.softplus(-pos))
                    + jnp.mean(jax.nn.softplus(neg)))
        loss, grad = jax.value_and_grad(loss_fn)(emb)
        # mean-reduced loss spreads each row's gradient over the batch, so
        # the embedding-table step wants a large lr
        return emb - 5.0 * grad, loss

    for epoch in range(30):
        gen = GraphDataGenerator(g, batch_size=1024, walk_len=8, window=2,
                                 num_neg=4, seed=epoch)
        for centers, contexts, negatives in gen:
            emb, loss = step(emb, centers, contexts, negatives)
        if epoch % 10 == 0 or epoch == 29:
            print(f"epoch {epoch:2d}  loss {float(loss):.4f}")

    # same-clique nodes should now be closer than cross-clique ones
    norm = emb / jnp.linalg.norm(emb, axis=-1, keepdims=True)
    same = float(jnp.mean(jnp.sum(norm[0] * norm[1:k], -1)))
    cross = float(jnp.mean(jnp.sum(norm[0] * norm[3 * k:4 * k], -1)))
    print(f"cosine same-clique {same:.3f} vs cross-clique {cross:.3f}")


if __name__ == "__main__":
    main()
