"""Train -> export StableHLO -> serve from Python (and plain C), plus
compiled KV-cache text generation.

``paddle_tpu.jit.save`` writes the reference's artifact pair: ``.pdmodel``
(serialized StableHLO — the portable IR, loadable under any XLA runtime)
and ``.pdiparams`` (weights). The Python ``Predictor`` serves it here;
``native/capi/infer_capi.h`` + ``tools/infer_demo.c`` serve the SAME
artifact from C with no Python. The second half demos the serving path
for decoder LMs: ``GPTForCausalLM.generate`` — O(1)-compile autoregressive
decode against a preallocated KV cache (``models/generation.py``).

    python examples/export_serving.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401,E402  (cpu-pinned runs skip accelerator discovery)

import numpy as np


def main():
    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.inference import Config, create_predictor
    from paddle_tpu.jit import InputSpec, save
    from paddle_tpu.optimizer import AdamW

    pt.seed(0)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Linear(32, 3))
    step = pt.TrainStep(model, AdamW(learning_rate=1e-2),
                        loss_fn=lambda out, b: F.cross_entropy(out, b[1]))
    rng = np.random.default_rng(0)
    x = rng.standard_normal((64, 8)).astype(np.float32)
    y = rng.integers(0, 3, 64)
    for _ in range(30):
        loss = step((x, y))
    print(f"trained to loss {float(loss):.4f}")
    step.sync_to_model()

    # export: dynamic batch via InputSpec(None, ...)
    save(model, "/tmp/demo_model",
         input_spec=[InputSpec(shape=[None, 8], dtype="float32")])
    print("exported /tmp/demo_model.pdmodel (+ .pdiparams)")

    pred = create_predictor(Config("/tmp/demo_model"))
    out = pred.run([x[:5]])[0]
    ref = np.asarray(model(pt.to_tensor(x[:5])))
    np.testing.assert_allclose(out, ref, rtol=1e-5)
    print("predictor output matches the eager model; batch is dynamic:",
          pred.run([x[:17]])[0].shape)

    generate_demo()


def generate_demo():
    """Batched autoregressive decode on gpt_tiny: #buckets_used + 1
    compiled programs total, per-token cost O(L) against the KV cache."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(0)
    lm = GPTForCausalLM(gpt_tiny(hidden_dropout_prob=0.0,
                                 attention_dropout_prob=0.0,
                                 use_flash_attention=False))
    lm.eval()
    prompts = np.random.default_rng(0).integers(
        1, 1024, (2, 12)).astype(np.int32)
    tokens, stats = lm.generate(
        prompts, max_new_tokens=8, max_length=64, prefill_buckets=(16, 32),
        do_sample=True, temperature=0.9, top_k=40, seed=7, return_stats=True)
    cc = stats["compile_stats"]
    print(f"generated {tokens.shape[1]} tokens/seq for {tokens.shape[0]} "
          f"prompts: {tokens[0].tolist()} ...")
    print(f"decode engine: {cc['prefill']['compiles']} prefill + "
          f"{cc['decode']['compiles']} decode compile(s), "
          f"ttft {stats['ttft_s'] * 1e3:.1f} ms, "
          f"{stats['tokens_per_sec']:.0f} tokens/s")


if __name__ == "__main__":
    main()
