"""GPT pretraining with the fused TrainStep — the flagship workflow.

Runs a tiny config by default (CPU-friendly, seconds); ``--bench`` runs
the 350M-class configuration bench.py records on real TPU hardware.

    python examples/gpt_pretrain.py
    python examples/gpt_pretrain.py --bench   # needs a TPU-class chip
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401,E402  (cpu-pinned runs skip accelerator discovery)

import argparse
import time

import numpy as np

import paddle_tpu as pt
from paddle_tpu import amp
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.optimizer import AdamW


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench", action="store_true",
                    help="350M-class TPU config instead of the tiny demo")
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    if args.bench:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                        num_heads=16, max_position_embeddings=1024,
                        use_flash_attention=True, loss_chunk=256,
                        dtype="bfloat16")
        batch, seq = 8, 1024
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=128)
        batch, seq = 4, 64

    pt.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=3e-4, weight_decay=0.01)
    if args.bench:
        # O2: bf16 compute, f32 master weights held by the optimizer
        model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    # forward(ids, labels) returns the shifted LM loss itself (chunked and
    # fused with the head projection when cfg.loss_chunk is set)
    step = pt.TrainStep(model, opt, loss_fn=None)

    # recompile-proof input pipeline: documents yield VARIABLE-length token
    # runs and the corpus size leaves a ragged tail batch — exactly the
    # stream that would retrace XLA once per novel shape. The loader's
    # pad_batches/length_buckets bound the shape set, and the async device
    # prefetch overlaps the host->HBM hop with the running step.
    rng = np.random.default_rng(0)
    n_docs = batch * args.steps + batch // 2      # ragged tail on purpose
    lengths = (seq // 2, seq)   # two buckets: enough to show the policy
                                # without a third demo-only XLA compile

    class TokenDocs(pt.io.Dataset):
        def __len__(self):
            return n_docs

        def __getitem__(self, i):
            L = lengths[(i // batch) % len(lengths)]
            ids = rng.integers(0, cfg.vocab_size, L).astype(np.int32)
            return ids, ids  # (input ids, labels)

    loader = pt.io.DataLoader(TokenDocs(), batch_size=batch, shuffle=False,
                              pad_batches=True,
                              length_buckets=lengths)
    t0 = time.perf_counter()
    tokens = 0
    i = 0
    prefetch = pt.io.prefetch_to_device(iter(loader), depth=2)
    from contextlib import ExitStack

    with ExitStack() as stack:  # guard + prefetch released on ANY exit
        stack.callback(prefetch.close)
        for ids_b, labels_b, valid in prefetch:
            loss = step((ids_b, labels_b))
            tokens += int(np.prod(ids_b.shape))
            if i % 5 == 0:
                print(f"step {i:4d}  loss {float(loss):.4f}  "
                      f"shape {tuple(ids_b.shape)}  "
                      f"valid {int(np.asarray(valid).sum())}")
            i += 1
            if i == len(lengths):
                # warmup traced one program per bucket; from here on any
                # recompile is a pipeline bug — fail loudly
                stack.enter_context(
                    pt.framework.compile_cache.retrace_guard(max_compiles=0))
    dt = time.perf_counter() - t0
    stats = step.cache_stats()
    print(f"{tokens / dt:,.0f} tokens/s (incl. compile) on {pt.get_device()}")
    print(f"compiled {stats['compiles']} program(s) over {stats['calls']} "
          f"steps (cache hits {stats['cache_hits']}); "
          f"h2d stall {prefetch.stats()['consumer_stall_s'] * 1e3:.0f}ms")

    # checkpoint + resume
    step.sync_to_model()
    pt.save(model.state_dict(), "/tmp/gpt_demo.pdparams")
    print("saved /tmp/gpt_demo.pdparams")


if __name__ == "__main__":
    main()
