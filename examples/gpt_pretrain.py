"""GPT pretraining with the fused TrainStep — the flagship workflow.

Runs a tiny config by default (CPU-friendly, seconds); ``--bench`` runs
the 350M-class configuration bench.py records on real TPU hardware.

    python examples/gpt_pretrain.py
    python examples/gpt_pretrain.py --bench   # needs a TPU-class chip
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import _env  # noqa: F401,E402  (cpu-pinned runs skip accelerator discovery)

import argparse
import time

import numpy as np

import paddle_tpu as pt
from paddle_tpu import amp
from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
from paddle_tpu.optimizer import AdamW


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--bench", action="store_true",
                    help="350M-class TPU config instead of the tiny demo")
    ap.add_argument("--steps", type=int, default=20)
    args = ap.parse_args()

    if args.bench:
        cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                        num_heads=16, max_position_embeddings=1024,
                        use_flash_attention=True, loss_chunk=256,
                        dtype="bfloat16")
        batch, seq = 8, 1024
    else:
        cfg = GPTConfig(vocab_size=1024, hidden_size=128, num_layers=2,
                        num_heads=4, max_position_embeddings=128)
        batch, seq = 4, 64

    pt.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=3e-4, weight_decay=0.01)
    if args.bench:
        # O2: bf16 compute, f32 master weights held by the optimizer
        model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    # forward(ids, labels) returns the shifted LM loss itself (chunked and
    # fused with the head projection when cfg.loss_chunk is set)
    step = pt.TrainStep(model, opt, loss_fn=None)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, (batch, seq)).astype(np.int32)
    t0 = time.perf_counter()
    for i in range(args.steps):
        loss = step((ids, ids))
        if i % 5 == 0 or i == args.steps - 1:
            print(f"step {i:4d}  loss {float(loss):.4f}")
    dt = time.perf_counter() - t0
    print(f"{batch * seq * args.steps / dt:,.0f} tokens/s "
          f"(incl. compile) on {pt.get_device()}")

    # checkpoint + resume
    step.sync_to_model()
    pt.save(model.state_dict(), "/tmp/gpt_demo.pdparams")
    print("saved /tmp/gpt_demo.pdparams")


if __name__ == "__main__":
    main()
