"""Shared example environment guard — import before anything touches a
jax array.

When the caller pins CPU (``JAX_PLATFORMS=cpu``), images that tunnel a
TPU need two things BEFORE the first array op: the accelerator plugin's
pool address cleared (its discovery can block indefinitely when the
tunnel is down), and the jax platform config actually flipped —
interpreter-startup hooks may have registered the accelerator platform
already, so the env var alone is not enough. ``set_device("cpu")`` does
the config flip the supported way.
"""
import os

if os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
    os.environ["PALLAS_AXON_POOL_IPS"] = ""
    import sys

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import paddle_tpu

    paddle_tpu.set_device("cpu")
