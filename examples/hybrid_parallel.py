"""Hybrid parallelism as configuration: dp x mp (+ ZeRO-2) on a device mesh.

This demo builds an 8-device VIRTUAL CPU mesh — exactly how the test
suite validates every sharding in CI, on any machine. On a real pod
slice, drop the ``set_device("cpu")`` line and the same code lays the
mesh over the physical chips.

    python examples/hybrid_parallel.py
"""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "xla_force_host_platform_device_count" not in os.environ.get("XLA_FLAGS", ""):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=8")
import _env  # noqa: F401,E402  (cpu-pinned runs skip accelerator discovery)

import numpy as np


def main():
    import paddle_tpu as pt

    # the demo mesh is the virtual CPU one; flip BEFORE any array op
    # (on a real slice, remove this line)
    pt.set_device("cpu")
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import fleet
    from paddle_tpu.distributed.fleet import DistributedStrategy
    from paddle_tpu.optimizer import AdamW

    from paddle_tpu.distributed.parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)

    s = DistributedStrategy()
    s.hybrid_configs = {"dp_degree": 2, "mp_degree": 4}
    s.sharding = True
    s.sharding_configs = {"stage": 2}      # ZeRO-2 over the dp axis
    fleet.init(strategy=s)

    pt.seed(0)
    # TP is explicit layer choice, exactly like the reference's
    # fleet.meta_parallel mpu layers: Column splits the output dim across
    # the mp axis, Row splits the input dim and reduces — XLA inserts the
    # collectives from the sharding annotations
    model = nn.Sequential(ColumnParallelLinear(64, 256), nn.ReLU(),
                          RowParallelLinear(256, 10))
    opt = AdamW(learning_rate=1e-3)
    step = fleet.distributed_model(
        model, opt, loss_fn=lambda out, b: F.cross_entropy(out, b[1]))

    rng = np.random.default_rng(0)
    x = rng.standard_normal((32, 64)).astype(np.float32)  # 32 % dp==0
    y = rng.integers(0, 10, 32)
    for i in range(10):
        loss = step((x, y))
        if i % 3 == 0:
            print(f"step {i}  loss {float(loss):.4f}")

    # the mesh placement is real: inspect the weight shardings
    for name, p in step.params.items():
        if getattr(p, "ndim", 0) == 2:
            print(f"param {name!r} sharding: {p.sharding.spec}")


if __name__ == "__main__":
    main()
