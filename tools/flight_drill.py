#!/usr/bin/env python
"""Flight-recorder crash drill: prove a crash leaves a usable artifact.

Serves one seeded request through a tiny continuous-batching server with
a seeded :class:`FaultPlan` injected at the ``serve.step`` site. The
fault resets the engine mid-decode (the crash-recovery path), which must
write a flight-recorder dump. The drill then asserts the postmortem is
actually usable:

- the dump exists, parses, and carries the ``flight_recorder`` format
  marker + ``engine_reset`` reason;
- the failing request's correlation id appears in the dump (both the
  ``inflight`` list and its span tail), so an operator can walk from the
  artifact to the exact request timeline;
- the request itself still COMPLETED with the right number of tokens
  (the crash drill must not cost availability);
- the dump's span list round-trips through ``tools/trace_view.py``'s
  merge (the artifact is consumable, not just well-formed JSON).

Used standalone and as the ``robustness_gate.py --observability`` crash
stage; ``tests/test_observability.py`` drives :func:`run_drill` in-proc.

    python tools/flight_drill.py
    python tools/flight_drill.py --dir /tmp/drill --new-tokens 8
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def run_drill(dump_dir: str, new_tokens: int = 6, model=None) -> dict:
    """Run the crash drill, dumping into ``dump_dir``; returns a result
    dict with ``ok`` plus per-check booleans (all must hold)."""
    import paddle_tpu as pt
    from paddle_tpu.distributed.resilience import FaultPlan
    from paddle_tpu.observability import flight
    from paddle_tpu.serving import InferenceServer

    flight.configure(dump_dir=dump_dir)
    if model is None:
        from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

        pt.seed(7)
        cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                       use_flash_attention=False)
        model = GPTForCausalLM(cfg)
        model.eval()
    vocab = model.cfg.vocab_size
    srv = InferenceServer(model, slots=2, max_length=64,
                          prefill_buckets=(16,), max_request_retries=1)
    prompt = np.random.default_rng(0).integers(
        0, vocab, (10,)).astype(np.int32)
    plan = FaultPlan([{"site": "serve.step", "kind": "drop", "times": 1}],
                     seed=3)
    before = flight.flight_recorder().stats()["dumps_written"]
    with plan, warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        handle = srv.submit(prompt, max_new_tokens=int(new_tokens),
                            seed=11)
        out = handle.result(timeout=300)
    srv.shutdown(drain=True, timeout=60)
    corr = handle.correlation_id

    result = {"ok": False, "correlation_id": corr, "dump_path": None,
              "fault_fired": bool(plan.fired and plan.fired[0] == 1),
              "request_completed": int(out.shape[0]) == int(new_tokens)}
    rec = flight.flight_recorder()
    result["dump_written"] = (rec.stats()["dumps_written"] == before + 1)
    path = rec.stats()["last_dump_path"]
    result["dump_path"] = path
    if not (result["fault_fired"] and result["dump_written"] and path):
        return result
    with open(path) as f:
        dump = json.load(f)
    result["well_formed"] = (
        dump.get("format") == "flight_recorder"
        and dump.get("reason") == "engine_reset"
        and dump.get("pid") == os.getpid()
        and isinstance(dump.get("events"), list)
        and isinstance(dump.get("spans"), list))
    result["corr_in_dump"] = (
        dump.get("correlation_id") == corr
        and corr in (dump.get("extra", {}).get("inflight") or []))
    result["corr_in_spans"] = any(s.get("corr") == corr
                                  for s in dump.get("spans", []))
    # the artifact must be consumable by the merge tool, not just valid
    from trace_view import load_spans, merge_chrome

    spans, kind = load_spans(path)
    merged = merge_chrome(spans, corr=corr)
    lanes = {ev["tid"] for ev in merged["traceEvents"]
             if ev["ph"] in ("X", "i")}
    result["trace_view_merge"] = kind == "flight" and len(lanes) == 1
    result["ok"] = all(v for k, v in result.items()
                       if k != "ok" and isinstance(v, bool))
    return result


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--dir", default=None,
                    help="dump directory (default: fresh temp dir)")
    ap.add_argument("--new-tokens", type=int, default=6)
    args = ap.parse_args(argv)
    dump_dir = args.dir or tempfile.mkdtemp(prefix="pt_flight_drill_")
    result = run_drill(dump_dir, new_tokens=args.new_tokens)
    print(json.dumps(result))
    if not result["ok"]:
        failed = [k for k, v in result.items()
                  if isinstance(v, bool) and not v and k != "ok"]
        print(f"FAIL: flight drill checks failed: {failed}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
