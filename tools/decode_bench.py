"""Decode-throughput bench for the compiled KV-cache generation engine.

Measures the two serving numbers that matter — tokens/s and
time-to-first-token — for batched greedy decode through
``models.generation``, plus the compile discipline (prefill/decode
program counts must be ``#buckets_used + 1``). Prints ONE JSON line:

    {"metric": "gpt_decode_tokens_per_sec", "value": N, "unit":
     "tokens/s", "extra": {"ttft_ms": ..., "decode_tokens_per_sec": ...,
     "prefill_compiles": ..., "decode_compiles": ..., ...}}

Runs on any backend (tier-1 invokes it with JAX_PLATFORMS=cpu on the
tiny config; on TPU pass --preset serving for a 350M-class model).

Speculative decoding and int8 KV-cache quantization are measured with
the same harness: ``--speculative K`` swaps in
``models.speculative.SpeculativeEngine`` (weight-copied truncated
draft, ``--draft-layers`` deep) and the record grows acceptance-rate
and tokens-per-target-dispatch stats; ``--kv-dtype int8`` quantizes
the cache and the record reports cache bytes. ``--json-out`` runs the
plain engine first and writes a paired before/after artifact (same
shape as ``bench_profile.py --distributed``) so the speedup is
self-contained in one file.

    python tools/decode_bench.py
    python tools/decode_bench.py --model llama --batch 8 --new-tokens 128
    python tools/decode_bench.py --preset serving   # TPU-sized config
    python tools/decode_bench.py --preset small --speculative 4 \
        --kv-dtype int8 --json-out /tmp/decode.json
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def build_model(family: str, preset: str):
    import paddle_tpu as pt

    pt.seed(0)
    if family == "gpt":
        from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM, gpt_tiny

        if preset == "serving":
            cfg = GPTConfig(vocab_size=50304, hidden_size=1024,
                            num_layers=24, num_heads=16,
                            max_position_embeddings=1024,
                            hidden_dropout_prob=0.0,
                            attention_dropout_prob=0.0, dtype="bfloat16")
        elif preset == "small":
            # CPU-runnable but COMPUTE-bound (tiny is dispatch-bound, so
            # prefill-vs-cache effects vanish in launch overhead) — the
            # config serve_bench's prefix-cache acceptance runs use
            cfg = GPTConfig(vocab_size=2048, hidden_size=256,
                            num_layers=4, num_heads=8,
                            max_position_embeddings=512,
                            hidden_dropout_prob=0.0,
                            attention_dropout_prob=0.0,
                            use_flash_attention=False)
        else:
            cfg = gpt_tiny(hidden_dropout_prob=0.0,
                           attention_dropout_prob=0.0,
                           use_flash_attention=False)
        return GPTForCausalLM(cfg), cfg
    from paddle_tpu.models.llama import (LlamaConfig, LlamaForCausalLM,
                                         llama_tiny)

    if preset == "serving":
        cfg = LlamaConfig(vocab_size=32000, hidden_size=1024, num_layers=24,
                          num_heads=16, num_kv_heads=4,
                          max_position_embeddings=1024, dtype="bfloat16")
    elif preset == "small":
        cfg = LlamaConfig(vocab_size=2048, hidden_size=256, num_layers=4,
                          num_heads=8, num_kv_heads=4,
                          max_position_embeddings=512,
                          use_flash_attention=False)
    else:
        cfg = llama_tiny(use_flash_attention=False)
    return LlamaForCausalLM(cfg), cfg


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=("gpt", "llama"), default="gpt")
    ap.add_argument("--preset", choices=("tiny", "small", "serving"), default="tiny",
                    help="tiny: CPU-safe smoke config; serving: 350M-class")
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--prompt-len", type=int, default=24)
    ap.add_argument("--new-tokens", type=int, default=64)
    ap.add_argument("--buckets", type=int, nargs="+", default=None,
                    help="prefill length buckets (default: engine default)")
    ap.add_argument("--trace-overhead", type=int, nargs="?", const=3,
                    default=0, metavar="REPS",
                    help="measure tracing-on vs tracing-off decode "
                         "throughput (best of REPS runs each, default 3); "
                         "exits non-zero if the overhead exceeds "
                         "--trace-overhead-pct")
    ap.add_argument("--trace-overhead-pct", type=float, default=2.0,
                    help="max acceptable tracing overhead, percent")
    ap.add_argument("--speculative", type=int, default=0, metavar="K",
                    help="draft-model speculative decoding: propose K "
                         "tokens per round (0 = plain engine)")
    ap.add_argument("--draft-layers", type=int, default=1,
                    help="layers kept in the weight-copied draft model")
    ap.add_argument("--kv-dtype", choices=("none", "int8"), default="none",
                    help="KV-cache storage dtype (int8 = quantized)")
    ap.add_argument("--json-out", default=None, metavar="PATH",
                    help="write a paired before/after summary (plain "
                         "engine vs the configured one) to PATH")
    args = ap.parse_args(argv)

    import jax

    from paddle_tpu.framework import compile_cache
    from paddle_tpu.models.generation import (GenerationEngine, cache_nbytes,
                                              init_cache, normalize_kv_dtype)
    from paddle_tpu.observability import default_registry, tracing

    model, cfg = build_model(args.model, args.preset)
    model.eval()
    kv_dtype = normalize_kv_dtype(
        None if args.kv_dtype == "none" else args.kv_dtype)
    spec_k = max(0, args.speculative)
    max_length = min(cfg.max_position_embeddings,
                     args.prompt_len + args.new_tokens + 8 + spec_k)
    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size,
                       (args.batch, args.prompt_len)).astype(np.int32)

    def build_engine(k: int, kv):
        if k:
            from paddle_tpu.models.speculative import (SpeculativeEngine,
                                                       build_draft_model)
            draft = build_draft_model(model, num_layers=args.draft_layers)
            return SpeculativeEngine(model, draft, k=k,
                                     max_length=max_length,
                                     prefill_buckets=args.buckets,
                                     kv_dtype=kv, draft_kv_dtype=kv)
        return GenerationEngine(model, max_length=max_length,
                                prefill_buckets=args.buckets, kv_dtype=kv)

    def measure(k: int, kv):
        """Warm up (pays the compiles), then time one pure-dispatch run."""
        engine = build_engine(k, kv)
        t_warm = time.perf_counter()
        engine.generate(ids, max_new_tokens=args.new_tokens)
        warmup_s = time.perf_counter() - t_warm
        before = compile_cache.cache_stats()["compiles"]
        out, stats = engine.generate(ids, max_new_tokens=args.new_tokens,
                                     return_stats=True)
        after = compile_cache.cache_stats()["compiles"]
        extra = {
            "ttft_ms": round(stats["ttft_s"] * 1e3, 2),
            "decode_tokens_per_sec": round(stats["decode_tokens_per_sec"], 1),
            "new_tokens": int(out.shape[1]),
            "batch": args.batch,
            "prompt_len": args.prompt_len,
            "prefill_bucket": stats["prefill_bucket"],
            "steady_state_recompiles": after - before,
            "warmup_s": round(warmup_s, 2),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "preset": args.preset,
            "mode": "speculative" if k else "plain",
            "kv_dtype": kv or "full",
            "cache_bytes": cache_nbytes(
                init_cache(model, args.batch, max_length, kv_dtype=kv)),
        }
        for name, family in stats["compile_stats"].items():
            extra[f"{name}_compiles"] = family["compiles"]
        if k:
            extra.update(
                k=stats["k"],
                draft_layers=args.draft_layers,
                rounds=stats["rounds"],
                acceptance_rate=round(stats["acceptance_rate"], 4),
                tokens_per_target_dispatch=round(
                    stats["tokens_per_target_dispatch"], 3),
            )
        record = {
            "metric": f"{args.model}_decode_tokens_per_sec",
            "value": round(stats["tokens_per_sec"], 1),
            "unit": "tokens/s",
            "extra": extra,
        }
        return record, after - before

    if args.trace_overhead:
        # the observability gate: per-token span recording on the decode
        # hot loop must cost <--trace-overhead-pct of throughput.
        # Best-of-REPS per mode filters scheduler noise on shared boxes;
        # modes alternate so drift hits both equally.
        engine = build_engine(spec_k, kv_dtype)
        engine.generate(ids, max_new_tokens=args.new_tokens)  # pay compiles
        reps = max(1, int(args.trace_overhead))
        best = {True: 0.0, False: 0.0}
        was_enabled = tracing.enabled()
        try:
            for _ in range(reps):
                for mode in (False, True):
                    tracing.enable(mode)
                    _, stats = engine.generate(
                        ids, max_new_tokens=args.new_tokens,
                        return_stats=True)
                    best[mode] = max(best[mode],
                                     stats["decode_tokens_per_sec"])
        finally:
            tracing.enable(was_enabled)
        overhead_pct = 100.0 * (best[False] - best[True]) / max(
            best[False], 1e-9)
        record = {
            "metric": "decode_trace_overhead_pct",
            "value": round(overhead_pct, 3),
            "unit": "%",
            "extra": {
                "tokens_per_sec_tracing_off": round(best[False], 1),
                "tokens_per_sec_tracing_on": round(best[True], 1),
                "reps": reps,
                "threshold_pct": args.trace_overhead_pct,
                "batch": args.batch,
                "new_tokens": args.new_tokens,
                "preset": args.preset,
                "backend": jax.default_backend(),
            },
        }
        print(json.dumps(record))
        if overhead_pct > args.trace_overhead_pct:
            print(f"FAIL: tracing costs {overhead_pct:.2f}% decode "
                  f"throughput (> {args.trace_overhead_pct}% budget) — "
                  f"the span recorder is on the wrong side of a "
                  f"dispatch point", file=sys.stderr)
            return 1
        return 0

    baseline_record = None
    if args.json_out and (spec_k or kv_dtype):
        baseline_record, _ = measure(0, None)

    record, recompiles = measure(spec_k, kv_dtype)
    # unified-registry snapshot: compile counters (and whatever else this
    # process absorbed) ride the bench artifact
    record["extra"]["metrics"] = default_registry().snapshot()
    print(json.dumps(record))

    if args.json_out:
        summary = {
            "bench": "decode_bench",
            "model": args.model,
            "preset": args.preset,
            "batch": args.batch,
            "prompt_len": args.prompt_len,
            "new_tokens": args.new_tokens,
            "before": baseline_record or record,
            "after": record,
            "speedup": round(
                record["value"]
                / max((baseline_record or record)["value"], 1e-9), 3),
        }
        with open(args.json_out, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"wrote {args.json_out}", file=sys.stderr)

    if recompiles:
        print(f"FAIL: timed run recompiled ({recompiles} new programs) — "
              f"the decode step is not shape-stable", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
