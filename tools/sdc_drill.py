#!/usr/bin/env python
"""Silent-data-corruption drill: the integrity escalation ladder, end to end.

Five child runs on a simulated dp4 x mp2 CPU mesh (same MLP + data
trajectory as ``chaos_soak.py --elastic``; every batch is a pure function
of the global step, so runs are bit-comparable):

1. **base** — integrity OFF (``integrity_check_interval=None``): the
   defaults-off reference. Per-step losses are recorded as exact float32
   bit patterns.
2. **clean** — integrity ON, no faults: the fingerprint vote must stay
   silent (zero mismatches) and every per-step loss must be BIT-IDENTICAL
   to the base run — the in-program fingerprints are observation-only and
   the feature defaults off, so enabling it must not perturb the math,
   and disabling it leaves the step program byte-identical to a build
   without the feature (asserted: the base child never compiles a
   fingerprint specialization).
3. **transient** — a seeded one-shot ``bitflip`` on vote-axis rank 2
   (``times=1``: the cosmic-ray model). The vote must name rank 2 within
   one check interval, the ladder must stop at deterministic replay (no
   conviction), and the final loss must land within 1% of fault-free
   (the replay is bit-deterministic, so it is in fact bit-identical).
4. **sticky** — the same flip with ``times=None`` (a chip that keeps
   lying): divergence recurs after the replay, the armed suspect is
   convicted, a quarantine record lands durably next to the checkpoints,
   a flight dump carries the fingerprints, and the child exits
   ``EXIT_EVICTED``.
5. **resume** — the post-eviction incarnation on the surviving 6 devices
   (rank 2's pair evicted): ``elastic_mesh.reshaped_mesh`` absorbs the
   shrink (dp4 -> dp3), the ledger-verified restore resumes from the
   last consistent checkpoint, and training completes with loss parity.

Gated as ``robustness_gate.py --sdc``; ``--quick`` stays under ~30s.
"""
from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.distributed.resilience import (  # noqa: E402
    EXIT_EVICTED, FaultPlan)

DIM = 16
BATCH = 12   # global; divides every drill topology (dp4, dp3, dp2)
SAVE_INTERVAL = 4


# ------------------------------------------------------------------ children
def run_child(args) -> int:
    import numpy as np

    import jax

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import elastic_mesh
    from paddle_tpu.distributed.shard import DistributedTrainStep
    from paddle_tpu.framework.supervisor import (HostEvictionRequested,
                                                 RecoveryPolicy,
                                                 RollbackRequested,
                                                 TrainingSupervisor)
    from paddle_tpu.distributed.parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)
    from paddle_tpu.distributed.integrity import host_fold_leaf
    from paddle_tpu.observability import flight
    from paddle_tpu.optimizer import AdamW

    assert len(jax.devices()) == args.devices, \
        f"expected {args.devices} simulated devices, got {len(jax.devices())}"
    flight.configure(dump_dir=os.path.join(args.workdir, "flight"))
    root = os.path.join(args.workdir, "ckpt")
    mesh = elastic_mesh.reshaped_mesh(root, default_axes={"dp": -1, "mp": 2})

    elastic_mesh.rescale_batch(BATCH, dict(mesh.shape))  # divisibility check
    pt.seed(args.seed)
    model = nn.Sequential(
        ColumnParallelLinear(DIM, 4 * DIM, gather_output=False),
        nn.ReLU(),
        RowParallelLinear(4 * DIM, DIM, input_is_parallel=True))
    step = DistributedTrainStep(
        model, AdamW(learning_rate=1e-2),
        loss_fn=lambda out, b: F.mse_loss(out, b[1]), mesh=mesh)

    rng = np.random.default_rng(args.seed)
    w_true = rng.standard_normal((DIM, DIM)).astype(np.float32)

    def batch_at(i: int):
        r = np.random.default_rng(args.seed * 100003 + i)
        x = r.standard_normal((BATCH, DIM)).astype(np.float32)
        return x, x @ w_true

    integrity_on = args.mode != "base"
    policy = RecoveryPolicy(
        checkpoint_dir=root, save_interval_steps=SAVE_INTERVAL, keep_max=4,
        async_save=False, preemption=False, check_interval=args.interval,
        integrity_check_interval=args.interval if integrity_on else None)
    sup = TrainingSupervisor(step, policy)
    if not integrity_on:
        assert step._integrity is None and sup.integrity is None

    losses = {}          # global step -> lazy loss (fetched once at the end)
    detections = []      # escalation verdicts, via the on_rollback hook
    evicted = None

    def on_rollback(info):
        if info.get("integrity"):
            v = dict(info["integrity"])
            v.pop("fingerprints", None)
            detections.append(v)
    sup.on_rollback = on_rollback

    with sup:
        sup.restore()
        start = int(step._count)
        print(f"[sdc-child:{args.mode}] devices={args.devices} "
              f"mesh={dict(mesh.shape)} start_step={start}", flush=True)
        i = start
        try:
            while i < args.total_steps:
                sup.before_batch()
                try:
                    loss, ok, found = step.watchdog_call(batch_at(i))
                    sup.after_batch(0, i, loss, ok, found)
                except RollbackRequested:
                    # batches are a pure function of the global step:
                    # resume replaying at the restored count
                    i = int(step._count)
                    continue
                losses[i] = loss
                i += 1
            sup.finish_epoch()
        except HostEvictionRequested as ev:
            evicted = {"rank": ev.rank, "step": ev.step,
                       "record_path": ev.record_path}
            print(f"[sdc-child:{args.mode}] evicted: {ev}", flush=True)

    if not integrity_on:
        # defaults-off means defaults off: the run must never have built
        # a fingerprint specialization nor produced a fingerprint
        assert step._fp_compiled is None and step._last_fp is None

    # tpu-lint: disable=R1(one batched readback of the whole run's losses, after training — not on the step path)
    fetched = jax.device_get([losses[k] for k in sorted(losses)])
    losses_hex = {str(k): np.float32(v).tobytes().hex()
                  for k, v in zip(sorted(losses), fetched)}
    tail = [float(np.float32(v)) for v in fetched[-4:]]
    stats = sup.integrity.stats() if sup.integrity is not None else None
    result = {
        "mode": args.mode,
        "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
        "start_step": start,
        "end_step": int(step._count),
        "final_eval_loss": float(np.mean(tail)) if tail else float("nan"),
        "losses_hex": losses_hex,
        "integrity": stats,
        "detections": detections,
        "evicted": evicted,
        "param_fold": {k: host_fold_leaf(np.asarray(v))
                       for k, v in sorted(step.params.items())},
    }
    out = os.path.join(args.workdir, f"result_{args.mode}.json")
    with open(out + ".tmp", "w") as f:
        json.dump(result, f, indent=1)
    os.replace(out + ".tmp", out)
    print(json.dumps({k: result[k] for k in
                      ("mode", "final_eval_loss", "end_step", "integrity",
                       "detections", "evicted")}), flush=True)
    if args.mode == "sticky":
        # the harness contract: a conviction ends the incarnation with
        # EXIT_EVICTED so the launcher reschedules on surviving capacity
        return EXIT_EVICTED if evicted is not None else 1
    return 0


# ------------------------------------------------------------------- harness
def _flip_rule(args, times):
    return {"site": "train.bitflip", "kind": "bitflip", "times": times,
            "after": args.flip_after, "tensor": "*weight*", "rank": 2}


def _spawn(workdir: str, args, mode: str, devices: int,
           plan: FaultPlan | None):
    env = dict(os.environ, PYTHONPATH=REPO)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={devices}"
                        ).strip()
    if plan is not None:
        env["PT_FAULT_PLAN"] = plan.to_json()
    else:
        env.pop("PT_FAULT_PLAN", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--mode", mode, "--workdir", workdir, "--seed", str(args.seed),
           "--devices", str(devices), "--total-steps",
           str(args.total_steps), "--interval", str(args.interval),
           "--flip-after", str(args.flip_after)]
    return subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True, timeout=600)


def _result(workdir: str, mode: str):
    path = os.path.join(workdir, f"result_{mode}.json")
    return json.load(open(path)) if os.path.exists(path) else None


def _rel(a: float, b: float) -> float:
    return abs(a - b) / max(abs(b), 1e-12)


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--tol", type=float, default=0.01,
                    help="relative final-loss tolerance vs fault-free")
    ap.add_argument("--json-out", default=None)
    ap.add_argument("--child", action="store_true", help="internal")
    ap.add_argument("--mode", default="base", help="internal")
    ap.add_argument("--workdir", default=None, help="internal")
    ap.add_argument("--devices", type=int, default=8, help="internal")
    ap.add_argument("--total-steps", type=int, default=None)
    ap.add_argument("--interval", type=int, default=2,
                    help="integrity/watchdog check interval (steps)")
    ap.add_argument("--flip-after", type=int, default=6,
                    help="matching calls before the bitflip rule fires")
    args = ap.parse_args()
    if args.total_steps is None:
        args.total_steps = 16 if args.quick else 32
    if args.child:
        return run_child(args)

    failures = []
    summary = {}
    with tempfile.TemporaryDirectory(prefix="sdc_drill_") as root:
        dirs = {m: os.path.join(root, m)
                for m in ("base", "clean", "transient", "sticky")}
        for d in dirs.values():
            os.makedirs(d)

        print("[sdc_drill] base run (integrity OFF — the defaults-off "
              "reference)...", flush=True)
        p = _spawn(dirs["base"], args, "base", 8, plan=None)
        base = _result(dirs["base"], "base")
        if p.returncode != 0 or base is None:
            print(p.stdout[-2000:])
            print("[sdc_drill] FAIL: base run failed")
            return 1

        print("[sdc_drill] clean run (integrity ON, no faults)...",
              flush=True)
        p = _spawn(dirs["clean"], args, "clean", 8, plan=None)
        clean = _result(dirs["clean"], "clean")
        if p.returncode != 0 or clean is None:
            failures.append(f"clean: rc={p.returncode}: {p.stdout[-800:]}")
        else:
            # observation-only: enabling the fingerprint programs must not
            # perturb a single bit of the training math
            if clean["losses_hex"] != base["losses_hex"]:
                diff = [k for k in base["losses_hex"]
                        if clean["losses_hex"].get(k)
                        != base["losses_hex"][k]]
                failures.append(
                    f"clean: losses NOT bit-identical to integrity-off "
                    f"base at steps {diff[:5]}")
            if clean["param_fold"] != base["param_fold"]:
                failures.append("clean: final params not bit-identical "
                                "to integrity-off base")
            if clean["integrity"]["mismatches"] != 0:
                failures.append(
                    f"clean: {clean['integrity']['mismatches']} false "
                    f"fingerprint mismatches on a fault-free run")

        print(f"[sdc_drill] transient flip (rank 2, once, after "
              f"{args.flip_after} steps)...", flush=True)
        p = _spawn(dirs["transient"], args, "transient", 8,
                   plan=FaultPlan([_flip_rule(args, times=1)],
                                  seed=args.seed))
        tr = _result(dirs["transient"], "transient")
        if p.returncode != 0 or tr is None:
            failures.append(f"transient: rc={p.returncode}: "
                            f"{p.stdout[-1200:]}")
        else:
            det = tr["detections"]
            if not det:
                failures.append("transient: bitflip never detected")
            else:
                # the flip lands before fp-step flip_after+1; the vote
                # must name it within one check interval of that step
                flip_step = args.flip_after + 1
                if det[0].get("rank") != 2:
                    failures.append(f"transient: wrong culprit "
                                    f"{det[0].get('rank')} (expected 2)")
                if not (flip_step <= det[0]["step"]
                        <= flip_step + args.interval):
                    failures.append(
                        f"transient: detected at step {det[0]['step']}, "
                        f"outside one check interval of the flip at "
                        f"{flip_step}")
            st = tr["integrity"] or {}
            if st.get("replays", 0) < 1:
                failures.append("transient: no deterministic replay ran")
            if st.get("convictions", 0) != 0:
                failures.append("transient: transient fault was CONVICTED "
                                "(should have been forgiven)")
            rel = _rel(tr["final_eval_loss"], base["final_eval_loss"])
            if not math.isfinite(rel) or rel > args.tol:
                failures.append(
                    f"transient: final loss {tr['final_eval_loss']} vs "
                    f"fault-free {base['final_eval_loss']} "
                    f"(rel {rel:.4f} > tol {args.tol})")
            summary["transient_rel"] = rel
            summary["transient_bitwise"] = (tr["losses_hex"].get(
                str(args.total_steps - 1)) == base["losses_hex"].get(
                str(args.total_steps - 1)))

        print("[sdc_drill] sticky flip (rank 2, every step -> "
              "conviction)...", flush=True)
        p = _spawn(dirs["sticky"], args, "sticky", 8,
                   plan=FaultPlan([_flip_rule(args, times=None)],
                                  seed=args.seed))
        stk = _result(dirs["sticky"], "sticky")
        if p.returncode != EXIT_EVICTED:
            failures.append(f"sticky: expected EXIT_EVICTED "
                            f"{EXIT_EVICTED}, got {p.returncode}: "
                            f"{p.stdout[-1200:]}")
        if stk is None:
            failures.append("sticky: no result file")
        else:
            ev = stk.get("evicted") or {}
            if ev.get("rank") != 2:
                failures.append(f"sticky: convicted rank {ev.get('rank')} "
                                f"(expected 2)")
            qpath = os.path.join(dirs["sticky"], "ckpt", "quarantine.json")
            if not os.path.exists(qpath):
                failures.append("sticky: no durable quarantine.json")
            else:
                q = json.load(open(qpath))
                ranks = [r.get("rank") for r in q.get("convicted", [])]
                if ranks != [2]:
                    failures.append(f"sticky: quarantine names {ranks}, "
                                    f"expected [2]")
            fdir = os.path.join(dirs["sticky"], "flight")
            dumps = ([f for f in os.listdir(fdir) if "conviction" in f]
                     if os.path.isdir(fdir) else [])
            if not dumps:
                failures.append("sticky: no integrity_conviction flight "
                                "dump")

        print("[sdc_drill] post-eviction resume (6 surviving devices, "
              "dp4 -> dp3)...", flush=True)
        p = _spawn(dirs["sticky"], args, "resume", 6, plan=None)
        rs = _result(dirs["sticky"], "resume")
        if p.returncode != 0 or rs is None:
            failures.append(f"resume: rc={p.returncode}: "
                            f"{p.stdout[-1200:]}")
        else:
            if "elastic reshard" not in p.stdout:
                failures.append("resume: no 'elastic reshard' logged — "
                                "the shrunk incarnation did not "
                                "reshard-restore")
            if rs["mesh"].get("dp") != 3 or rs["mesh"].get("mp") != 2:
                failures.append(f"resume: mesh {rs['mesh']}, expected "
                                f"dp3 x mp2")
            if rs["end_step"] != args.total_steps:
                failures.append(f"resume: stopped at step "
                                f"{rs['end_step']}/{args.total_steps}")
            if not (0 < rs["start_step"] < args.total_steps):
                failures.append(f"resume: no cross-topology progress "
                                f"(start_step={rs['start_step']})")
            rel = _rel(rs["final_eval_loss"], base["final_eval_loss"])
            if not math.isfinite(rel) or rel > args.tol:
                failures.append(
                    f"resume: final loss {rs['final_eval_loss']} vs "
                    f"fault-free {base['final_eval_loss']} "
                    f"(rel {rel:.4f} > tol {args.tol})")
            summary["resume_rel"] = rel

        summary.update({
            "base_loss": base["final_eval_loss"],
            "detections": (tr or {}).get("detections"),
            "sticky_evicted": (stk or {}).get("evicted"),
            "failures": failures,
        })
        if args.json_out:
            with open(args.json_out, "w") as f:
                json.dump(summary, f, indent=1)

    if failures:
        print("[sdc_drill] FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print(f"[sdc_drill] PASS: integrity-on bit-identical to integrity-off; "
          f"transient flip detected (rank 2, within one interval), "
          f"replayed + forgiven (rel "
          f"{summary.get('transient_rel', 0):.2e}, bitwise="
          f"{summary.get('transient_bitwise')}); sticky flip convicted + "
          f"quarantined + evicted; resumed on 6 devices (rel "
          f"{summary.get('resume_rel', 0):.2e})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
