"""MFU sweep over GPT configs on the available chip (VERDICT r1 weak #1).

Usage: python -m tools.bench_sweep [one <label>]
Each config runs in its own subprocess (isolates HBM + compile-helper state).
Not part of the driver bench — a tuning tool for picking bench.py's config.
"""
from __future__ import annotations

import subprocess
import sys
import time

import numpy as np

CONFIGS = [
    # label, hidden, layers, batch, seq, remat, flash, loss_chunk
    ("base_h1024_b8", 1024, 24, 8, 1024, False, True, 0),
    ("h1024_b8_fused", 1024, 24, 8, 1024, False, True, 256),
    ("h1024_b16_fused", 1024, 24, 16, 1024, False, True, 256),
    ("h1024_b24_fused", 1024, 24, 24, 1024, False, True, 256),
    ("h1024_b32_fused", 1024, 24, 32, 1024, False, True, 256),
    ("h1024_b32_remat_fused", 1024, 24, 32, 1024, True, True, 256),
    ("h1024_b64_remat_fused", 1024, 24, 64, 1024, True, True, 256),
    ("h1024_b8_s2048_fused", 1024, 24, 8, 2048, False, True, 256),
    ("h1536_b16_fused", 1536, 24, 16, 1024, False, True, 256),
    ("h1024_b16_fused_c128", 1024, 24, 16, 1024, False, True, 128),
    ("h1024_b16_fused_c512", 1024, 24, 16, 1024, False, True, 512),
    ("h1024_b12_fused", 1024, 24, 12, 1024, False, True, 256),
    ("h1024_b16_dots", 1024, 24, 16, 1024, "dots", True, 256),
    ("h1024_b32_dots", 1024, 24, 32, 1024, "dots", True, 256),
    ("h1024_b64_dots", 1024, 24, 64, 1024, "dots", True, 256),
    ("h1536_b32_dots", 1536, 24, 32, 1024, "dots", True, 256),
    ("h1024_b64_s2048_dots", 1024, 24, 64, 2048, "dots", True, 256),
    ("h1024_b8_noflash_fused", 1024, 24, 8, 1024, False, False, 256),
    ("h1024_b12_noflash_fused", 1024, 24, 12, 1024, False, False, 256),
    ("h1024_b16_noflash_fused", 1024, 24, 16, 1024, False, False, 256),
    ("h1024_b16_noflash_dots", 1024, 24, 16, 1024, "dots", False, 256),
    ("h1024_b32_noflash_dots", 1024, 24, 32, 1024, "dots", False, 256),
    ("h1536_b8_noflash_fused", 1536, 24, 8, 1024, False, False, 256),
    ("h1024_b16_noflash_rattn", 1024, 24, 16, 1024, "attn", False, 256),
    ("h1024_b32_noflash_rattn", 1024, 24, 32, 1024, "attn", False, 256),
    ("h1024_b10_noflash_fused", 1024, 24, 10, 1024, False, False, 256),
]


def run_config(hidden, layers, batch, seq, remat, flash, loss_chunk=0, heads=16,
               timed_steps=10, warmup=3, label=""):
    import jax
    import paddle_tpu
    from paddle_tpu import amp
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       gpt_flops_per_token, gpt_loss_fn)
    from paddle_tpu.optimizer import AdamW
    from bench import _chip_peak_flops

    cfg = GPTConfig(vocab_size=50304, hidden_size=hidden, num_layers=layers,
                    num_heads=heads, max_position_embeddings=seq,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_recompute=bool(remat) and remat != "attn",
                    recompute_attn_only=remat == "attn",
                    recompute_policy="save_dots_no_batch" if remat == "dots" else None,
                    use_flash_attention=flash,
                    loss_chunk=loss_chunk, dtype="bfloat16")
    paddle_tpu.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    if loss_chunk:
        # fused path: model consumes (ids, labels) and returns the loss
        step = TrainStep(model, opt, loss_fn=None)
    else:
        step = TrainStep(model, opt, loss_fn=gpt_loss_fn(model))

    rng = np.random.default_rng(0)
    ids = np.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), np.int32)
    batch_data = (ids, ids)
    for _ in range(warmup):
        loss = step(batch_data)
    float(np.asarray(loss))
    t0 = time.perf_counter()
    for _ in range(timed_steps):
        loss = step(batch_data)
    float(np.asarray(loss))
    dt = time.perf_counter() - t0
    tps = batch * seq * timed_steps / dt
    mfu = tps * gpt_flops_per_token(cfg, seq) / _chip_peak_flops()
    print(f"{label:24s} {tps:10.0f} tok/s  MFU {mfu:.4f}", flush=True)
    return mfu


def main():
    if len(sys.argv) > 2 and sys.argv[1] == "one":
        cfg = next(c for c in CONFIGS if c[0] == sys.argv[2])
        label, h, l, b, s, r, f, lc = cfg
        run_config(h, l, b, s, r, f, lc, label=label)
        return
    for cfg in CONFIGS:
        label = cfg[0]
        proc = subprocess.run(
            [sys.executable, "-m", "tools.bench_sweep", "one", label],
            capture_output=True, text=True, timeout=900)
        out = [ln for ln in proc.stdout.splitlines() if "MFU" in ln]
        if proc.returncode == 0 and out:
            print(out[0], flush=True)
        else:
            err = (proc.stderr or "").strip().splitlines()
            print(f"{label:24s} FAILED: {err[-1][:140] if err else 'no output'}",
                  flush=True)


if __name__ == "__main__":
    main()
