#!/usr/bin/env python
"""Fleet observability drill: a 2-process rpc fleet proving the
cross-host telemetry plane end to end.

Topology: this process (rank 0, "router") runs a ``ReplicaRouter`` over
one LOCAL ``InferenceServer`` (with a ``tenantA`` LoRA adapter store)
and one REMOTE replica (rank 1, "r1") hosting a base server in a child
process. The phases, in order:

1. **scrape** — warmup traffic on both replicas, then one
   ``fleet_metrics_text()`` scrape must return BOTH processes' serving
   metrics with per-replica labels (``replica="r1"`` /
   ``replica="_local"``), and the probe-fed clock-offset estimate for
   the remote must exist and be sane;
2. **remote trace** — a request served on r1 must come back from
   ``collect_fleet_trace(corr)`` as ONE correlation-id lane stitching
   the router's local spans with the replica's rpc-exported spans
   (skew-aligned, no dump files shipped), renderable by
   ``tools/trace_view.py``;
3. **SLO burn** — a ``slow`` FaultPlan on the local ``serve.admit``
   path stalls tenantA's TTFT past its SLO target; the next scrape's
   burn-rate ingest must flight-dump an ``slo_burn`` artifact carrying
   the RIGHT tenant label;
4. **partition mid-scrape** — an rpc partition against r1 must degrade
   the next scrape to a PARTIAL roll-up: r1 stale-marked with its
   last-known numbers still present, the scrape returning (bounded, no
   router stall) instead of raising.

Exit 0 iff every check held. Wired into CI as part of
``robustness_gate.py --observability``.

    python tools/fleet_obs_drill.py
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

SLOTS = 2
GEO = dict(max_length=64, prefill_buckets=(32,))
SEED = 7


def log(msg: str) -> None:
    print(f"[fleet_obs_drill] {msg}", flush=True)


def build_model():
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(SEED)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


# ---------------------------------------------------------------- child
def child_main(endpoint: str) -> int:
    from paddle_tpu.distributed import rpc
    from paddle_tpu.serving import InferenceServer, remote

    rpc.init_rpc(name="r1", rank=1, world_size=2,
                 master_endpoint=endpoint)
    model, _ = build_model()
    server = InferenceServer(model, slots=SLOTS, max_queue_depth=16,
                             **GEO)
    remote.host_server(server, name="default")
    log(f"child r1 (pid {os.getpid()}) hosting")
    remote.wait_for_stop(timeout=600.0)
    try:
        server.shutdown(drain=False, timeout=20)
    except Exception as e:
        log(f"child shutdown: {e}")
    rpc.shutdown(timeout=6.0)
    return 0


# --------------------------------------------------------------- parent
class Check:
    def __init__(self):
        self.failures = []

    def expect(self, ok: bool, what: str) -> bool:
        log(f"{'PASS' if ok else 'FAIL'}: {what}")
        if not ok:
            self.failures.append(what)
        return ok


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def parent_main(args) -> int:
    import numpy as np

    flight_dir = tempfile.mkdtemp(prefix="fleet_obs_flight_")
    os.environ["PT_FLIGHT_DIR"] = flight_dir

    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.resilience import FaultPlan
    from paddle_tpu.lora import (AdapterStore, LoraConfig, apply_lora,
                                 lora_state)
    from paddle_tpu.observability.slo import SloPolicy
    from paddle_tpu.serving import (InferenceServer, RemoteReplica,
                                    ReplicaRouter)
    from paddle_tpu.serving import remote as remote_mod
    from trace_view import main as trace_view_main

    endpoint = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PT_FAULT_PLAN", None)
    check = Check()
    t_start = time.monotonic()
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child",
         "--endpoint", endpoint], env=env)
    try:
        rpc.init_rpc(name="router", rank=0, world_size=2,
                     master_endpoint=endpoint)
        model, cfg = build_model()
        rng = np.random.default_rng(1234)

        def prompt(n):
            return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)

        # local replica carries the tenant (remote stays base-only: the
        # drill's SLO phase must prove the PER-TENANT label plumbing)
        lcfg = LoraConfig(rank=2, alpha=4.0)
        apply_lora(model, lcfg)
        zero = lora_state(model)
        arng = np.random.default_rng(3)
        store = AdapterStore(model, lcfg, max_loaded=2)
        store.register("tenantA", {
            k: arng.normal(0.0, 0.02, v.shape).astype(np.float32)
            for k, v in zero.items()})
        local = InferenceServer(model, slots=SLOTS, max_queue_depth=16,
                                adapter_store=store, **GEO)
        remote = RemoteReplica("r1", rpc_timeout=8.0,
                               connect_deadline=0.75, poll_interval=0.01)
        if not remote.wait_ready(timeout=300.0):
            raise RuntimeError("r1 never hosted its server")
        log(f"replicas ready at {time.monotonic() - t_start:.0f}s")
        policy = SloPolicy(target_ttft_s=0.05, target_availability=0.9,
                           fast_window_s=60.0, slow_window_s=600.0,
                           fast_burn_threshold=2.0)
        router = ReplicaRouter(slo_policy=policy)
        router.add_replica(local, "local")
        router.add_replica(remote, "r1")

        # ---- phase 1: warmup + one-endpoint fleet scrape -------------
        h_remote = router.submit(prompt(12), max_new_tokens=6,
                                 prefer="r1")
        h_remote.result(timeout=300)
        for _ in range(2):
            router.submit(prompt(8), max_new_tokens=4, prefer="local",
                          adapter_id="tenantA").result(timeout=300)
        statz = router.fleet_scrape_now()
        text = router.fleet_metrics_text()
        check.expect('replica="r1"' in text
                     and 'replica="_local"' in text,
                     "fleet_metrics_text carries per-replica labels "
                     "for both processes")
        check.expect("serving_requests_completed" in text,
                     "fleet scrape rolled up remote serving counters")
        check.expect(statz["replicas"]["r1"]["stale"] is False,
                     "remote replica fresh after scrape")
        off = remote.clock_offset_s
        check.expect(off is not None and abs(off) < 1.0,
                     f"probe-fed clock offset estimated "
                     f"({0 if off is None else off * 1e3:.1f}ms)")
        dz = router.statusz()["detector"]
        check.expect(dz["replicas"]["r1"]["state"] == "active"
                     and "remote_client" in dz["replicas"]["r1"],
                     "statusz detector block carries remote state + "
                     "client clock view")
        log(f"scrape done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 2: remote trace = one corr lane, no files shipped -
        corr = h_remote.correlation_id
        spans, skew = router.collect_fleet_trace(corr=corr)
        names = {s["name"] for s in spans}
        remote_spans = [s for s in spans if s.get("src") == "r1"]
        check.expect("router:submit" in names,
                     "stitched trace has the router-side span")
        check.expect(bool(remote_spans)
                     and {"queue_wait", "prefill"} <= {
                         s["name"] for s in remote_spans},
                     f"stitched trace has the replica-side spans "
                     f"({len(remote_spans)} remote)")
        check.expect(all(s.get("corr") == corr for s in spans),
                     "every stitched span keyed by the request corr id")
        rep = next((r for r in skew if r.get("replica") == "r1"), {})
        check.expect(rep and not rep.get("clamped", True),
                     f"skew within correction bound "
                     f"(offset {rep.get('offset_s')}s)")
        check.expect(spans == sorted(
            spans, key=lambda s: (s["t0"], s["t1"])),
            "stitched spans time-ordered after alignment")
        spans_path = os.path.join(flight_dir, "stitched_spans.json")
        with open(spans_path, "w") as f:
            json.dump(spans, f)
        merged_path = os.path.join(flight_dir, "merged_trace.json")
        rc = trace_view_main([spans_path, "-o", merged_path,
                              "--corr", corr])
        lanes = set()
        if rc == 0:
            with open(merged_path) as f:
                merged = json.load(f)
            lanes = {e["tid"] for e in merged["traceEvents"]
                     if e["ph"] in ("X", "i")}
        check.expect(rc == 0 and len(lanes) == 1,
                     f"trace_view renders the remote request as ONE "
                     f"lane (rc={rc}, lanes={len(lanes)})")
        log(f"trace done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 3: SLO burn on an induced stall -> tenant-labeled
        # flight dump -------------------------------------------------
        plan = FaultPlan([{"site": "serve.admit", "kind": "slow",
                           "times": None, "delay": 0.2}], seed=3)
        plan.install(env=False)
        try:
            for _ in range(4):
                router.submit(prompt(8), max_new_tokens=4,
                              prefer="local",
                              adapter_id="tenantA").result(timeout=300)
        finally:
            plan.uninstall()
        router.fleet_scrape_now()   # ingests the burn window
        slo = router.slo_report()
        ten = (slo or {}).get("tenants", {}).get("tenantA", {})
        check.expect(ten.get("alerting") is True,
                     f"tenantA fast-window burn alerting "
                     f"(burn={ten.get('burn_fast')})")
        dumps = sorted(f for f in os.listdir(flight_dir)
                       if "slo_burn" in f)
        tenants_dumped = []
        for fname in dumps:
            with open(os.path.join(flight_dir, fname)) as f:
                tenants_dumped.append(
                    (json.load(f).get("extra") or {}).get("tenant"))
        check.expect("tenantA" in tenants_dumped,
                     f"slo_burn flight dump carries the tenant label "
                     f"(dumped: {tenants_dumped})")
        host_tok = "".join(
            c if (c.isalnum() or c in "_-") else "_"
            for c in socket.gethostname())[:24] or "host"
        check.expect(bool(dumps) and all(host_tok in d for d in dumps),
                     f"flight dumps hostname-prefixed ({dumps[:1]})")
        log(f"slo burn done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 4: partition mid-scrape -> partial roll-up --------
        part = FaultPlan([{"site": "rpc.connect.r1",
                           "kind": "partition", "times": None}], seed=0)
        part.install(env=False)
        try:
            t0 = time.monotonic()
            statz = router.fleet_scrape_now()
            dur = time.monotonic() - t0
        finally:
            part.uninstall()
        check.expect(statz["replicas"]["r1"]["stale"] is True
                     and statz["replicas"]["r1"]["error"] is not None,
                     f"partitioned replica stale-marked "
                     f"({statz['replicas']['r1']['error']})")
        check.expect(dur < 30.0,
                     f"partitioned scrape stayed bounded ({dur:.1f}s)")
        text = router.fleet_metrics_text()
        check.expect('replica="r1"' in text
                     and 'fleet_replica_stale{replica="r1"} 1.0' in text,
                     "partial roll-up keeps last-known r1 numbers, "
                     "stale-marked")
        log(f"partition done at {time.monotonic() - t_start:.0f}s")

        # ---- teardown ------------------------------------------------
        try:
            rpc.rpc_sync("r1", remote_mod._host_request_stop,
                         timeout=10.0, connect_deadline=2.0)
        except Exception as e:
            check.expect(False, f"stop signal to r1: {e}")
        local.shutdown(drain=False, timeout=20.0)
        rpc.shutdown(timeout=8.0)
        rc1 = proc.wait(timeout=120)
        check.expect(rc1 == 0, f"child exited clean (rc={rc1})")
        summary = {"elapsed_s": round(time.monotonic() - t_start, 1),
                   "failures": check.failures}
        print(json.dumps({"fleet_obs_drill": summary}), flush=True)
        return 0 if not check.failures else 1
    finally:
        if proc.poll() is None:
            proc.kill()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--endpoint", default=None)
    args = ap.parse_args()
    if args.child:
        return child_main(args.endpoint)
    return parent_main(args)


if __name__ == "__main__":
    sys.exit(main())
