#!/usr/bin/env python
"""Fault-matrix sweep: run each distributed scenario under every injected
fault kind and print a pass/fail table.

Scenarios (each runs in a fresh subprocess so ``crash`` faults can kill it):

- ``kv``   — KV store put/get/delete through a retrying ``KVClient``
- ``rpc``  — single-world ``init_rpc`` + ``rpc_sync`` + bounded shutdown
- ``ckpt`` — two checkpoint saves + verified restore from the newest VALID
  checkpoint (faults may fail a save; they must never corrupt the root)

Expected outcomes by kind:

- ``drop``/``delay``/``slow`` — the scenario retries/absorbs the fault
  and exits 0 (``slow`` is the gray-failure kind: seeded-random latency
  at the site; for ``ckpt``, a failed save is fine as long as restore
  stays valid);
- ``crash`` — the process dies with ``CRASH_EXIT``, and a clean re-run
  against the same state recovers (resume-after-crash).

Deterministic: seeded plans, counted faults, bounded deadlines. Exit code
is non-zero iff any cell fails, so CI can gate on it. Usage::

    python tools/fault_sweep.py            # the full matrix
    python tools/fault_sweep.py --scenario kv   # internal: one scenario
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.distributed.resilience import CRASH_EXIT, FaultPlan  # noqa: E402


# --------------------------------------------------------------- scenarios
def scenario_kv() -> None:
    from paddle_tpu.distributed.launch.kv_server import KVClient, KVServer
    from paddle_tpu.distributed.resilience import RetryPolicy

    with KVServer(0, host="127.0.0.1") as server:
        kv = KVClient(f"127.0.0.1:{server.port}",
                      retry=RetryPolicy(max_attempts=5, base_delay=0.05))
        kv.put("sweep/a", "1")
        assert kv.get("sweep/a") == "1"
        kv.delete("sweep/a")
        assert kv.get("sweep/a") is None


def scenario_rpc() -> None:
    import socket

    from paddle_tpu.distributed import rpc

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        ep = f"127.0.0.1:{s.getsockname()[1]}"
    rpc.init_rpc(name="solo", rank=0, world_size=1, master_endpoint=ep)
    assert rpc.rpc_sync("solo", int, args=(7,), timeout=30.0) == 7
    rpc.shutdown(timeout=10.0)


def scenario_ckpt() -> None:
    import numpy as np

    from paddle_tpu.distributed.checkpoint import (
        latest_checkpoint, load_state, save_state)

    root = os.environ["SWEEP_CKPT_ROOT"]
    done = latest_checkpoint(root)
    if done is None:  # first run (fault plans skip this save via "after")
        save_state({"w": np.full((16, 16), 1.0, np.float32), "step": 1},
                   os.path.join(root, "step_1"))
    try:
        save_state({"w": np.full((16, 16), 2.0, np.float32), "step": 2},
                   os.path.join(root, "step_2"))
    except ConnectionError:
        pass  # an injected drop may fail the save — that is allowed...
    best = latest_checkpoint(root)  # ...a corrupted/torn root is NOT
    assert best is not None, "no valid checkpoint left behind"
    state = load_state(best)        # checksum-verified
    assert state["step"] in (1, 2)


SCENARIOS = {"kv": scenario_kv, "rpc": scenario_rpc, "ckpt": scenario_ckpt}

MATRIX = [
    ("kv", "kv.put"),
    ("kv", "kv.get"),
    ("rpc", "rpc.connect.*"),
    ("ckpt", "ckpt.shard_write"),
    ("ckpt", "ckpt.publish"),
]
KINDS = ("drop", "delay", "slow", "crash")


def _make_plan(site: str, kind: str) -> FaultPlan:
    # ckpt rules skip the first save (1 shard write + 1 publish) so the
    # fault lands on the SECOND checkpoint and fallback is observable
    after = 1 if site.startswith("ckpt") else 0
    return FaultPlan([{"site": site, "kind": kind,
                       "times": 1 if kind == "crash" else 2,
                       "delay": 0.2, "after": after}], seed=1234)


def _run_child(scenario: str, env: dict) -> subprocess.CompletedProcess:
    # stderr merged into stdout: failure details (tracebacks) land in the
    # table instead of vanishing
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--scenario", scenario],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=300)


def run_cell(scenario: str, site: str, kind: str):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env["PT_FAULT_PLAN"] = _make_plan(site, kind).to_json()
    with tempfile.TemporaryDirectory(prefix="fault_sweep_") as workdir:
        env["SWEEP_CKPT_ROOT"] = workdir
        p = _run_child(scenario, env)
        if kind == "crash":
            if p.returncode != CRASH_EXIT:
                return False, (f"expected crash exit {CRASH_EXIT}, got "
                               f"{p.returncode}: {p.stdout[-200:]}")
            env.pop("PT_FAULT_PLAN")
            p2 = _run_child(scenario, env)  # same state dir: must recover
            if p2.returncode != 0:
                return False, (f"crashed but recovery failed "
                               f"rc={p2.returncode}: {p2.stdout[-200:]}")
            return True, "crashed with CRASH_EXIT, clean re-run recovered"
        if p.returncode != 0:
            return False, f"rc={p.returncode}: {p.stdout[-200:]}"
        return True, "survived injected faults"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenario", choices=sorted(SCENARIOS))
    args = ap.parse_args()
    if args.scenario:  # child mode
        SCENARIOS[args.scenario]()
        return 0

    rows, failed = [], 0
    for scenario, site in MATRIX:
        for kind in KINDS:
            t0 = time.monotonic()
            ok, detail = run_cell(scenario, site, kind)
            rows.append((scenario, site, kind,
                         "PASS" if ok else "FAIL",
                         f"{time.monotonic() - t0:5.1f}s  {detail}"))
            failed += 0 if ok else 1
            print(f"[{len(rows)}/{len(MATRIX) * len(KINDS)}] "
                  f"{scenario:5s} {site:18s} {kind:6s} "
                  f"{'PASS' if ok else 'FAIL'}", flush=True)

    print()
    print(f"{'scenario':8s} {'site':18s} {'kind':6s} {'result':6s} detail")
    print("-" * 78)
    for r in rows:
        print(f"{r[0]:8s} {r[1]:18s} {r[2]:6s} {r[3]:6s} {r[4]}")
    print("-" * 78)
    print(f"{len(rows) - failed}/{len(rows)} cells passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
