#!/usr/bin/env python
"""Fault-matrix sweep: run each distributed scenario under every injected
fault kind and print a pass/fail table.

Scenarios (each runs in a fresh subprocess so ``crash`` faults can kill it):

- ``kv``   — KV store put/get/delete through a retrying ``KVClient``
- ``rpc``  — single-world ``init_rpc`` + ``rpc_sync`` + bounded shutdown
- ``ckpt`` — two checkpoint saves + verified restore from the newest VALID
  checkpoint (faults may fail a save; they must never corrupt the root)
- ``sdc``  — a supervised dp4 train loop with the cross-replica integrity
  vote on (4 simulated CPU devices; the only row whose kinds include
  ``bitflip``)

Expected outcomes by kind:

- ``drop``/``delay``/``slow`` — the scenario retries/absorbs the fault
  and exits 0 (``slow`` is the gray-failure kind: seeded-random latency
  at the site; for ``ckpt``, a failed save is fine as long as restore
  stays valid; for ``sdc``, a non-bitflip kind at ``train.bitflip``
  degrades to the NaN-poison seam and the numerics watchdog rolls it
  back);
- ``crash`` — the process dies with ``CRASH_EXIT``, and a clean re-run
  against the same state recovers (resume-after-crash);
- ``bitflip`` (``sdc`` row only) — one seeded flip on rank 1's physical
  copies after the second checkpoint: the fingerprint vote must detect
  it (NaN watchdog stays blind), deterministically replay, and finish
  clean — the child asserts ``replays >= 1`` and zero convictions.

Deterministic: seeded plans, counted faults, bounded deadlines. Exit code
is non-zero iff any cell fails, so CI can gate on it. Usage::

    python tools/fault_sweep.py            # the full matrix
    python tools/fault_sweep.py --scenario kv   # internal: one scenario
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.distributed.resilience import CRASH_EXIT, FaultPlan  # noqa: E402


# --------------------------------------------------------------- scenarios
def scenario_kv() -> None:
    from paddle_tpu.distributed.launch.kv_server import KVClient, KVServer
    from paddle_tpu.distributed.resilience import RetryPolicy

    with KVServer(0, host="127.0.0.1") as server:
        kv = KVClient(f"127.0.0.1:{server.port}",
                      retry=RetryPolicy(max_attempts=5, base_delay=0.05))
        kv.put("sweep/a", "1")
        assert kv.get("sweep/a") == "1"
        kv.delete("sweep/a")
        assert kv.get("sweep/a") is None


def scenario_rpc() -> None:
    import socket

    from paddle_tpu.distributed import rpc

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        ep = f"127.0.0.1:{s.getsockname()[1]}"
    rpc.init_rpc(name="solo", rank=0, world_size=1, master_endpoint=ep)
    assert rpc.rpc_sync("solo", int, args=(7,), timeout=30.0) == 7
    rpc.shutdown(timeout=10.0)


def scenario_ckpt() -> None:
    import numpy as np

    from paddle_tpu.distributed.checkpoint import (
        latest_checkpoint, load_state, save_state)

    root = os.environ["SWEEP_CKPT_ROOT"]
    done = latest_checkpoint(root)
    if done is None:  # first run (fault plans skip this save via "after")
        save_state({"w": np.full((16, 16), 1.0, np.float32), "step": 1},
                   os.path.join(root, "step_1"))
    try:
        save_state({"w": np.full((16, 16), 2.0, np.float32), "step": 2},
                   os.path.join(root, "step_2"))
    except ConnectionError:
        pass  # an injected drop may fail the save — that is allowed...
    best = latest_checkpoint(root)  # ...a corrupted/torn root is NOT
    assert best is not None, "no valid checkpoint left behind"
    state = load_state(best)        # checksum-verified
    assert state["step"] in (1, 2)


def scenario_sdc() -> None:
    import numpy as np

    import jax

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import elastic_mesh
    from paddle_tpu.distributed.shard import DistributedTrainStep
    from paddle_tpu.framework.supervisor import (RecoveryPolicy,
                                                 RollbackRequested,
                                                 TrainingSupervisor)
    from paddle_tpu.optimizer import AdamW

    assert len(jax.devices()) >= 4, "sdc row needs 4 simulated devices"
    root = os.environ["SWEEP_CKPT_ROOT"]
    mesh = elastic_mesh.reshaped_mesh(os.path.join(root, "ckpt"),
                                      default_axes={"dp": -1})
    pt.seed(1234)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 8))
    step = DistributedTrainStep(
        model, AdamW(learning_rate=1e-2),
        loss_fn=lambda out, b: F.mse_loss(out, b[1]), mesh=mesh)
    policy = RecoveryPolicy(
        checkpoint_dir=os.path.join(root, "ckpt"), save_interval_steps=2,
        keep_max=4, async_save=False, preemption=False, check_interval=2,
        integrity_check_interval=2)
    sup = TrainingSupervisor(step, policy)
    rng = np.random.default_rng(7)
    w_true = rng.standard_normal((8, 8)).astype(np.float32)

    def batch_at(i: int):
        r = np.random.default_rng(100003 + i)
        x = r.standard_normal((8, 8)).astype(np.float32)
        return x, x @ w_true

    total = 10
    with sup:
        sup.restore()
        i = int(step._count)
        while i < total:
            sup.before_batch()
            try:
                loss, ok, found = step.watchdog_call(batch_at(i))
                sup.after_batch(0, i, loss, ok, found)
            except RollbackRequested:
                i = int(step._count)
                continue
            i += 1
        sup.finish_epoch()
    assert int(step._count) == total
    plan = json.loads(os.environ.get("PT_FAULT_PLAN", "{}"))
    if any(r.get("kind") == "bitflip" for r in plan.get("rules", [])):
        st = sup.integrity.stats()
        assert st["replays"] >= 1, f"flip never detected: {st}"
        assert st["convictions"] == 0, f"transient flip convicted: {st}"


SCENARIOS = {"kv": scenario_kv, "rpc": scenario_rpc, "ckpt": scenario_ckpt,
             "sdc": scenario_sdc}

MATRIX = [
    ("kv", "kv.put"),
    ("kv", "kv.get"),
    ("rpc", "rpc.connect.*"),
    ("ckpt", "ckpt.shard_write"),
    ("ckpt", "ckpt.publish"),
    ("sdc", "train.bitflip"),
]
KINDS = ("drop", "delay", "slow", "crash")


def _kinds_for(scenario: str):
    # only the supervised train row has an owner for the bitflip kind
    # (integrity.apply_bitflip behind the train.bitflip site)
    return KINDS + ("bitflip",) if scenario == "sdc" else KINDS


def _make_plan(site: str, kind: str) -> FaultPlan:
    # ckpt rules skip the first save (1 shard write + 1 publish) so the
    # fault lands on the SECOND checkpoint and fallback is observable;
    # the bitflip lands after the second checkpoint so the deterministic
    # replay has a consistent restore point to discard the step from
    if kind == "bitflip":
        return FaultPlan([{"site": site, "kind": kind, "times": 1,
                           "after": 4, "rank": 1}], seed=1234)
    after = 1 if site.startswith("ckpt") else 0
    return FaultPlan([{"site": site, "kind": kind,
                       "times": 1 if kind == "crash" else 2,
                       "delay": 0.2, "after": after}], seed=1234)


def _run_child(scenario: str, env: dict) -> subprocess.CompletedProcess:
    # stderr merged into stdout: failure details (tracebacks) land in the
    # table instead of vanishing
    return subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--scenario", scenario],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
        text=True, timeout=300)


def run_cell(scenario: str, site: str, kind: str):
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    if scenario == "sdc":  # the integrity vote needs dp replicas
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=4"
                            ).strip()
    env["PT_FAULT_PLAN"] = _make_plan(site, kind).to_json()
    with tempfile.TemporaryDirectory(prefix="fault_sweep_") as workdir:
        env["SWEEP_CKPT_ROOT"] = workdir
        p = _run_child(scenario, env)
        if kind == "crash":
            if p.returncode != CRASH_EXIT:
                return False, (f"expected crash exit {CRASH_EXIT}, got "
                               f"{p.returncode}: {p.stdout[-200:]}")
            env.pop("PT_FAULT_PLAN")
            p2 = _run_child(scenario, env)  # same state dir: must recover
            if p2.returncode != 0:
                return False, (f"crashed but recovery failed "
                               f"rc={p2.returncode}: {p2.stdout[-200:]}")
            return True, "crashed with CRASH_EXIT, clean re-run recovered"
        if p.returncode != 0:
            return False, f"rc={p.returncode}: {p.stdout[-200:]}"
        return True, "survived injected faults"


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--scenario", choices=sorted(SCENARIOS))
    args = ap.parse_args()
    if args.scenario:  # child mode
        SCENARIOS[args.scenario]()
        return 0

    rows, failed = [], 0
    total_cells = sum(len(_kinds_for(s)) for s, _ in MATRIX)
    for scenario, site in MATRIX:
        for kind in _kinds_for(scenario):
            t0 = time.monotonic()
            ok, detail = run_cell(scenario, site, kind)
            rows.append((scenario, site, kind,
                         "PASS" if ok else "FAIL",
                         f"{time.monotonic() - t0:5.1f}s  {detail}"))
            failed += 0 if ok else 1
            print(f"[{len(rows)}/{total_cells}] "
                  f"{scenario:5s} {site:18s} {kind:7s} "
                  f"{'PASS' if ok else 'FAIL'}", flush=True)

    print()
    print(f"{'scenario':8s} {'site':18s} {'kind':6s} {'result':6s} detail")
    print("-" * 78)
    for r in rows:
        print(f"{r[0]:8s} {r[1]:18s} {r[2]:6s} {r[3]:6s} {r[4]}")
    print("-" * 78)
    print(f"{len(rows) - failed}/{len(rows)} cells passed")
    return 1 if failed else 0


if __name__ == "__main__":
    sys.exit(main())
