"""Op-level performance regression harness.

Reference parity: ``tools/ci_op_benchmark.sh`` +
``tools/check_op_benchmark_result.py`` (per-op timing gate between
revisions). Usage:

    python -m tools.op_bench --save tools/op_bench_baseline.json
    python -m tools.op_bench --compare tools/op_bench_baseline.json

Compare exits 1 when any op regressed past ``--threshold`` (default 30% —
wall timings on shared hosts are noisy; the gate catches order-of-magnitude
regressions like a Pallas kernel silently falling back to the O(L^2) path,
not single-digit drift). Baselines are PER-MACHINE artifacts: regenerate
with --save when the hardware changes.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

import jax
import jax.numpy as jnp


def _bench(fn, *args, warmup=3, iters=20):
    # reduce to a scalar and materialize it on host: over tunneled PJRT
    # backends block_until_ready alone does not reliably fence execution,
    # and a scalar device_get costs nothing but forces the whole chain
    fn_j = jax.jit(lambda *a: jnp.sum(jax.tree.leaves(fn(*a))[0]
                                      .astype(jnp.float32)))
    for _ in range(warmup):
        float(fn_j(*args))
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn_j(*args)
    # tpu-lint: disable=R1(the benchmark fence — a scalar host read is the only reliable way to time the chain on tunneled backends)
    float(out)
    return (time.perf_counter() - t0) / iters


def build_suite():
    """The hot-op set: what bench.py's GPT step spends its time in."""
    rng = np.random.default_rng(0)
    f32 = lambda *s: jnp.asarray(rng.normal(size=s).astype(np.float32))  # noqa: E731
    bf16 = lambda *s: f32(*s).astype(jnp.bfloat16)  # noqa: E731

    suite = {}

    a, b = bf16(1024, 1024), bf16(1024, 1024)
    suite["matmul_1k_bf16"] = (lambda x, y: x @ y, (a, b))

    x = bf16(8, 1024, 1024)
    w = bf16(1024, 4096)
    suite["ffn_proj_bf16"] = (lambda x, w: jax.nn.gelu(x @ w), (x, w))

    h = f32(8, 1024, 1024)
    g = f32(1024)
    suite["layernorm"] = (
        lambda h, g: (h - h.mean(-1, keepdims=True))
        / jnp.sqrt(h.var(-1, keepdims=True) + 1e-5) * g, (h, g))

    from paddle_tpu.kernels.flash_attention import flash_attention_bhld as flash_attention

    q = bf16(4, 8, 1024, 64)
    suite["flash_attn_fwd"] = (
        lambda q: flash_attention(q, q, q, causal=True), (q,))
    suite["flash_attn_grad"] = (
        jax.grad(lambda q: flash_attention(q, q, q, causal=True)
                 .astype(jnp.float32).sum()), (q,))

    # L=4096: the shape where should_use_flash engages the Pallas kernel
    # on TPU — a silent fallback to the O(L^2) XLA path is exactly the
    # order-of-magnitude regression this gate exists to trip on
    # (VERDICT r3 item 9)
    q4 = bf16(1, 8, 4096, 64)
    suite["flash_attn_fwd_L4096"] = (
        lambda q: flash_attention(q, q, q, causal=True), (q4,))
    suite["flash_attn_grad_L4096"] = (
        jax.grad(lambda q: flash_attention(q, q, q, causal=True)
                 .astype(jnp.float32).sum()), (q4,))

    logits = bf16(8 * 1024, 50304)
    labels = jnp.asarray(rng.integers(0, 50304, 8 * 1024))
    suite["vocab_xent"] = (
        lambda lg, lb: -jnp.take_along_axis(
            jax.nn.log_softmax(lg.astype(jnp.float32), -1),
            lb[:, None], 1).mean(), (logits, labels))

    emb = f32(50304, 512)
    ids = jnp.asarray(rng.integers(0, 50304, (8, 1024)))
    suite["embedding_gather"] = (lambda e, i: e[i], (emb, ids))

    p = f32(4_000_000)
    gr = f32(4_000_000)
    m = f32(4_000_000)
    suite["adam_update"] = (
        lambda p, g, m: (p - 1e-3 * (0.9 * m + 0.1 * g)
                         / (jnp.sqrt(g * g) + 1e-8)), (p, gr, m))
    return suite


def run(out_path=None):
    results = {}
    for name, (fn, args) in build_suite().items():
        dt = _bench(fn, *args)
        results[name] = dt
        print(json.dumps({"op": name, "ms": round(dt * 1e3, 4)}), flush=True)
    payload = {"device": jax.devices()[0].device_kind,
               "backend": jax.default_backend(), "ms": {
                   k: v * 1e3 for k, v in results.items()}}
    if out_path:
        with open(out_path, "w") as f:
            json.dump(payload, f, indent=1)
        print(f"saved baseline to {out_path}")
    return payload


def compare(baseline_path, threshold):
    base = json.load(open(baseline_path))
    cur = run()
    if cur["device"] != base.get("device"):
        print(f"SKIP: baseline device {base.get('device')!r} != current "
              f"{cur['device']!r}; timings are not comparable — regenerate "
              f"the baseline with --save on this machine", flush=True)
        return 2  # distinct from regression (1): no comparable baseline
    failed = []
    new_ops = []
    for op, ms in cur["ms"].items():
        ref = base["ms"].get(op)
        if ref is None:
            # visible, not silent: a suite addition is uncompared until
            # the baseline is regenerated — say so every run
            print(f"{op:24s} {'—':>9s} -> {ms:9.3f} ms  NEW (no baseline; "
                  f"regenerate with --save)")
            new_ops.append(op)
            continue
        ratio = ms / ref
        status = "REGRESSED" if ratio > 1 + threshold else "ok"
        print(f"{op:24s} {ref:9.3f} -> {ms:9.3f} ms  ({ratio:5.2f}x) {status}")
        if ratio > 1 + threshold:
            failed.append(op)
    if new_ops:
        print(f"NOTE: {len(new_ops)} op(s) not in baseline: {new_ops}")
    if failed:
        print(f"FAIL: {len(failed)} op(s) regressed past "
              f"{threshold:.0%}: {failed}")
        return 1
    print("all ops within threshold")
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--save", default=None)
    ap.add_argument("--compare", default=None)
    ap.add_argument("--threshold", type=float, default=0.30)
    args = ap.parse_args(argv)
    if args.compare:
        return compare(args.compare, args.threshold)
    run(args.save)
    return 0


if __name__ == "__main__":
    sys.exit(main())
