#!/usr/bin/env python
"""Robustness gate: ONE command CI can block on for the fault-tolerance
story. Runs, in order:

0. ``tools/tpu_lint.py --json --changed-only --baseline
   .tpu_lint_baseline.json`` — the static trace-discipline analyzer
   (host syncs, retrace hazards, donation misuse, PRNG reuse, lock
   bypasses, lock-order/deadlock, blocking-under-lock, sharding
   discipline, resource-lifecycle leaks, SPMD collective divergence,
   rpc deadline/idempotence — R1–R11). The stage rides the
   ``.tpu_lint_cache/`` incremental engine by default (git diff +
   one-hop import closure; the tool falls back to — and refreshes —
   a full run whenever the cache is missing or the unchanged tree
   drifted); ``--full-lint`` forces the whole-repo run. One stage
   covers every package, replacing the per-subsystem scoped runs the
   ``--lora``/``--observability`` stages used to carry; it prints a
   per-package parse/lint timing roll-up from the ``--json`` timing
   block so lint-perf regressions are visible in CI logs. First because
   it is the cheapest stage by two orders of magnitude (seconds cold,
   milliseconds warm): a NEW unbaselined finding fails the gate before
   any soak spends minutes proving the same bug at runtime;
1. ``tools/chaos_soak.py --quick`` — the self-healing train loop under
   NaN batches, a step stall, and a kill-and-restart (fails on any
   unrecovered fault, loss divergence beyond tolerance, or a steady-state
   recompile — the soak children run under ``retrace_guard(0)``);
2. ``tools/fault_sweep.py`` — the distributed-primitive fault matrix
   (kv/rpc/checkpoint under drop/delay/crash);
3. with ``--elastic``, ``tools/chaos_soak.py --elastic --quick`` — the
   shrink/grow-on-preemption scenario: kill a run mid-training, resume on
   HALF the devices via reshard-restore, kill again, regrow to the full
   topology, and demand final-loss parity with an uninterrupted run
   (fails on any unrecovered shrink, a resize that never resharded, or
   loss divergence);
4. with ``--fleet``, ``tools/serve_bench.py --check --replicas 2
   --prefix-cache-mb 4 --prefix-tokens 24 --crash-replica --verify 3`` —
   the serving-fleet crash scenario: one replica is hard-killed
   mid-window under a prefix-heavy trace; the router must requeue its
   requests onto the survivor (zero lost), seeded-greedy probes must
   stay token-identical to a solo ``generate`` (no divergence across the
   reroute), and the survivor must hold its #buckets+1 compile budget
   with zero steady-state recompiles;
4a. with ``--fairness``, ``tools/serve_bench.py --fairness`` — the
   adversarial SLO-control-loop trace: one abusive tenant at 10x rate
   (token-bucket throttled, its rejects booking ZERO tenant failures so
   abuse cannot buy capacity) plus a traffic spike whose slow-window
   burn must force a REAL burn-driven scale-out (child replica spawned
   over the rpc fabric mid-run, its cold-start-to-first-token
   reported); protected tenants' fast-window burn must never
   edge-trigger, zero requests may be lost across the scale events, and
   the #buckets+1 compile budget must hold on every replica, the
   cold-started one included;
4b. with ``--fleet-chaos``, ``tools/fleet_chaos.py --quick`` — the
   CROSS-HOST fleet soak: rpc remote replicas in child processes under
   SIGKILL + network partition + slow-replica (``slow`` fault) +
   2x-overload faults. Zero lost requests, detector-driven reroutes
   (heartbeat misses -> DEAD -> abandoned handles fail over), hedge
   winners token-identical to solo generate, and overload sheds failing
   fast (< 10%% of their deadline) instead of timing out;
5. with ``--observability``, the telemetry gate in three parts:
   ``tools/flight_drill.py`` (an injected serve-loop crash must leave a
   well-formed flight-recorder dump carrying the failing request's
   correlation id, consumable by ``tools/trace_view.py``),
   ``tools/fleet_obs_drill.py`` (a 2-process rpc fleet: one
   ``fleet_metrics_text()`` scrape returns BOTH processes' serving
   metrics with per-replica labels; a replica partitioned mid-scrape
   degrades to a stale-marked partial roll-up, not an error; a remote
   request's stitched trace renders as one skew-aligned corr-id lane;
   an SLO burn on an induced stall flight-dumps with the right tenant
   label), and ``tools/decode_bench.py --trace-overhead`` (per-token
   span recording on the decode hot loop must cost <2% throughput,
   tracing-on vs tracing-off). The old scoped ``tpu_lint
   paddle_tpu/observability`` run folded into stage 0's whole-repo
   lint;
6. with ``--lora``, ``tools/lora_soak.py`` — the multi-tenant adapter
   lifecycle: fine-tune a tiny adapter 20 steps under the supervisor,
   hard-kill the process mid-checkpoint-save, resume from the newest
   complete checkpoint, finish, publish the adapter, then serve it
   mixed with base traffic — zero lost requests, zero steady-state
   recompiles, token parity vs solo generate. (Its old scoped
   ``tpu_lint paddle_tpu/lora`` companion folded into stage 0's
   whole-repo lint.)
7. with ``--overlap``, the step-schedule regression gate:
   ``tools/bench_profile.py --overlap --distributed`` measures the
   pre-PR serial schedule (stage 0: fused tail all-reduce + replicated
   weight update) against the bucketed overlap schedule
   (``overlap_grad_reduce=True`` + ZeRO sharded update) on the same
   model/batch; FAILS if the bucketed ``non_compute_frac`` regresses
   past the ``.overlap_baseline.json`` threshold or the serial->
   bucketed reduction drops below its floor. A scoped tpu_lint of the
   restructured step files (jit.py / shard.py / overlap.py /
   bench_profile.py) rides along so the R10 collective-divergence
   discipline is asserted even under ``--skip-lint``.
8. with ``--decode``, the raw-decode-speed regression gate:
   ``tools/decode_bench.py`` runs the ``small`` preset (compute-bound —
   the dispatch-bound ``tiny`` config hides model-level wins in launch
   overhead) with speculative decoding + int8 KV on, paired against the
   plain engine in the same process, and FAILS if the speedup drops
   below the ``.decode_baseline.json`` floor, the quantized cache stops
   halving, or the timed run recompiles. A ``--trace-overhead`` run
   rides the same baseline's threshold, and a scoped tpu_lint of the
   speculative/quantization files holds the R1/R9 line under
   ``--skip-lint``.
9. with ``--disagg``, the disaggregated prefill/decode gate:
   ``tools/fleet_chaos.py --disagg`` (KV-block migration parity — greedy
   and seeded-sampled migrated streams token-identical to solo generate
   — then SIGKILL the prefill replica MID-migration: the decode replica
   must fall back to local recompute with zero lost requests and the
   dead replica must drop from the fleet prefix index), followed by
   ``tools/serve_bench.py --disagg --check`` regression-gated against
   ``.disagg_baseline.json``: warm replica boot via the persistent
   compile cache must keep cutting cold TTFT by the stored floor, and
   migration overhead must stay under its ceiling.

10. with ``--sdc``, the silent-data-corruption drill:
   ``tools/sdc_drill.py --quick`` — a seeded one-bit flip on vote-axis
   rank 2's physical copies (logical value untouched, numerics watchdog
   blind) must be caught by the cross-replica fingerprint vote within
   one check interval with the right culprit named; the transient case
   must end at a deterministic replay (final loss bit-identical to
   fault-free), the sticky case must escalate to a conviction — durable
   quarantine record, flight dump, ``EXIT_EVICTED`` — and the next
   incarnation must resume on the surviving reduced topology via the
   elastic reshard path with loss parity. The integrity-ON clean run
   must be BIT-identical to the integrity-OFF reference (defaults off
   means defaults off). A scoped tpu_lint of the integrity/supervisor
   files rides along so the R1 (one batched fingerprint readback) and
   R9 (durable quarantine staging) lines hold under ``--skip-lint``.

Exit code is non-zero iff any stage fails. ``--skip-sweep`` /
``--skip-soak`` run a single stage (e.g. pre-merge quick signal vs the
nightly full matrix)::

    python tools/robustness_gate.py
    python tools/robustness_gate.py --skip-sweep   # lint + soak only
    python tools/robustness_gate.py --elastic      # + shrink/grow proof
    python tools/robustness_gate.py --fleet        # + serving-fleet crash
    python tools/robustness_gate.py --fairness     # + SLO control loop
    python tools/robustness_gate.py --fleet-chaos  # + cross-host rpc soak
    python tools/robustness_gate.py --lora         # + adapter lifecycle
    python tools/robustness_gate.py --observability  # + telemetry gate
    python tools/robustness_gate.py --overlap      # + step-schedule gate
    python tools/robustness_gate.py --decode       # + decode-speed gate
    python tools/robustness_gate.py --disagg       # + prefill/decode split
    python tools/robustness_gate.py --sdc          # + bit-flip defense
    python tools/robustness_gate.py --skip-lint    # runtime stages only
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _run(name: str, cmd: list) -> bool:
    print(f"[robustness_gate] === {name}: {' '.join(cmd[1:])}", flush=True)
    t0 = time.monotonic()
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    p = subprocess.run(cmd, env=env, timeout=2400)
    ok = p.returncode == 0
    print(f"[robustness_gate] === {name}: "
          f"{'PASS' if ok else f'FAIL (rc={p.returncode})'} "
          f"in {time.monotonic() - t0:.0f}s", flush=True)
    return ok


def _package_of(rel: str) -> str:
    """paddle_tpu/serving/server.py -> paddle_tpu/serving; tools/x.py ->
    tools — the roll-up grain of the lint timing table."""
    parts = rel.split("/")
    return "/".join(parts[:2]) if len(parts) > 2 else parts[0]


def _run_lint(full: bool = False) -> bool:
    """ONE tpu_lint run (R1–R11, baseline-gated) with a per-package
    parse/lint timing roll-up — the unified replacement for the scoped
    per-subsystem runs the --lora/--observability stages used to carry.

    Default is ``--changed-only``: the gate's lint step rides the
    ``.tpu_lint_cache/`` incremental engine (git diff + one-hop import
    closure) instead of re-linting every file — sub-second on a typical
    diff, and the tool itself falls back to a full run (refreshing the
    cache) whenever the cache is missing or the unchanged tree drifted.
    ``--full-lint`` forces the whole-repo run (the nightly/CI-trunk
    setting, and the one that refreshes the cache everyone else rides).
    """
    name = "tpu_lint"
    cmd = [sys.executable, os.path.join(TOOLS, "tpu_lint.py"), "--json",
           "--baseline", os.path.join(REPO, ".tpu_lint_baseline.json")]
    if not full:
        cmd.append("--changed-only")
    print(f"[robustness_gate] === {name}: {' '.join(cmd[1:])}", flush=True)
    t0 = time.monotonic()
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    p = subprocess.run(cmd, env=env, timeout=2400, capture_output=True,
                       text=True)
    ok = p.returncode == 0
    try:
        data = json.loads(p.stdout)
    except json.JSONDecodeError:
        data = {}
    timing = data.get("timing") or {}
    # a warm-cache run reports the cached analysis' timings under
    # "cached_run" — the per-package table must survive the fast path
    files_ms = (timing.get("files")
                or (timing.get("cached_run") or {}).get("files") or {})
    per_pkg: dict = {}
    for rel, t in files_ms.items():
        agg = per_pkg.setdefault(_package_of(rel),
                                 {"files": 0, "parse_ms": 0.0,
                                  "lint_ms": 0.0})
        agg["files"] += 1
        agg["parse_ms"] += t.get("parse_ms", 0.0)
        agg["lint_ms"] += t.get("lint_ms", 0.0)
    if per_pkg:
        print(f"[robustness_gate] {'package':32s} {'files':>5s} "
              f"{'parse_ms':>9s} {'lint_ms':>9s}")
        for pkg in sorted(per_pkg, key=lambda k: -per_pkg[k]["lint_ms"]):
            a = per_pkg[pkg]
            print(f"[robustness_gate] {pkg:32s} {a['files']:5d} "
                  f"{a['parse_ms']:9.1f} {a['lint_ms']:9.1f}")
    cache = data.get("cache") or {}
    stats = data.get("stats") or {}
    print(f"[robustness_gate] lint: {stats.get('files', '?')} files, "
          f"{len(data.get('new_findings', []))} NEW finding(s), "
          f"cache={'hit' if cache.get('hit') else cache.get('mode', '?')}",
          flush=True)
    for f in data.get("new_findings", []):
        print(f"[robustness_gate]   NEW {f['rule']} {f['path']}:"
              f"{f['line']} {f['message']}")
    if not ok and not data:
        sys.stdout.write(p.stdout[-2000:])
        sys.stderr.write(p.stderr[-2000:])
    print(f"[robustness_gate] === {name}: "
          f"{'PASS' if ok else f'FAIL (rc={p.returncode})'} "
          f"in {time.monotonic() - t0:.0f}s", flush=True)
    return ok


def _run_overlap_gate() -> bool:
    """``--overlap``: the step-schedule regression gate. Runs
    ``tools/bench_profile.py --overlap --distributed`` (pre-PR serial
    stage-0 schedule vs bucketed+ZeRO schedule, same model/batch) and
    fails if the bucketed schedule's ``non_compute_frac`` regresses past
    the stored ``.overlap_baseline.json`` threshold or the serial->
    bucketed reduction factor drops below its floor. Also scope-lints
    the restructured step files so ``--overlap --skip-lint`` still
    asserts the SPMD collective-divergence discipline (R10) on them."""
    name = "overlap"
    baseline_path = os.path.join(REPO, ".overlap_baseline.json")
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except OSError as e:
        print(f"[robustness_gate] === {name}: FAIL "
              f"(no {baseline_path}: {e})", flush=True)
        return False
    out = os.path.join(tempfile.gettempdir(),
                       f"overlap_gate_{os.getpid()}.json")
    ok = _run(name, [sys.executable,
                     os.path.join(TOOLS, "bench_profile.py"),
                     "--overlap", "--distributed", "--steps", "2",
                     "--json-out", out])
    if not ok:
        return False
    try:
        with open(out) as f:
            summary = json.load(f)
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass
    frac = summary["bucketed"]["value"]
    reduction = summary["non_compute_frac_reduction"]
    max_frac = baseline["max_bucketed_non_compute_frac"]
    min_red = baseline["min_reduction"]
    ok = frac <= max_frac and reduction >= min_red
    print(f"[robustness_gate] === {name}: bucketed non_compute_frac="
          f"{frac:.4f} (max {max_frac}), reduction={reduction}x "
          f"(min {min_red}) -> {'PASS' if ok else 'FAIL'}", flush=True)
    if not ok:
        return False
    # scoped self-application: the restructured step files must carry
    # zero unbaselined findings (R1 host-sync, R10 collective divergence)
    return _run(f"{name}_lint",
                [sys.executable, os.path.join(TOOLS, "tpu_lint.py"),
                 "--baseline",
                 os.path.join(REPO, ".tpu_lint_baseline.json"),
                 os.path.join(REPO, "paddle_tpu/framework/jit.py"),
                 os.path.join(REPO, "paddle_tpu/distributed/shard.py"),
                 os.path.join(REPO, "paddle_tpu/distributed/overlap.py"),
                 os.path.join(REPO, "tools/bench_profile.py")])


def _run_decode_gate() -> bool:
    """``--decode``: the raw-decode-speed regression gate. Runs
    ``tools/decode_bench.py`` on the compute-bound ``small`` preset with
    the checked-in speculative/int8 config paired against the plain
    engine (same process, same box — the ratio is host-independent
    where absolute tokens/s is not) and fails if the speedup drops
    below the ``.decode_baseline.json`` floor or the quantized cache
    stops halving. The bench itself fails the stage on steady-state
    recompiles. A ``--trace-overhead`` run rides the same baseline's
    threshold, and the speculative/quantization files are scope-linted
    so R1 (host-sync in the round loop) and R9 stay asserted under
    ``--skip-lint``."""
    name = "decode"
    baseline_path = os.path.join(REPO, ".decode_baseline.json")
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except OSError as e:
        print(f"[robustness_gate] === {name}: FAIL "
              f"(no {baseline_path}: {e})", flush=True)
        return False
    bench = baseline["bench"]
    out = os.path.join(tempfile.gettempdir(),
                       f"decode_gate_{os.getpid()}.json")
    ok = _run(name, [sys.executable,
                     os.path.join(TOOLS, "decode_bench.py"),
                     "--preset", str(bench["preset"]),
                     "--batch", str(bench["batch"]),
                     "--new-tokens", str(bench["new_tokens"]),
                     "--speculative", str(bench["speculative_k"]),
                     "--draft-layers", str(bench["draft_layers"]),
                     "--kv-dtype", str(bench["kv_dtype"]),
                     "--json-out", out])
    if not ok:
        return False
    try:
        with open(out) as f:
            summary = json.load(f)
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass
    speedup = summary["speedup"]
    min_speedup = baseline["min_speedup"]
    cache_frac = (summary["after"]["extra"]["cache_bytes"]
                  / max(summary["before"]["extra"]["cache_bytes"], 1))
    max_frac = baseline["max_cache_bytes_frac"]
    ok = speedup >= min_speedup and cache_frac <= max_frac
    print(f"[robustness_gate] === {name}: speedup={speedup}x "
          f"(min {min_speedup}), cache_frac={cache_frac:.3f} "
          f"(max {max_frac}), acceptance="
          f"{summary['after']['extra'].get('acceptance_rate')} -> "
          f"{'PASS' if ok else 'FAIL'}", flush=True)
    if not ok:
        return False
    # trace overhead on the SAME compute-bound preset: on tiny the span
    # recorder's fixed cost is a visible fraction of the ~launch-bound
    # step and the number is pure noise; on small it must stay inside
    # the baseline's budget (best-of-5 per mode filters box noise)
    if not _run(f"{name}_trace_overhead",
                [sys.executable, os.path.join(TOOLS, "decode_bench.py"),
                 "--preset", str(bench["preset"]),
                 "--batch", str(bench["batch"]),
                 "--trace-overhead", "5", "--trace-overhead-pct",
                 str(baseline["max_trace_overhead_pct"])]):
        return False
    # scoped self-application: the speculative round loop and the
    # quantize-on-write path must carry zero unbaselined findings
    return _run(f"{name}_lint",
                [sys.executable, os.path.join(TOOLS, "tpu_lint.py"),
                 "--baseline",
                 os.path.join(REPO, ".tpu_lint_baseline.json"),
                 os.path.join(REPO, "paddle_tpu/models/speculative.py"),
                 os.path.join(REPO, "paddle_tpu/models/generation.py"),
                 os.path.join(REPO, "paddle_tpu/models/lm_utils.py"),
                 os.path.join(REPO, "paddle_tpu/quantization/__init__.py"),
                 os.path.join(REPO, "tools/decode_bench.py")])


def _run_disagg_gate() -> bool:
    """``--disagg``: the disaggregated prefill/decode gate, two stages.

    First ``tools/fleet_chaos.py --disagg`` — the migration fault drill:
    a dedicated prefill replica fills KV blocks and ships them to a
    decode replica over rpc; greedy AND seeded-sampled migrated streams
    must be token-identical to solo ``generate``, then the prefill
    replica is SIGKILLed MID-migration (a ``slow`` fault holds the
    export) and the decode replica must fall back to local recompute —
    zero lost requests, the fallback traced, the dead replica dropped
    from the fleet prefix index, and the prefill replica's #buckets
    (decode-free) compile budget held at exit.

    Then ``tools/serve_bench.py --disagg --check`` — the performance
    regression half: warm replica boot (persistent compile cache) and
    migration overhead are compared against the stored
    ``.disagg_baseline.json`` floors (warm boot must keep cutting cold
    TTFT by ``min_warm_boot_reduction_frac``; shipping prefilled blocks
    must stay under ``max_migration_overhead_frac`` of the window).
    The bench itself already fails the stage on lost requests, verify
    divergence, a post-scale-out p99 TTFT spike, steady-state
    recompiles, or a compile-budget breach on any replica."""
    name = "disagg"
    baseline_path = os.path.join(REPO, ".disagg_baseline.json")
    try:
        with open(baseline_path) as f:
            baseline = json.load(f)
    except OSError as e:
        print(f"[robustness_gate] === {name}: FAIL "
              f"(no {baseline_path}: {e})", flush=True)
        return False
    if not _run(f"{name}_chaos",
                [sys.executable, os.path.join(TOOLS, "fleet_chaos.py"),
                 "--disagg"]):
        return False
    bench = baseline["bench"]
    out = os.path.join(tempfile.gettempdir(),
                       f"disagg_gate_{os.getpid()}.json")
    ok = _run(name, [sys.executable,
                     os.path.join(TOOLS, "serve_bench.py"),
                     "--disagg", "--check",
                     "--requests", str(bench["requests"]),
                     "--prefill-ratio", str(bench["prefill_ratio"]),
                     "--verify", str(bench["verify"]),
                     "--json-out", out])
    if not ok:
        return False
    try:
        with open(out) as f:
            summary = json.load(f)
    finally:
        try:
            os.unlink(out)
        except OSError:
            pass
    extra = summary["extra"]
    red = extra["cold_start_ttft_s"]["reduction_frac"]
    min_red = baseline["min_warm_boot_reduction_frac"]
    overhead = extra["migration"]["overhead_frac"]
    max_overhead = baseline["max_migration_overhead_frac"]
    ok = red >= min_red and overhead <= max_overhead
    print(f"[robustness_gate] === {name}: warm-boot reduction_frac="
          f"{red:.4f} (min {min_red}), migration overhead_frac="
          f"{overhead:.4f} (max {max_overhead}) -> "
          f"{'PASS' if ok else 'FAIL'}", flush=True)
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-soak", action="store_true")
    ap.add_argument("--skip-sweep", action="store_true")
    ap.add_argument("--full-soak", action="store_true",
                    help="run the soak without --quick")
    ap.add_argument("--elastic", action="store_true",
                    help="also run the shrink/grow-on-preemption scenario")
    ap.add_argument("--fleet", action="store_true",
                    help="also run the serving-fleet replica-crash "
                         "scenario (router reroute, token parity, "
                         "compile budget)")
    ap.add_argument("--fairness", action="store_true",
                    help="also run the adversarial SLO-control-loop "
                         "trace (10x abusive tenant + spike-driven "
                         "burn scale-out over rpc, "
                         "tools/serve_bench.py --fairness)")
    ap.add_argument("--fleet-chaos", action="store_true",
                    help="also run the cross-host rpc fleet soak "
                         "(SIGKILL + partition + slow replica + "
                         "overload shed, tools/fleet_chaos.py --quick)")
    ap.add_argument("--lora", action="store_true",
                    help="also run the multi-tenant LoRA lifecycle "
                         "(train, SIGKILL mid-save, resume, serve mixed "
                         "+ scoped tpu_lint of paddle_tpu/lora)")
    ap.add_argument("--observability", action="store_true",
                    help="also run the telemetry gate (flight-recorder "
                         "crash drill + 2-process fleet observability "
                         "drill [scrape/partition/SLO-burn/trace] + "
                         "<2%% decode tracing overhead)")
    ap.add_argument("--overlap", action="store_true",
                    help="also run the step-schedule regression gate "
                         "(bench_profile --overlap --distributed vs the "
                         ".overlap_baseline.json threshold + scoped "
                         "tpu_lint of the restructured step files)")
    ap.add_argument("--disagg", action="store_true",
                    help="also run the disaggregated prefill/decode "
                         "gate (fleet_chaos --disagg migration fault "
                         "drill + serve_bench --disagg warm-boot and "
                         "migration-overhead regression vs the "
                         ".disagg_baseline.json floors)")
    ap.add_argument("--decode", action="store_true",
                    help="also run the raw-decode-speed regression gate "
                         "(decode_bench small preset, speculative + int8 "
                         "KV vs plain engine, against the "
                         ".decode_baseline.json floor + scoped tpu_lint "
                         "of the speculative/quantization files)")
    ap.add_argument("--sdc", action="store_true",
                    help="also run the silent-data-corruption drill "
                         "(sdc_drill --quick: fingerprint-vote detection "
                         "of a seeded bit flip, replay-vs-convict ladder, "
                         "quarantine + eviction + reduced-topology resume "
                         "+ scoped tpu_lint of the integrity files)")
    ap.add_argument("--skip-lint", action="store_true",
                    help="skip the tpu_lint static-analysis stage")
    ap.add_argument("--full-lint", action="store_true",
                    help="force a whole-repo lint (default: "
                         "--changed-only riding the incremental cache; "
                         "the tool falls back to a full run on its own "
                         "when the cache is missing or stale)")
    args = ap.parse_args()

    results = {}
    if not args.skip_lint:
        results["tpu_lint"] = _run_lint(full=args.full_lint)
    elif args.lora or args.observability:
        # the scoped per-subsystem lints folded into stage 0; skipping
        # it now skips THEIR lint coverage too — say so loudly instead
        # of silently weakening the subsystem gates (MIGRATION.md)
        print("[robustness_gate] WARNING: --skip-lint also skips the "
              "lora/observability lint coverage that used to ride "
              "their stages (now part of the unified whole-repo lint)",
              flush=True)
    if not args.skip_soak:
        cmd = [sys.executable, os.path.join(TOOLS, "chaos_soak.py")]
        if not args.full_soak:
            cmd.append("--quick")
        results["chaos_soak"] = _run("chaos_soak", cmd)
    if args.elastic:
        cmd = [sys.executable, os.path.join(TOOLS, "chaos_soak.py"),
               "--elastic"]
        if not args.full_soak:
            cmd.append("--quick")
        results["elastic"] = _run("elastic", cmd)
    if args.fleet:
        results["fleet"] = _run(
            "fleet", [sys.executable, os.path.join(TOOLS, "serve_bench.py"),
                      "--check", "--replicas", "2", "--prefix-cache-mb",
                      "4", "--prefix-tokens", "24", "--crash-replica",
                      "--verify", "3"])
    if args.fairness:
        results["fairness"] = _run(
            "fairness", [sys.executable,
                         os.path.join(TOOLS, "serve_bench.py"),
                         "--fairness"])
    if args.fleet_chaos:
        results["fleet_chaos"] = _run(
            "fleet_chaos", [sys.executable,
                            os.path.join(TOOLS, "fleet_chaos.py"),
                            "--quick"])
    if args.observability:
        results["flight_drill"] = _run(
            "flight_drill", [sys.executable,
                             os.path.join(TOOLS, "flight_drill.py")])
        results["fleet_obs_drill"] = _run(
            "fleet_obs_drill", [sys.executable,
                                os.path.join(TOOLS,
                                             "fleet_obs_drill.py")])
        results["trace_overhead"] = _run(
            "trace_overhead", [sys.executable,
                               os.path.join(TOOLS, "decode_bench.py"),
                               "--trace-overhead", "3"])
    if args.lora:
        results["lora"] = _run(
            "lora", [sys.executable, os.path.join(TOOLS, "lora_soak.py")])
    if args.overlap:
        results["overlap"] = _run_overlap_gate()
    if args.disagg:
        results["disagg"] = _run_disagg_gate()
    if args.decode:
        results["decode"] = _run_decode_gate()
    if args.sdc:
        results["sdc"] = _run(
            "sdc", [sys.executable, os.path.join(TOOLS, "sdc_drill.py"),
                    "--quick"])
        if results["sdc"]:
            # scoped self-application: the fingerprint readback (R1
            # suppressed at exactly one reasoned sync point), the
            # monitor's lock discipline (R5/R7) and the quarantine
            # staging write (R9) must carry zero unbaselined findings
            results["sdc_lint"] = _run(
                "sdc_lint",
                [sys.executable, os.path.join(TOOLS, "tpu_lint.py"),
                 "--baseline",
                 os.path.join(REPO, ".tpu_lint_baseline.json"),
                 os.path.join(REPO, "paddle_tpu/distributed/integrity.py"),
                 os.path.join(REPO, "paddle_tpu/distributed/shard.py"),
                 os.path.join(REPO, "paddle_tpu/framework/supervisor.py"),
                 os.path.join(REPO, "tools/sdc_drill.py")])
    if not args.skip_sweep:
        results["fault_sweep"] = _run(
            "fault_sweep", [sys.executable,
                            os.path.join(TOOLS, "fault_sweep.py")])

    print()
    for name, ok in results.items():
        print(f"[robustness_gate] {name:12s} {'PASS' if ok else 'FAIL'}")
    if not results:
        print("[robustness_gate] nothing ran (both stages skipped)")
        return 2
    return 0 if all(results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
