#!/usr/bin/env python
"""Robustness gate: ONE command CI can block on for the fault-tolerance
story. Runs, in order:

0. ``tools/tpu_lint.py --baseline .tpu_lint_baseline.json`` — the static
   trace-discipline analyzer (host syncs, retrace hazards, donation
   misuse, PRNG reuse, lock bypasses). First because it is the cheapest
   stage by two orders of magnitude (~5 s, no backend): a NEW unbaselined
   finding fails the gate before any soak spends minutes proving the same
   bug at runtime;
1. ``tools/chaos_soak.py --quick`` — the self-healing train loop under
   NaN batches, a step stall, and a kill-and-restart (fails on any
   unrecovered fault, loss divergence beyond tolerance, or a steady-state
   recompile — the soak children run under ``retrace_guard(0)``);
2. ``tools/fault_sweep.py`` — the distributed-primitive fault matrix
   (kv/rpc/checkpoint under drop/delay/crash);
3. with ``--elastic``, ``tools/chaos_soak.py --elastic --quick`` — the
   shrink/grow-on-preemption scenario: kill a run mid-training, resume on
   HALF the devices via reshard-restore, kill again, regrow to the full
   topology, and demand final-loss parity with an uninterrupted run
   (fails on any unrecovered shrink, a resize that never resharded, or
   loss divergence);
4. with ``--fleet``, ``tools/serve_bench.py --check --replicas 2
   --prefix-cache-mb 4 --prefix-tokens 24 --crash-replica --verify 3`` —
   the serving-fleet crash scenario: one replica is hard-killed
   mid-window under a prefix-heavy trace; the router must requeue its
   requests onto the survivor (zero lost), seeded-greedy probes must
   stay token-identical to a solo ``generate`` (no divergence across the
   reroute), and the survivor must hold its #buckets+1 compile budget
   with zero steady-state recompiles;
5. with ``--observability``, the telemetry gate in three parts:
   ``tools/flight_drill.py`` (an injected serve-loop crash must leave a
   well-formed flight-recorder dump carrying the failing request's
   correlation id, consumable by ``tools/trace_view.py``), a scoped
   ``tpu_lint paddle_tpu/observability`` run (0 findings — the
   telemetry layer itself must not regress trace discipline), and
   ``tools/decode_bench.py --trace-overhead`` (per-token span recording
   on the decode hot loop must cost <2% throughput, tracing-on vs
   tracing-off);
6. with ``--lora``, ``tools/lora_soak.py`` — the multi-tenant adapter
   lifecycle: fine-tune a tiny adapter 20 steps under the supervisor,
   hard-kill the process mid-checkpoint-save, resume from the newest
   complete checkpoint, finish, publish the adapter, then serve it
   mixed with base traffic — zero lost requests, zero steady-state
   recompiles, token parity vs solo generate. A scoped
   ``tpu_lint paddle_tpu/lora`` run (0 findings, reasoned suppressions
   only) rides in the same stage so the new subsystem cannot regress
   trace discipline even when the full-repo lint stage is skipped.

Exit code is non-zero iff any stage fails. ``--skip-sweep`` /
``--skip-soak`` run a single stage (e.g. pre-merge quick signal vs the
nightly full matrix)::

    python tools/robustness_gate.py
    python tools/robustness_gate.py --skip-sweep   # lint + soak only
    python tools/robustness_gate.py --elastic      # + shrink/grow proof
    python tools/robustness_gate.py --fleet        # + serving-fleet crash
    python tools/robustness_gate.py --lora         # + adapter lifecycle
    python tools/robustness_gate.py --observability  # + telemetry gate
    python tools/robustness_gate.py --skip-lint    # runtime stages only
"""
from __future__ import annotations

import argparse
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TOOLS = os.path.join(REPO, "tools")


def _run(name: str, cmd: list) -> bool:
    print(f"[robustness_gate] === {name}: {' '.join(cmd[1:])}", flush=True)
    t0 = time.monotonic()
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    p = subprocess.run(cmd, env=env, timeout=2400)
    ok = p.returncode == 0
    print(f"[robustness_gate] === {name}: "
          f"{'PASS' if ok else f'FAIL (rc={p.returncode})'} "
          f"in {time.monotonic() - t0:.0f}s", flush=True)
    return ok


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--skip-soak", action="store_true")
    ap.add_argument("--skip-sweep", action="store_true")
    ap.add_argument("--full-soak", action="store_true",
                    help="run the soak without --quick")
    ap.add_argument("--elastic", action="store_true",
                    help="also run the shrink/grow-on-preemption scenario")
    ap.add_argument("--fleet", action="store_true",
                    help="also run the serving-fleet replica-crash "
                         "scenario (router reroute, token parity, "
                         "compile budget)")
    ap.add_argument("--lora", action="store_true",
                    help="also run the multi-tenant LoRA lifecycle "
                         "(train, SIGKILL mid-save, resume, serve mixed "
                         "+ scoped tpu_lint of paddle_tpu/lora)")
    ap.add_argument("--observability", action="store_true",
                    help="also run the telemetry gate (flight-recorder "
                         "crash drill + scoped tpu_lint of "
                         "paddle_tpu/observability + <2%% decode "
                         "tracing overhead)")
    ap.add_argument("--skip-lint", action="store_true",
                    help="skip the tpu_lint static-analysis stage")
    args = ap.parse_args()

    results = {}
    if not args.skip_lint:
        results["tpu_lint"] = _run(
            "tpu_lint", [sys.executable, os.path.join(TOOLS, "tpu_lint.py"),
                         "--baseline",
                         os.path.join(REPO, ".tpu_lint_baseline.json")])
    if not args.skip_soak:
        cmd = [sys.executable, os.path.join(TOOLS, "chaos_soak.py")]
        if not args.full_soak:
            cmd.append("--quick")
        results["chaos_soak"] = _run("chaos_soak", cmd)
    if args.elastic:
        cmd = [sys.executable, os.path.join(TOOLS, "chaos_soak.py"),
               "--elastic"]
        if not args.full_soak:
            cmd.append("--quick")
        results["elastic"] = _run("elastic", cmd)
    if args.fleet:
        results["fleet"] = _run(
            "fleet", [sys.executable, os.path.join(TOOLS, "serve_bench.py"),
                      "--check", "--replicas", "2", "--prefix-cache-mb",
                      "4", "--prefix-tokens", "24", "--crash-replica",
                      "--verify", "3"])
    if args.observability:
        results["flight_drill"] = _run(
            "flight_drill", [sys.executable,
                             os.path.join(TOOLS, "flight_drill.py")])
        results["obs_lint"] = _run(
            "obs_lint", [sys.executable, os.path.join(TOOLS, "tpu_lint.py"),
                         os.path.join("paddle_tpu", "observability"),
                         "--no-baseline"])
        results["trace_overhead"] = _run(
            "trace_overhead", [sys.executable,
                               os.path.join(TOOLS, "decode_bench.py"),
                               "--trace-overhead", "3"])
    if args.lora:
        results["lora"] = _run(
            "lora", [sys.executable, os.path.join(TOOLS, "lora_soak.py")])
        results["lora_lint"] = _run(
            "lora_lint", [sys.executable,
                          os.path.join(TOOLS, "tpu_lint.py"),
                          os.path.join("paddle_tpu", "lora"),
                          "--no-baseline"])
    if not args.skip_sweep:
        results["fault_sweep"] = _run(
            "fault_sweep", [sys.executable,
                            os.path.join(TOOLS, "fault_sweep.py")])

    print()
    for name, ok in results.items():
        print(f"[robustness_gate] {name:12s} {'PASS' if ok else 'FAIL'}")
    if not results:
        print("[robustness_gate] nothing ran (both stages skipped)")
        return 2
    return 0 if all(results.values()) else 1


if __name__ == "__main__":
    sys.exit(main())
