"""Sparse-table pull/push throughput benchmark.

The PS table's per-key find is the CTR-training hot operation (reference:
``MemorySparseTable`` + accessor rules, ``table/memory_sparse_table.cc``);
this measures cold pull (insert+init), hot pull (gather), and
push-with-optimizer-rule throughput at the 2M-key scale, host-side.

Usage:  python tools/ps_bench.py [--keys 2000000] [--save]
Prints one JSON dict; --save writes tools/ps_bench_results.json.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--keys", type=int, default=2_000_000)
    ap.add_argument("--dim", type=int, default=16)
    ap.add_argument("--save", action="store_true")
    args = ap.parse_args()

    from paddle_tpu.distributed.ps import MemorySparseTable

    t = MemorySparseTable(embed_dim=args.dim, optimizer="adagrad")
    rng = np.random.default_rng(0)
    universe = rng.integers(0, 2**40, args.keys).astype(np.int64)

    batch = 8192
    iters_fill = args.keys // batch
    pulled = iters_fill * batch
    t0 = time.perf_counter()
    for i in range(iters_fill):
        t.pull(universe[i * batch:(i + 1) * batch])
    cold = pulled / (time.perf_counter() - t0)
    if pulled < args.keys:  # tail keys join before the hot phase
        t.pull(universe[pulled:])

    iters = 100
    batches = [rng.choice(universe, batch) for _ in range(iters)]
    t0 = time.perf_counter()
    for b in batches:
        t.pull(b)
    hot = batch * iters / (time.perf_counter() - t0)

    grads = rng.standard_normal((batch, args.dim)).astype(np.float32)
    t0 = time.perf_counter()
    for b in batches:
        t.push(b, grads)
    push = batch * iters / (time.perf_counter() - t0)

    result = {
        "keys": args.keys, "dim": args.dim, "rows": len(t),
        "host": {"cpu_count": os.cpu_count()},
        "cold_pull_keys_per_sec": round(cold, 1),
        "hot_pull_keys_per_sec": round(hot, 1),
        "push_adagrad_keys_per_sec": round(push, 1),
    }
    print(json.dumps(result))
    if args.save:
        out = os.path.join(REPO, "tools", "ps_bench_results.json")
        with open(out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
