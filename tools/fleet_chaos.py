#!/usr/bin/env python
"""Cross-host fleet chaos soak: rpc remote replicas under SIGKILL,
network partition, gray slowness, and 2x overload.

Topology: this process (rank 0, "router") drives a ``ReplicaRouter``
whose replicas are :class:`~paddle_tpu.serving.remote.RemoteReplica`
adapters over three CHILD PROCESSES (ranks 1..3, "r1".."r3"), each
hosting a real ``InferenceServer`` on the same seeded gpt_tiny weights.
The phases, in order:

1. **warmup** — one seeded request per replica, token-verified against a
   parent-side solo ``generate()`` (also compiles every host's programs
   and warms the router's inter-token EWMA);
2. **overload** — a burst at ~2x fleet capacity with per-request
   deadlines: the deadline-aware scheduler must SHED the overflow fast
   (every shed < 10%% of its deadline, raised as the retryable
   ``Overloaded``) while every accepted request completes — no
   expirations, no timeouts;
3. **slow replica** — a seeded ``slow`` FaultPlan is rpc-installed into
   r3's ``serve.step``: a request pinned there stalls mid-stream, the
   router's hedge fires to a healthy replica, and the hedge winner's
   tokens are identical to solo (router-assigned-seed replay);
4. **partition** — the parent installs a local partition plan on its
   ``rpc.connect.r2`` site mid-stream: the in-flight request reroutes to
   a survivor with identical tokens, and the heartbeat detector walks r2
   through SUSPECT to DEAD (flight-recorder dump carrying the affected
   correlation ids);
5. **SIGKILL** — r1 is hard-killed mid-stream: same contract, zero lost.

Exit 0 iff every phase held: zero lost requests, zero token divergence,
sheds fast-failed, detector-driven reroutes happened, and the surviving
hosts finish at their #prefill_buckets+1 compile budget. Wired into CI
as ``robustness_gate.py --fleet-chaos`` (which runs ``--quick``).

    python tools/fleet_chaos.py --quick
    python tools/fleet_chaos.py            # longer overload burst
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SLOTS = 2
GEO = dict(max_length=64, prefill_buckets=(32,))
N_REPLICAS = 3
SEED = 7
BLOCK_TOKENS = 8


def log(msg: str) -> None:
    print(f"[fleet_chaos] {msg}", flush=True)


def build_model():
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(SEED)
    cfg = gpt_tiny(hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    return model, cfg


# ---------------------------------------------------------------- child
def child_main(rank: int, endpoint: str, role: str = None,
               world: int = None) -> int:
    from paddle_tpu.distributed import rpc
    from paddle_tpu.serving import InferenceServer, remote

    name = f"r{rank}"
    rpc.init_rpc(name=name, rank=rank,
                 world_size=(N_REPLICAS + 1) if world is None else world,
                 master_endpoint=endpoint)
    model, _ = build_model()
    kw = dict(slots=SLOTS, max_queue_depth=16, shed_on_overload=True)
    if role is not None:
        # disagg replicas carry the paged KV pool the migration fills
        kw["prefix_cache"] = dict(max_bytes=4 << 20,
                                  block_tokens=BLOCK_TOKENS)
    server = InferenceServer(model, **kw, **GEO)
    if role is not None:
        # prefill replicas serve max_new_tokens=1 only: their decode
        # program must never be traced (#buckets, not #buckets+1)
        server.engine.warmup(max_new_tokens=1 if role == "prefill" else 2)
    remote.host_server(server, name="default")
    log(f"child {name} (pid {os.getpid()}) hosting role={role}")
    remote.wait_for_stop(timeout=600.0)
    cc = server.engine.cache_stats()
    n_buckets = len(server.engine.prefill_buckets)
    want_decode = 0 if role == "prefill" else 1
    budget_ok = (cc["prefill"]["compiles"] == n_buckets
                 and cc["decode"]["compiles"] == want_decode)
    log(f"child {name} compile budget: prefill "
        f"{cc['prefill']['compiles']}/{n_buckets}, decode "
        f"{cc['decode']['compiles']}/{want_decode} "
        f"-> {'OK' if budget_ok else 'OVER'}")
    try:
        server.shutdown(drain=False, timeout=20)
    except Exception as e:
        log(f"child {name} shutdown: {e}")
    rpc.shutdown(timeout=6.0)
    return 0 if budget_ok else 3


# --------------------------------------------------------------- parent
class Check:
    def __init__(self):
        self.failures = []

    def expect(self, ok: bool, what: str) -> bool:
        log(f"{'PASS' if ok else 'FAIL'}: {what}")
        if not ok:
            self.failures.append(what)
        return ok


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _wait(cond, timeout: float, what: str) -> bool:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.05)
    log(f"timeout waiting for {what}")
    return False


def parent_main(args) -> int:
    import numpy as np

    flight_dir = tempfile.mkdtemp(prefix="fleet_chaos_flight_")
    os.environ["PT_FLIGHT_DIR"] = flight_dir

    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.resilience import FaultPlan
    from paddle_tpu.serving import (Overloaded, RemoteReplica,
                                    ReplicaRouter)
    from paddle_tpu.serving import remote as remote_mod

    endpoint = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PT_FAULT_PLAN", None)
    procs = {}
    check = Check()
    t_start = time.monotonic()
    try:
        for rank in range(1, N_REPLICAS + 1):
            procs[f"r{rank}"] = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--child",
                 "--rank", str(rank), "--endpoint", endpoint],
                env=env)
        rpc.init_rpc(name="router", rank=0, world_size=N_REPLICAS + 1,
                     master_endpoint=endpoint)
        log(f"rpc world up in {time.monotonic() - t_start:.0f}s")
        model, cfg = build_model()
        rng = np.random.default_rng(1234)

        def prompt(n):
            return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)

        def solo(p, n, seed=None):
            return model.generate(
                p[None], max_new_tokens=n,
                do_sample=seed is not None,
                temperature=0.8 if seed is not None else 1.0,
                seed=seed, **GEO)[0]

        replicas = {f"r{r}": RemoteReplica(
            f"r{r}", rpc_timeout=8.0, connect_deadline=0.75,
            poll_interval=0.01) for r in range(1, N_REPLICAS + 1)}
        # children host their servers only after a multi-second model
        # build: wait for readiness BEFORE the router's detector starts
        # counting their boot window as probe misses
        for name, rep in replicas.items():
            if not rep.wait_ready(timeout=300.0):
                raise RuntimeError(f"{name} never hosted its server")
        log(f"replicas ready at {time.monotonic() - t_start:.0f}s")
        router = ReplicaRouter(
            health_check_interval=0.25, suspect_misses=1, dead_misses=3,
            hedge_multiplier=4.0, hedge_min_s=0.4,
            hedge_warmup_tokens=8, max_reroutes=3)
        for name, rep in replicas.items():
            router.add_replica(rep, name)

        # ---- phase 1: warmup + token parity per replica --------------
        warm_tokens = 10
        for name in sorted(replicas):
            p = prompt(12)
            want = solo(p, warm_tokens, seed=100)
            h = router.submit(p, max_new_tokens=warm_tokens,
                              do_sample=True, temperature=0.8, seed=100,
                              prefer=name)
            got = h.result(timeout=300)
            check.expect(np.array_equal(got, want),
                         f"warmup tokens identical on {name}")
            last_corr = h.correlation_id
        log(f"warmup done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 1b: fleet observability — one scrape, all hosts ---
        router.fleet_scrape_now()
        obs_text = router.fleet_metrics_text()
        check.expect(
            all(f'replica="{n}"' in obs_text for n in replicas),
            "one fleet_metrics_text scrape carries every replica's "
            "labels")
        check.expect("serving_requests_completed" in obs_text,
                     "fleet scrape rolled up remote serving metrics")
        tspans, tskew = router.collect_fleet_trace(corr=last_corr)
        check.expect(
            any(s.get("src") in replicas for s in tspans)
            and all(s.get("corr") == last_corr for s in tspans),
            f"remote trace collection stitched one corr lane "
            f"({len(tspans)} spans)")
        check.expect(all(not r.get("clamped") for r in tskew
                         if not r.get("error")),
                     f"host clock skew within correction bound "
                     f"({[r.get('offset_s') for r in tskew]})")
        log(f"fleet scrape done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 2: 2x overload -> shed fast, accepted keep SLO ----
        # gpt_tiny decodes so fast on this box that honest queues never
        # form; slow EVERY host's serve loop with the seeded `slow`
        # fault so the fleet has a realistic service rate to overload
        # (and the phase is box-speed independent)
        load_plan = FaultPlan([{"site": "serve.step", "kind": "slow",
                                "times": None, "delay": 0.08}], seed=5)
        for name in sorted(replicas):
            rpc.rpc_sync(name, remote_mod._host_install_plan,
                         args=(load_plan.to_json(),), timeout=15.0)
        # saturate first (no deadlines) so every host's admission-
        # cadence EWMA is warm — and measured UNDER the load the burst
        # will see — before the deadline'd burst arrives
        pre = [router.submit(prompt(8), max_new_tokens=24)
               for _ in range(6 * N_REPLICAS * SLOTS)]
        time.sleep(2.5)   # let cadence samples accumulate under load
        burst_n = 24 if args.quick else 48
        # two SLO classes sized off the fleet's own admission-control
        # telemetry (probe() exposes predicted_queue_wait): a GENEROUS
        # wave whose deadline clears the deepest queue — accepted
        # requests must keep their SLO — and a TIGHT wave below today's
        # median wait, which deadline-aware admission must shed AT THE
        # DOOR instead of letting it time out
        waits = []
        for rep in replicas.values():
            try:
                w = rep.probe().get("predicted_queue_wait")
            except Exception:
                w = None
            if w:
                waits.append(w)
        waits.sort()
        median_w = waits[len(waits) // 2] if waits else 0.5
        generous = max(3.0, 3.0 * (waits[-1] if waits else 1.0))
        tight = max(1.0, 0.5 * median_w)
        log(f"overload: predicted waits {[round(w, 2) for w in waits]} "
            f"-> deadlines generous {generous:.2f}s / tight {tight:.2f}s")
        door_shed, late_shed, accepted, lost = [], [], [], []
        burst = []
        for i in range(burst_n):
            p = prompt(8)
            deadline = generous if i % 2 == 0 else tight
            t0 = time.monotonic()
            try:
                h = router.submit(p, max_new_tokens=6, deadline=deadline)
            except ConnectionError:
                # Overloaded (deadline-aware shed) or, at the very
                # bottom of the queue ladder, QueueFull — either way a
                # retryable reject raised at the door, in milliseconds
                door_shed.append((time.monotonic() - t0, deadline))
                continue
            burst.append((h, t0, deadline))

        # harvest CONCURRENTLY: a serial result() loop would timestamp a
        # shed when the loop reaches its handle, not when it happened
        harvest_lock = threading.Lock()

        def harvest(h, t0, deadline):
            try:
                out = h.result(timeout=120)
                with harvest_lock:
                    accepted.append(len(out))
            except Overloaded:
                # post-admission shed: service degraded after this
                # request was queued; still far faster than timing out
                with harvest_lock:
                    late_shed.append((time.monotonic() - t0, deadline))
            except Exception as e:
                with harvest_lock:
                    lost.append(f"{type(e).__name__}: {e}")

        threads = [threading.Thread(target=harvest, args=b, daemon=True)
                   for b in burst]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=180)
        for h in pre:
            try:
                h.result(timeout=180)
            except Exception as e:
                lost.append(f"preload {type(e).__name__}: {e}")
        n_shed = len(door_shed) + len(late_shed)
        check.expect(n_shed > 0,
                     f"overload shed part of the 2x burst "
                     f"({len(door_shed)} at the door + {len(late_shed)} "
                     f"from queue of {burst_n})")
        check.expect(len(accepted) >= burst_n // 4,
                     f"overload kept serving the generous SLO class "
                     f"({len(accepted)}/{burst_n // 2} completed)")
        frac = [lat / dl for lat, dl in door_shed]
        check.expect(bool(door_shed) and max(frac) < 0.1,
                     f"door sheds failed fast: worst at "
                     f"{max(frac) * 100 if frac else 0:.1f}% of its "
                     f"deadline ({len(door_shed)} sheds)")
        # a sweep-shed legitimately fires when remaining time crosses
        # below the predicted wait — i.e. NEAR the deadline — so the
        # bound is deadline + one serve-loop tick of slack; the real
        # "never timed out" proof is the expired==0 check below
        late_frac = [lat / dl for lat, dl in late_shed]
        late_over = [lat - dl for lat, dl in late_shed]
        check.expect(not late_over or max(late_over) < 0.5,
                     f"queue sheds landed by their deadline (worst "
                     f"{max(late_frac) * 100 if late_frac else 0:.0f}% "
                     f"of deadline)")
        check.expect(not lost, f"overload lost nothing ({lost[:3]})")
        snaps = {n: r.snapshot() for n, r in replicas.items()}
        fleet_shed = sum(s.get("requests_shed", 0) for s in snaps.values())
        fleet_expired = sum(s.get("requests_expired", 0)
                            for s in snaps.values())
        check.expect(fleet_expired == 0,
                     f"no request waited out its deadline "
                     f"(expired={fleet_expired}, host sheds={fleet_shed})")
        for name in sorted(replicas):   # restore full speed everywhere
            rpc.rpc_sync(name, remote_mod._host_clear_plan, timeout=15.0)
        log(f"overload done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 3: slow replica -> hedge, token-identical ---------
        # delay must dominate the hedge threshold, which is EWMA-derived
        # and inflated by the overload phase's contention: 4.0 -> every
        # slowed step sleeps 2-6s, far past any realistic threshold
        slow_plan = FaultPlan([{"site": "serve.step", "kind": "slow",
                                "times": None, "delay": 4.0}], seed=11)
        rpc.rpc_sync("r3", remote_mod._host_install_plan,
                     args=(slow_plan.to_json(),), timeout=15.0)
        p = prompt(12)
        want = solo(p, 8, seed=555)
        hedged_before = router.requests_hedged
        h = router.submit(p, max_new_tokens=8, do_sample=True,
                          temperature=0.8, seed=555, prefer="r3")
        got = h.result(timeout=120)
        rpc.rpc_sync("r3", remote_mod._host_clear_plan, timeout=15.0)
        check.expect(np.array_equal(got, want),
                     "hedged stream token-identical to solo")
        check.expect(router.requests_hedged > hedged_before,
                     f"hedge fired on the gray replica "
                     f"(hedged={router.requests_hedged}, "
                     f"wins={router.hedge_wins})")
        hedge_dumps = [f for f in os.listdir(flight_dir)
                       if "hedge_fire" in f]
        check.expect(len(hedge_dumps) > 0,
                     f"hedge fire flight-dumped ({len(hedge_dumps)})")
        log(f"hedge done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 4: partition r2 -> detector death + reroute -------
        p = prompt(12)
        want = solo(p, 16, seed=777)
        h = router.submit(p, max_new_tokens=16, do_sample=True,
                          temperature=0.8, seed=777, prefer="r2")
        part_plan = FaultPlan([{"site": "rpc.connect.r2",
                                "kind": "partition", "times": None}],
                              seed=0)
        part_plan.install(env=False)
        got = h.result(timeout=180)
        check.expect(np.array_equal(got, want),
                     "partitioned stream rerouted token-identical")
        check.expect(
            _wait(lambda: router.replicas().get("r2") == "dead",
                  timeout=60, what="detector declaring r2 dead"),
            "heartbeat detector declared the partitioned replica dead")
        dead_dumps = [f for f in os.listdir(flight_dir)
                      if "replica_dead" in f]
        check.expect(len(dead_dumps) > 0,
                     f"replica death flight-dumped ({len(dead_dumps)})")
        check.expect(router.snapshot()["replicas_suspected"] >= 1,
                     "detector counted a SUSPECT transition")
        log(f"partition done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 5: SIGKILL r1 mid-stream --------------------------
        p = prompt(12)
        want = solo(p, 16, seed=888)
        h = router.submit(p, max_new_tokens=16, do_sample=True,
                          temperature=0.8, seed=888, prefer="r1")
        for i, _tok in enumerate(h.stream()):
            if i >= 2:   # provably mid-stream
                break
        procs["r1"].kill()
        got = h.result(timeout=180)
        check.expect(np.array_equal(got, want),
                     "SIGKILLed stream rerouted token-identical")
        check.expect(
            _wait(lambda: router.replicas().get("r1") == "dead",
                  timeout=60, what="detector declaring r1 dead"),
            "heartbeat detector declared the killed replica dead")
        snap = router.snapshot()
        check.expect(snap["requests_rerouted"] + snap["hedge_wins"] >= 2,
                     f"the partition + kill were rerouted/hedged "
                     f"(rerouted={snap['requests_rerouted']}, "
                     f"hedge_wins={snap['hedge_wins']})")
        log(f"kill done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 5b: partial roll-up after deaths, no router stall -
        t_scrape = time.monotonic()
        obs_statz = router.fleet_scrape_now()
        scrape_dur = time.monotonic() - t_scrape
        check.expect(obs_statz["replicas"]["r1"]["stale"] is True
                     and obs_statz["replicas"]["r2"]["stale"] is True,
                     "dead/partitioned replicas stale-marked in the "
                     "roll-up")
        obs_text = router.fleet_metrics_text()
        check.expect('replica="r3"' in obs_text
                     and 'replica="r1"' in obs_text,
                     "partial roll-up keeps the survivor fresh and the "
                     "casualties' last-known numbers")
        check.expect(scrape_dur < 30.0,
                     f"post-kill scrape stayed bounded "
                     f"({scrape_dur:.1f}s, no router stall)")

        # ---- teardown: stop survivors, collect their budget verdicts -
        part_plan.uninstall()   # r2 reachable again for its stop signal
        for name in ("r2", "r3"):
            try:
                rpc.rpc_sync(name, remote_mod._host_request_stop,
                             timeout=10.0, connect_deadline=2.0)
            except Exception as e:
                check.expect(False, f"stop signal to {name}: {e}")
        rpc.shutdown(timeout=8.0)
        rc1 = procs["r1"].wait(timeout=30)
        check.expect(rc1 == -9, f"r1 died by SIGKILL (rc={rc1})")
        for name in ("r2", "r3"):
            rc = procs[name].wait(timeout=120)
            check.expect(rc == 0,
                         f"{name} exited clean with compile budget held "
                         f"(rc={rc})")

        summary = {
            "elapsed_s": round(time.monotonic() - t_start, 1),
            "sheds": n_shed,
            "worst_shed_frac": round(max(frac + late_frac or [0.0]), 4),
            "accepted": len(accepted),
            "requests_routed": snap["requests_routed"],
            "requests_rerouted": snap["requests_rerouted"],
            "requests_hedged": snap["requests_hedged"],
            "hedge_wins": snap["hedge_wins"],
            "replicas_failed": snap["replicas_failed"],
            "replicas_suspected": snap["replicas_suspected"],
            "failures": check.failures,
        }
        print(json.dumps({"fleet_chaos": summary}), flush=True)
        return 0 if not check.failures else 1
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()


# ------------------------------------------------- disagg chaos (PR 19)
def disagg_main(args) -> int:
    """SIGKILL a prefill replica mid-migration: the decode replica must
    fall back to local recompute with zero lost requests and
    token-identical streams, and keep serving after the prefill pool is
    gone entirely.

    Topology: rank 0 (this parent) drives a
    ``serving.disagg.DisaggClient`` over two children — r1 hosts the
    prefill replica, r2 the decode replica, both with KV block pools.
    A seeded ``slow`` FaultPlan is rpc-installed on r1's
    ``disagg.kv_export`` fault point so the kill provably lands
    mid-migration, not between requests."""
    import numpy as np

    from paddle_tpu.distributed import rpc
    from paddle_tpu.distributed.resilience import FaultPlan
    from paddle_tpu.observability import tracing
    from paddle_tpu.serving import RemoteReplica
    from paddle_tpu.serving import remote as remote_mod
    from paddle_tpu.serving.disagg import DisaggClient, PrefixIndex

    endpoint = f"127.0.0.1:{_free_port()}"
    env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
    env.pop("PT_FAULT_PLAN", None)
    world = 3
    procs = {}
    check = Check()
    t_start = time.monotonic()
    try:
        for rank, role in ((1, "prefill"), (2, "decode")):
            procs[f"r{rank}"] = subprocess.Popen(
                [sys.executable, os.path.abspath(__file__), "--child",
                 "--rank", str(rank), "--endpoint", endpoint,
                 "--role", role, "--world", str(world)],
                env=env)
        rpc.init_rpc(name="router", rank=0, world_size=world,
                     master_endpoint=endpoint)
        model, cfg = build_model()
        rng = np.random.default_rng(1234)

        def prompt(n):
            return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)

        def solo(p, n, seed=None):
            return model.generate(
                p[None], max_new_tokens=n,
                do_sample=seed is not None,
                temperature=0.8 if seed is not None else 1.0,
                seed=seed, **GEO)[0]

        pre = RemoteReplica("r1", rpc_timeout=8.0, connect_deadline=0.75,
                            poll_interval=0.01)
        dec = RemoteReplica("r2", rpc_timeout=8.0, connect_deadline=0.75,
                            poll_interval=0.01)
        for name, rep in (("r1", pre), ("r2", dec)):
            if not rep.wait_ready(timeout=300.0):
                raise RuntimeError(f"{name} never hosted its server")
        log(f"replicas ready at {time.monotonic() - t_start:.0f}s")
        client = DisaggClient([pre], [dec], block_tokens=BLOCK_TOKENS,
                              index=PrefixIndex())

        # ---- phase 1: migrated streams token-identical ---------------
        # prompts past one full block so the migration path engages;
        # greedy + seeded-sampled both checked against parent-side solo
        p1, p2 = prompt(2 * BLOCK_TOKENS + 3), prompt(2 * BLOCK_TOKENS + 5)
        want1, want2 = solo(p1, 8), solo(p2, 8, seed=321)
        got1 = client.submit(p1, max_new_tokens=8).result(timeout=300)
        got2 = client.submit(p2, max_new_tokens=8, do_sample=True,
                             temperature=0.8, seed=321).result(timeout=300)
        check.expect(np.array_equal(got1, want1),
                     "migrated greedy stream token-identical to solo")
        check.expect(np.array_equal(got2, want2),
                     "migrated seeded-sampled stream token-identical")
        check.expect(client.migrations == 2 and client.fallbacks == 0,
                     f"both requests really migrated "
                     f"(migrations={client.migrations}, "
                     f"fallbacks={client.fallbacks})")
        client.scrape_index()
        check.expect("r1" in client.index.replicas(),
                     "prefix index scraped the prefill replica")
        log(f"migration parity done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 2: SIGKILL the prefill replica MID-migration ------
        # the slow fault pins the export leg for seconds, the kill lands
        # inside it, and the in-flight request must fall back to the
        # decode replica's local recompute — token-identical, not lost
        slow_plan = FaultPlan([{"site": "disagg.kv_export",
                                "kind": "slow", "times": None,
                                "delay": 5.0}], seed=3)
        rpc.rpc_sync("r1", remote_mod._host_install_plan,
                     args=(slow_plan.to_json(),), timeout=15.0)
        p3 = prompt(2 * BLOCK_TOKENS + 7)
        want3 = solo(p3, 8)
        box = {}

        def submit_mid_kill():
            h = client.submit(p3, max_new_tokens=8)
            box["out"] = h.result(timeout=300)

        th = threading.Thread(target=submit_mid_kill, daemon=True)
        th.start()
        time.sleep(1.2)   # the export leg is now sleeping in the fault
        procs["r1"].kill()
        th.join(timeout=300)
        check.expect(np.array_equal(box.get("out"), want3),
                     "mid-migration kill: stream fell back "
                     "token-identical")
        check.expect(client.fallbacks >= 1,
                     f"the killed migration was absorbed as a fallback "
                     f"(fallbacks={client.fallbacks})")
        events = tracing.spans(name="kv_migrate:fallback")
        check.expect(len(events) >= 1,
                     f"fallback left a kv_migrate:fallback trace event "
                     f"({len(events)})")
        log(f"mid-migration kill done at {time.monotonic() - t_start:.0f}s")

        # ---- phase 3: prefill pool dead — decode keeps serving -------
        lost = 0
        for k in range(4):
            p = prompt(2 * BLOCK_TOKENS + 2 + k)
            want = solo(p, 6)
            try:
                got = client.submit(p, max_new_tokens=6).result(timeout=300)
            except Exception:
                lost += 1
                continue
            if not np.array_equal(got, want):
                lost += 1
        check.expect(lost == 0,
                     "decode pool served 4/4 token-identical with the "
                     "prefill pool dead")
        client.scrape_index()
        check.expect("r1" not in client.index.replicas(),
                     "dead prefill replica dropped from the prefix index")
        log(f"prefill-dead serving done at {time.monotonic() - t_start:.0f}s")

        # ---- teardown ------------------------------------------------
        try:
            rpc.rpc_sync("r2", remote_mod._host_request_stop,
                         timeout=10.0, connect_deadline=2.0)
        except Exception as e:
            check.expect(False, f"stop signal to r2: {e}")
        rpc.shutdown(timeout=8.0)
        rc1 = procs["r1"].wait(timeout=30)
        check.expect(rc1 == -9, f"r1 died by SIGKILL (rc={rc1})")
        rc2 = procs["r2"].wait(timeout=120)
        check.expect(rc2 == 0,
                     f"decode replica exited clean with its "
                     f"#buckets+1 budget held (rc={rc2})")

        summary = {
            "elapsed_s": round(time.monotonic() - t_start, 1),
            "migrations": client.migrations,
            "fallbacks": client.fallbacks,
            "migrated_bytes": client.migrated_bytes,
            "failures": check.failures,
        }
        print(json.dumps({"fleet_chaos_disagg": summary}), flush=True)
        return 0 if not check.failures else 1
    finally:
        for proc in procs.values():
            if proc.poll() is None:
                proc.kill()


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="smaller overload burst (the CI gate shape)")
    ap.add_argument("--disagg", action="store_true",
                    help="disagg scenario: SIGKILL a prefill replica "
                         "mid-migration; decode must fall back to local "
                         "recompute with zero lost requests")
    ap.add_argument("--child", action="store_true")
    ap.add_argument("--rank", type=int, default=0)
    ap.add_argument("--endpoint", default=None)
    ap.add_argument("--role", choices=("prefill", "decode"), default=None)
    ap.add_argument("--world", type=int, default=None)
    args = ap.parse_args()
    if args.child:
        return child_main(args.rank, args.endpoint, role=args.role,
                          world=args.world)
    if args.disagg:
        return disagg_main(args)
    return parent_main(args)


if __name__ == "__main__":
    sys.exit(main())
