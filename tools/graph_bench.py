"""Graph-engine scale benchmark (VERDICT r3 item 5).

Synthetic power-law-ish graph at the 10M-edge scale: measures CSR
build rate, neighbor-sampling and random-walk throughput on the native
store (single-host and 2-shard service), and the walk-feed/train overlap
(GraphDataGenerator batches prefetched on a host thread while a jitted
skip-gram step trains — the reference's ``pre_build_thread`` overlap,
``ps_gpu_wrapper.h:198``; sampling kernels: ``graph_gpu_ps_table.h:128-134``).

Usage:  python tools/graph_bench.py [--edges 10000000] [--save]
Prints one JSON dict; --save writes tools/graph_bench_results.json.
"""
from __future__ import annotations

import argparse
import json
import os
import queue
import sys
import threading
import time

# the graph engine is host-side C++; only the feed/train-overlap section
# touches jax, and its skip-gram step measures HOST overlap — pin it to
# CPU (and skip accelerator-plugin pool discovery, which can block when a
# tunneled TPU is unreachable) unless the caller explicitly chose a
# platform
os.environ.setdefault("JAX_PLATFORMS", "cpu")
if os.environ["JAX_PLATFORMS"].startswith("cpu"):
    # override, not setdefault: TPU-tunnel images pre-set the pool address
    os.environ["PALLAS_AXON_POOL_IPS"] = ""

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_graph(num_nodes: int, num_edges: int, seed: int = 0):
    from paddle_tpu.distributed.ps.graph import GraphTable

    rng = np.random.default_rng(seed)
    src = rng.integers(0, num_nodes, num_edges, dtype=np.int64)
    # mild power law on destinations: squaring skews toward low ids
    dst = (rng.random(num_edges) ** 2 * num_nodes).astype(np.int64)
    g = GraphTable()
    t0 = time.perf_counter()
    g.add_edges(src, dst)
    g.build()
    build_s = time.perf_counter() - t0
    return g, build_s


def bench_sampling(store, node_ids, batch: int, sample_size: int,
                   iters: int, seed: int = 1):
    rng = np.random.default_rng(seed)
    batches = [rng.choice(node_ids, batch) for _ in range(iters)]
    store.sample_neighbors(batches[0], sample_size)  # warm
    t0 = time.perf_counter()
    for b in batches:
        store.sample_neighbors(b, sample_size)
    dt = time.perf_counter() - t0
    return batch * sample_size * iters / dt


def bench_walks(store, node_ids, batch: int, walk_len: int, iters: int,
                seed: int = 2):
    rng = np.random.default_rng(seed)
    batches = [rng.choice(node_ids, batch) for _ in range(iters)]
    store.random_walk(batches[0], walk_len, seed=0)  # warm
    t0 = time.perf_counter()
    for i, b in enumerate(batches):
        store.random_walk(b, walk_len, seed=i)
    dt = time.perf_counter() - t0
    return batch * walk_len * iters / dt


def bench_sharded(num_nodes: int, num_edges: int, batch, sample_size,
                  walk_len, iters):
    """Same measurements through the 2-shard multi-host service."""
    from paddle_tpu.distributed.ps.graph import (DistGraphClient,
                                                 launch_graph_servers)

    servers, endpoints = launch_graph_servers(2)
    try:
        client = DistGraphClient(endpoints)
        rng = np.random.default_rng(0)
        src = rng.integers(0, num_nodes, num_edges, dtype=np.int64)
        dst = (rng.random(num_edges) ** 2 * num_nodes).astype(np.int64)
        t0 = time.perf_counter()
        client.add_edges(src, dst)
        client.build()
        build_s = time.perf_counter() - t0
        ids = client.node_ids()
        return {
            "build_edges_per_sec": round(num_edges / build_s, 1),
            "neighbor_samples_per_sec": round(
                bench_sampling(client, ids, batch, sample_size, iters), 1),
            "walk_hops_per_sec": round(
                bench_walks(client, ids, batch, walk_len, iters), 1),
        }
    finally:
        try:
            client.stop_servers()
            client.close()
        except Exception:
            for s in servers:
                s.terminate()


def bench_overlap(g, steps: int = 30, batch_size: int = 4096):
    """Deepwalk feed overlapped with a jitted skip-gram step vs strictly
    sequential generate-then-train: the async-feed proof."""
    import jax
    import jax.numpy as jnp

    from paddle_tpu.distributed.ps.graph import GraphDataGenerator

    n = int(g.node_ids().max()) + 1
    dim = 64
    emb = jnp.asarray(np.random.default_rng(0).normal(
        size=(n, dim), scale=0.1), jnp.float32)

    @jax.jit
    def step(emb, c, x, negs):
        def loss_fn(e):
            ce, xe, ne = e[c], e[x], e[negs]
            pos = jnp.sum(ce * xe, -1)
            neg = jnp.einsum("bd,bkd->bk", ce, ne)
            return (jnp.mean(jax.nn.softplus(-pos))
                    + jnp.mean(jax.nn.softplus(neg)))
        loss, grad = jax.value_and_grad(loss_fn)(emb)
        return emb - 0.1 * grad, loss

    def batches():
        gen = GraphDataGenerator(g, batch_size=batch_size, walk_len=8,
                                 window=2, num_neg=4, seed=0)
        count = 0
        while count < steps:  # small graphs need several epochs per run
            produced = False
            for b in gen:
                produced = True
                yield b
                count += 1
                if count >= steps:
                    return
            if not produced:
                raise RuntimeError("graph too small for one batch; lower "
                                   "batch_size or raise --edges")

    # warm the compile outside both timed regions
    c, x, negs = next(iter(batches()))
    emb2, _ = step(emb, c, x, negs)
    # tpu-lint: disable=R1(compile-warmup fence before the timed regions)
    emb2.block_until_ready()

    t0 = time.perf_counter()
    pending = list(batches())          # feed fully materialized first
    e = emb
    for c, x, negs in pending:
        e, _ = step(e, c, x, negs)
    # tpu-lint: disable=R1(benchmark timing fence — t_seq must include the dispatched work)
    e.block_until_ready()
    t_seq = time.perf_counter() - t0

    t0 = time.perf_counter()
    q: queue.Queue = queue.Queue(maxsize=4)

    def producer():
        for b in batches():
            q.put(b)
        q.put(None)

    th = threading.Thread(target=producer, daemon=True)
    th.start()
    e = emb
    while True:
        item = q.get()
        if item is None:
            break
        c, x, negs = item
        e, _ = step(e, c, x, negs)
    # tpu-lint: disable=R1(benchmark timing fence — t_pipe must include the dispatched work)
    e.block_until_ready()
    th.join()
    t_pipe = time.perf_counter() - t0
    return {"sequential_s": round(t_seq, 3), "overlapped_s": round(t_pipe, 3),
            "speedup": round(t_seq / t_pipe, 3)}


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--edges", type=int, default=10_000_000)
    p.add_argument("--nodes", type=int, default=None)
    p.add_argument("--iters", type=int, default=50)
    p.add_argument("--save", action="store_true")
    args = p.parse_args()
    num_nodes = args.nodes or max(args.edges // 10, 1000)

    g, build_s = build_graph(num_nodes, args.edges)
    ids = g.node_ids()
    batch, sample_size, walk_len = 4096, 10, 20
    result = {
        "edges": args.edges,
        "nodes_with_edges": int(ids.size),
        # sharding/overlap wins are scale-OUT effects: on a single-core
        # host every byte of IPC and every producer-thread switch is pure
        # added work, so two_shard <= single_host and overlap <= 1.0 are
        # the expected envelope there; record the context so the numbers
        # are read against the right ceiling
        "host": {"cpu_count": os.cpu_count()},
        "single_host": {
            "build_edges_per_sec": round(args.edges / build_s, 1),
            "neighbor_samples_per_sec": round(
                bench_sampling(g, ids, batch, sample_size, args.iters), 1),
            "walk_hops_per_sec": round(
                bench_walks(g, ids, batch, walk_len, args.iters), 1),
        },
        # sharded service at the SAME scale as the single-host run so the
        # two throughput columns are a fair head-to-head (the r4 bench used
        # a tenth of the edges for the service, flattering neither side)
        "two_shard": bench_sharded(num_nodes, args.edges,
                                   batch, sample_size, walk_len,
                                   max(args.iters // 2, 5)),
        "feed_train_overlap": bench_overlap(g),
    }
    print(json.dumps(result))
    if args.save:
        out = os.path.join(REPO, "tools", "graph_bench_results.json")
        with open(out, "w") as f:
            json.dump(result, f, indent=1)


if __name__ == "__main__":
    main()
