"""Per-shape compile report for the input pipeline.

Runs a short ``hapi.Model.fit`` loop over a deliberately hostile dataset —
three sequence lengths plus a ragged tail batch — and prints the compile
table from ``framework.compile_cache.cache_stats()``: one row per traced
shape signature of the train step. Exits non-zero when the step compiled
more programs than ``--budget``, so CI can pin the shape-stability
guarantee.

    python tools/retrace_report.py                  # padding+bucketing on
    python tools/retrace_report.py --no-stabilize   # raw shapes (one
                                                    # compile per shape)
    python tools/retrace_report.py --budget 3

Runs on any backend; tier-1 invokes it with JAX_PLATFORMS=cpu.
"""
from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


LENGTHS = (12, 20, 28)
BUCKETS = (16, 32)
N_SAMPLES = 22        # not divisible by batch size -> ragged tail
BATCH_SIZE = 4
NUM_CLASSES = 4
VOCAB = 64


def build_model():
    import paddle_tpu.nn as nn

    class TinyClassifier(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(VOCAB, 16)
            self.head = nn.Linear(16, NUM_CLASSES)

        def forward(self, ids):
            # mean-pool over the (padded) sequence axis; padding ids are 0
            return self.head(self.embed(ids).mean(axis=1))

    return TinyClassifier()


class RaggedDataset:
    """(ids[L], label) with L in length-sorted blocks (the usual layout a
    length-grouping sampler produces), plus a ragged tail batch."""

    def __len__(self):
        return N_SAMPLES

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        L = LENGTHS[min(i // 8, len(LENGTHS) - 1)]  # blocks of 8 = 2 batches
        return (np.asarray(rng.integers(1, VOCAB, L), np.int64),
                np.int64(i % NUM_CLASSES))


def run_fit(stabilize: bool, epochs: int):
    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.hapi import Model
    from paddle_tpu.io.dataset import Dataset

    class DS(RaggedDataset, Dataset):
        pass

    pt.seed(0)
    model = Model(build_model())
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1),
                  loss=lambda logits, label: F.cross_entropy(logits, label))
    model.fit(DS(), batch_size=BATCH_SIZE, epochs=epochs, verbose=0,
              shuffle=False,
              pad_batches=stabilize,
              length_buckets=BUCKETS if stabilize else None)
    return model._train_step.cache_stats()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--budget", type=int, default=None,
                    help="max train-step compiles before a non-zero exit "
                         "(default: 1 + #buckets when stabilized, else off)")
    ap.add_argument("--no-stabilize", action="store_true",
                    help="disable pad_batches/length_buckets to show the "
                         "per-shape recompile behavior")
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args(argv)

    stabilize = not args.no_stabilize
    budget = args.budget
    if budget is None and stabilize:
        budget = 1 + len(BUCKETS)

    stats = run_fit(stabilize, args.epochs)

    mode = ("pad_batches=True length_buckets=%s" % (BUCKETS,)
            if stabilize else "raw shapes (no padding/bucketing)")
    print(f"retrace report — {mode}")
    print(f"{'train-step trace signature':<72}{'compiles':>9}")
    for sig, n in sorted(stats["signatures"].items()):
        print(f"{sig:<72}{n:>9}")
    print(f"{'TOTAL':<72}{stats['compiles']:>9}   "
          f"(calls {stats['calls']}, cache hits {stats['cache_hits']})")

    if budget is not None and stats["compiles"] > budget:
        print(f"FAIL: {stats['compiles']} compiles > budget {budget} — "
              f"the input pipeline is recompiling the step", file=sys.stderr)
        return 1
    if budget is not None:
        print(f"OK: {stats['compiles']} compiles <= budget {budget}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
