"""Per-shape compile report for the input pipeline AND the decode engine.

Runs a short ``hapi.Model.fit`` loop over a deliberately hostile dataset —
three sequence lengths plus a ragged tail batch — and prints the compile
table from ``framework.compile_cache.cache_stats()``: one row per traced
shape signature, labeled by KIND (``train`` / ``prefill`` / ``decode``).
Exits non-zero when the train step compiled more programs than
``--budget``, so CI can pin the shape-stability guarantee.

With ``--generate`` it also drives the compiled KV-cache generation
engine (``models/generation.py``) over prompts spanning two prefill
buckets and appends the prefill/decode rows to the table, budget-checked
at ``#buckets_used + 1`` programs.

    python tools/retrace_report.py                  # padding+bucketing on
    python tools/retrace_report.py --no-stabilize   # raw shapes (one
                                                    # compile per shape)
    python tools/retrace_report.py --budget 3 --generate

Runs on any backend; tier-1 invokes it with JAX_PLATFORMS=cpu.
"""
from __future__ import annotations

import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

import numpy as np


LENGTHS = (12, 20, 28)
BUCKETS = (16, 32)
N_SAMPLES = 22        # not divisible by batch size -> ragged tail
BATCH_SIZE = 4
NUM_CLASSES = 4
VOCAB = 64


def build_model():
    import paddle_tpu.nn as nn

    class TinyClassifier(nn.Layer):
        def __init__(self):
            super().__init__()
            self.embed = nn.Embedding(VOCAB, 16)
            self.head = nn.Linear(16, NUM_CLASSES)

        def forward(self, ids):
            # mean-pool over the (padded) sequence axis; padding ids are 0
            return self.head(self.embed(ids).mean(axis=1))

    return TinyClassifier()


class RaggedDataset:
    """(ids[L], label) with L in length-sorted blocks (the usual layout a
    length-grouping sampler produces), plus a ragged tail batch."""

    def __len__(self):
        return N_SAMPLES

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        L = LENGTHS[min(i // 8, len(LENGTHS) - 1)]  # blocks of 8 = 2 batches
        return (np.asarray(rng.integers(1, VOCAB, L), np.int64),
                np.int64(i % NUM_CLASSES))


def run_fit(stabilize: bool, epochs: int):
    import paddle_tpu as pt
    import paddle_tpu.nn.functional as F
    from paddle_tpu.hapi import Model
    from paddle_tpu.io.dataset import Dataset

    class DS(RaggedDataset, Dataset):
        pass

    pt.seed(0)
    model = Model(build_model())
    model.prepare(optimizer=pt.optimizer.SGD(learning_rate=0.1),
                  loss=lambda logits, label: F.cross_entropy(logits, label))
    model.fit(DS(), batch_size=BATCH_SIZE, epochs=epochs, verbose=0,
              shuffle=False,
              pad_batches=stabilize,
              length_buckets=BUCKETS if stabilize else None)
    return model._train_step.cache_stats()


GEN_PROMPT_LENS = (12, 24)   # spans both GEN_BUCKETS
GEN_BUCKETS = (16, 32)
GEN_NEW_TOKENS = 8


def run_generate():
    """Drive the compiled generation engine across two prefill buckets and
    return its per-step compile stats (prefill keyed per bucket shape,
    decode exactly once)."""
    import paddle_tpu as pt
    from paddle_tpu.models.generation import GenerationEngine
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny(hidden_dropout_prob=0.0,
                                    attention_dropout_prob=0.0,
                                    use_flash_attention=False))
    model.eval()
    engine = GenerationEngine(model, max_length=64,
                              prefill_buckets=GEN_BUCKETS)
    for plen in GEN_PROMPT_LENS:
        ids = np.random.default_rng(plen).integers(
            1, VOCAB, (2, plen)).astype(np.int32)
        engine.generate(ids, max_new_tokens=GEN_NEW_TOKENS)
    return engine.cache_stats()


SPEC_K = 4


def run_speculative():
    """Drive the speculative draft/verify engine across the same two
    prefill buckets and return its per-family compile stats. The
    declared budget is ``2 * #buckets + 1``: a target prefill AND a
    draft prefill per bucket, plus ONE fused decode-round program (the
    K-step draft chain and the [B, K+1] verify live in the same
    program)."""
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny
    from paddle_tpu.models.speculative import (SpeculativeEngine,
                                               build_draft_model)

    pt.seed(0)
    model = GPTForCausalLM(gpt_tiny(hidden_dropout_prob=0.0,
                                    attention_dropout_prob=0.0,
                                    use_flash_attention=False))
    model.eval()
    draft = build_draft_model(model, num_layers=1)
    engine = SpeculativeEngine(model, draft, k=SPEC_K, max_length=64,
                               prefill_buckets=GEN_BUCKETS)
    for plen in GEN_PROMPT_LENS:
        ids = np.random.default_rng(plen).integers(
            1, VOCAB, (2, plen)).astype(np.int32)
        engine.generate(ids, max_new_tokens=GEN_NEW_TOKENS)
    return engine.cache_stats()


_LINT_CACHE = []   # one (baseline, analysis) pass even if both budgets fail


def _lint_pointers(kind_tokens) -> list:
    """Baselined R2 (retrace-hazard) findings whose trace-entry chain
    roots at the overrunning program kind. Pure-AST (runs only on the
    failure path): an overrun whose program already carries a known,
    accepted retrace hazard gets pointed at the lint rule instead of
    leaving the debugging to compile-table archaeology."""
    try:
        from paddle_tpu.analysis import analyze, load_baseline

        if not _LINT_CACHE:
            baseline = load_baseline(
                os.path.join(REPO, ".tpu_lint_baseline.json"))
            _LINT_CACHE.append(
                (baseline, analyze(REPO, ["paddle_tpu"]) if baseline
                 else None))
        baseline, result = _LINT_CACHE[0]
        if not baseline:
            return []
        out = []
        for f in result.findings:
            if f.rule != "R2" or baseline.get(f.key(), 0) < 1:
                continue
            root = f.chain[0].lower() if f.chain else ""
            if any(tok in root for tok in kind_tokens):
                out.append(f)
        return out
    except Exception:
        return []   # the report must never die on the pointer lookup


def _print_lint_pointers(kind_tokens) -> None:
    for f in _lint_pointers(kind_tokens):
        print(f"note: baselined tpu_lint {f.rule} finding is "
              f"trace-reachable from this program — a known retrace "
              f"hazard may explain the overrun:\n"
              f"      {f.rule} {f.path}:{f.line} [{f.symbol}] "
              f"{f.snippet}\n"
              f"      (see README 'Static analysis (tpu_lint)'; "
              f"re-triage with python tools/tpu_lint.py --no-baseline)",
              file=sys.stderr)


def _print_rows(kind: str, signatures: dict):
    for sig, n in sorted(signatures.items()):
        sig = sig if len(sig) <= 62 else sig[:59] + "..."
        print(f"{kind:<9}{sig:<63}{n:>9}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--budget", type=int, default=None,
                    help="max train-step compiles before a non-zero exit "
                         "(default: 1 + #buckets when stabilized, else off)")
    ap.add_argument("--no-stabilize", action="store_true",
                    help="disable pad_batches/length_buckets to show the "
                         "per-shape recompile behavior")
    ap.add_argument("--generate", action="store_true",
                    help="also run the KV-cache generation engine (and "
                         "the speculative draft/verify engine) and "
                         "report their compile rows against the "
                         "declared program-family budgets")
    ap.add_argument("--epochs", type=int, default=2)
    args = ap.parse_args(argv)

    stabilize = not args.no_stabilize
    budget = args.budget
    if budget is None and stabilize:
        budget = 1 + len(BUCKETS)

    stats = run_fit(stabilize, args.epochs)

    mode = ("pad_batches=True length_buckets=%s" % (BUCKETS,)
            if stabilize else "raw shapes (no padding/bucketing)")
    print(f"retrace report — {mode}")
    print(f"{'kind':<9}{'trace signature':<63}{'compiles':>9}")
    _print_rows("train", stats["signatures"])
    print(f"{'TOTAL':<9}{'train step':<63}{stats['compiles']:>9}   "
          f"(calls {stats['calls']}, cache hits {stats['cache_hits']})")

    gen_fail = False
    if args.generate:
        gen = run_generate()
        for kind in ("prefill", "decode"):
            _print_rows(kind, gen[kind]["signatures"])
        gen_compiles = gen["prefill"]["compiles"] + gen["decode"]["compiles"]
        gen_calls = gen["prefill"]["calls"] + gen["decode"]["calls"]
        gen_budget = len(GEN_BUCKETS) + 1
        print(f"{'TOTAL':<9}{'generate (prefill+decode)':<63}"
              f"{gen_compiles:>9}   (calls {gen_calls}, budget "
              f"{gen_budget} = #buckets + 1)")
        if gen_compiles > gen_budget:
            print(f"FAIL: generation compiled {gen_compiles} programs > "
                  f"{gen_budget} (#prefill buckets + one decode step)",
                  file=sys.stderr)
            _print_lint_pointers(("prefill", "decode", "generate"))
            gen_fail = True
        # speculative decoding's declared program family rides the same
        # gate: target + draft prefills per bucket, ONE fused decode
        # round (draft chain + verify in a single program)
        spec = run_speculative()
        for kind in ("target_prefill", "draft_prefill", "decode_round"):
            _print_rows(kind, spec[kind]["signatures"])
        spec_compiles = sum(v["compiles"] for v in spec.values())
        spec_calls = sum(v["calls"] for v in spec.values())
        spec_budget = 2 * len(GEN_BUCKETS) + 1
        print(f"{'TOTAL':<9}{'speculative (2 prefill families + round)':<63}"
              f"{spec_compiles:>9}   (calls {spec_calls}, budget "
              f"{spec_budget} = 2 * #buckets + 1)")
        if spec_compiles > spec_budget:
            print(f"FAIL: speculative decoding compiled {spec_compiles} "
                  f"programs > {spec_budget} (target prefill + draft "
                  f"prefill per bucket + one fused decode round)",
                  file=sys.stderr)
            _print_lint_pointers(("speculative", "draft", "verify",
                                  "round"))
            gen_fail = True

    if budget is not None and stats["compiles"] > budget:
        print(f"FAIL: {stats['compiles']} compiles > budget {budget} — "
              f"the input pipeline is recompiling the step", file=sys.stderr)
        _print_lint_pointers(("_step", "trainstep", "train"))
        return 1
    if budget is not None:
        print(f"OK: {stats['compiles']} compiles <= budget {budget}")
    return 1 if gen_fail else 0


if __name__ == "__main__":
    sys.exit(main())
