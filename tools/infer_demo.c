/* Plain-C serving consumer for paddle_tpu exported models.
 *
 * Reference parity: the demo programs of paddle/fluid/inference/capi_exp/
 * — a C-only process serving a saved model with no Python in the source.
 *
 * Usage:
 *   infer_demo <libpaddle_tpu_infer.so> <artifact_prefix> <input.bin> \
 *              <d0> [d1 ...]
 * Reads float32s from input.bin with the given shape, runs one inference,
 * and prints the output shape + float32 values (one per line) on stdout.
 * The runtime needs PYTHONPATH/JAX_PLATFORMS in the environment (see
 * infer_capi.h).
 */
#include <dlfcn.h>
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

typedef void* (*create_fn)(const char*);
typedef int64_t (*run_fn)(void*, const float*, const int64_t*, int32_t,
                          float*, int64_t, int64_t*, int32_t*);
typedef void (*destroy_fn)(void*);
typedef const char* (*err_fn)(void);

int main(int argc, char** argv) {
  if (argc < 5) {
    fprintf(stderr, "usage: %s <lib.so> <artifact> <input.bin> <d0> [d1...]\n",
            argv[0]);
    return 2;
  }
  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_GLOBAL);
  if (!lib) {
    fprintf(stderr, "dlopen: %s\n", dlerror());
    return 2;
  }
  create_fn create = (create_fn)dlsym(lib, "PT_InferCreate");
  run_fn run = (run_fn)dlsym(lib, "PT_InferRun");
  destroy_fn destroy = (destroy_fn)dlsym(lib, "PT_InferDestroy");
  err_fn last_err = (err_fn)dlsym(lib, "PT_InferLastError");
  if (!create || !run || !destroy || !last_err) {
    fprintf(stderr, "missing symbols in %s\n", argv[1]);
    return 2;
  }

  int32_t rank = argc - 4;
  if (rank > 8) {
    fprintf(stderr, "at most 8 input dims supported\n");
    return 2;
  }
  int64_t shape[8];
  int64_t n = 1;
  for (int i = 0; i < rank; ++i) {
    shape[i] = atoll(argv[4 + i]);
    n *= shape[i];
  }
  float* input = (float*)malloc(n * sizeof(float));
  FILE* f = fopen(argv[3], "rb");
  if (!f || fread(input, sizeof(float), (size_t)n, f) != (size_t)n) {
    fprintf(stderr, "failed reading %lld floats from %s\n", (long long)n,
            argv[3]);
    return 2;
  }
  fclose(f);

  void* pred = create(argv[2]);
  if (!pred) {
    fprintf(stderr, "PT_InferCreate: %s\n", last_err());
    return 1;
  }

  int64_t cap = 1 << 20;
  float* output = (float*)malloc(cap * sizeof(float));
  int64_t out_shape[8];
  int32_t out_rank = 0;
  int64_t wrote = run(pred, input, shape, rank, output, cap, out_shape,
                      &out_rank);
  if (wrote < 0) {
    fprintf(stderr, "PT_InferRun: %lld (%s)\n", (long long)wrote, last_err());
    return 1;
  }
  printf("shape");
  for (int i = 0; i < out_rank; ++i) printf(" %lld", (long long)out_shape[i]);
  printf("\n");
  for (int64_t i = 0; i < wrote; ++i) printf("%.8g\n", (double)output[i]);

  destroy(pred);
  free(input);
  free(output);
  return 0;
}
