#!/usr/bin/env python
"""tpu_lint — trace-discipline static analyzer for the TPU-native stack.

Catches, before runtime: host syncs in trace-reachable/hot code (R1),
retrace hazards (R2), donation-after-use (R3), PRNG key reuse (R4),
unguarded shared state in threaded classes (R5), lock-order cycles and
non-reentrant re-entry (R6), blocking work under held locks (R7),
mesh-axis/sharding discipline (R8), exception-path resource-lifecycle
leaks (R9), SPMD collective divergence (R10), and rpc deadline/
idempotence discipline (R11). Pure-AST: no jax import, no backend.

    python tools/tpu_lint.py                          # paddle_tpu + tools
    python tools/tpu_lint.py paddle_tpu/serving       # a subtree
    python tools/tpu_lint.py --changed-only           # pre-commit: git
    python tools/tpu_lint.py --baseline .tpu_lint_baseline.json
    python tools/tpu_lint.py --baseline ... --update-baseline
    python tools/tpu_lint.py --json                   # machine-readable
    python tools/tpu_lint.py --sarif out.sarif        # CI PR annotations
    python tools/tpu_lint.py --list-rules

Incremental engine: full runs persist a content-hash result cache under
``.tpu_lint_cache/`` — when nothing changed, the next whole-repo run is
served from the cache in milliseconds; any edit re-analyzes (and
refreshes). ``--changed-only`` asks git for the changed files and lints
just their one-hop import closure — the sub-second pre-commit path (it
falls back to a full run when no cache exists yet). ``--no-cache``
disables both. ``--json`` carries ``schema_version``, a ``timing`` block
(per-file parse/lint ms, per-rule totals), the R6 ``lock_graph`` (lock
nodes, acquisition sites, held→acquired order edges), the R9
``lifecycle_graph`` (protocols + per-function acquire/release sites),
and a ``cache`` block (hit/miss, mode, changed files). ``--sarif PATH``
writes the same findings as SARIF 2.1.0 so CI can annotate PR diffs
(``-`` for stdout; NEW-vs-baseline status rides in each result's
``properties.new``).

Exit codes: 0 = clean (every finding suppressed or baselined);
1 = NEW findings (beyond the baseline); 2 = usage error.

Suppression (reason REQUIRED — a bare disable is rule R0 and fails)::

    x = flag.item()   # tpu-lint: disable=R1(one-time init readback)
    # tpu-lint: disable-file=R5(single-threaded CLI tool)

Baseline workflow: triage every finding — fix it or suppress it with a
reason; only then accept the residue with ``--update-baseline``. The
checked-in ``.tpu_lint_baseline.json`` makes pre-existing accepted
findings pass while any NEW finding fails the build (first stage of
``tools/robustness_gate.py``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_PATHS = ("paddle_tpu", "tools")
DEFAULT_BASELINE = os.path.join(REPO, ".tpu_lint_baseline.json")
# 3: R9/R10/R11 rule families, the `lifecycle_graph` block, and the
# baseline re-key (baseline format v3) — see MIGRATION.md
SCHEMA_VERSION = 3


def _emit_json(payload: dict) -> None:
    print(json.dumps(payload, indent=1))


def to_sarif(findings, new_keys, rule_docs) -> dict:
    """SARIF 2.1.0 for CI PR annotation. One result per finding;
    ``partialFingerprints.tpuLintKey`` is the baseline key (stable
    across line drift), ``properties.new`` marks findings beyond the
    baseline — the ones a PR gate should comment on."""
    results = []
    for f in findings:
        msg = f.message
        if f.hint:
            msg += f" (hint: {f.hint})"
        results.append({
            "ruleId": f.rule,
            "level": "error" if f.key() in new_keys else "note",
            "message": {"text": msg},
            "partialFingerprints": {"tpuLintKey": f.key()},
            "properties": {"new": f.key() in new_keys,
                           "symbol": f.symbol,
                           "chain": list(f.chain)},
            "locations": [{"physicalLocation": {
                "artifactLocation": {"uri": f.path},
                "region": {"startLine": int(f.line)}}}],
        })
    return {
        "$schema": "https://json.schemastore.org/sarif-2.1.0.json",
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "tpu_lint",
                "informationUri":
                    "README.md#static-analysis-tpu_lint",
                "rules": [{"id": rid,
                           "shortDescription": {"text": doc}}
                          for rid, doc in sorted(rule_docs.items())],
            }},
            "results": results,
        }],
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: paddle_tpu tools)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (schema_version, "
                         "timing, lock_graph, cache blocks)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; accepted findings pass, new "
                         "findings fail (default: .tpu_lint_baseline.json "
                         "when it exists)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0 (R0 policy findings still fail)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore any baseline")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only git-changed files (plus their one-"
                         "hop import closure for context) — the "
                         "pre-commit path; falls back to a full run "
                         "when no cache exists")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the .tpu_lint_cache/ incremental "
                         "engine (always analyze from scratch)")
    ap.add_argument("--cache-dir", default=None,
                    help="cache directory (default: "
                         "<repo>/.tpu_lint_cache)")
    ap.add_argument("--sarif", default=None, metavar="PATH",
                    help="also write findings as SARIF 2.1.0 (for CI "
                         "PR annotation); '-' writes to stdout")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import (analyze, diff_baseline, load_baseline,
                                     save_baseline, RULE_DOCS)
    from paddle_tpu.analysis.cache import LintCache, git_changed_files

    if args.list_rules:
        for rule, doc in sorted(RULE_DOCS.items()):
            print(f"{rule}  {doc}")
        return 0

    paths = list(args.paths) or list(DEFAULT_PATHS)
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(REPO, p)
        if not os.path.exists(full):
            print(f"tpu_lint: no such path: {p}", file=sys.stderr)
            return 2
    if args.update_baseline and args.paths:
        # a subtree run sees a subset of the findings — rewriting the
        # whole-repo baseline from it would silently erase every
        # accepted entry outside the subtree and fail the next gate
        print("tpu_lint: --update-baseline only works on the default "
              "scope (paddle_tpu + tools); drop the explicit paths",
              file=sys.stderr)
        return 2
    if args.update_baseline and args.changed_only:
        print("tpu_lint: --update-baseline needs the full view; drop "
              "--changed-only", file=sys.stderr)
        return 2
    if args.update_baseline and args.sarif:
        # the baseline rewrite returns before findings are gated, so a
        # combined invocation would silently skip the SARIF write —
        # reject loudly like the other --update-baseline combos
        print("tpu_lint: --update-baseline does not emit SARIF; run "
              "--sarif in a separate invocation", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline \
            and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    cache = None if args.no_cache else LintCache(REPO, args.cache_dir)
    t0 = time.monotonic()
    cache_info = {"enabled": cache is not None, "hit": False,
                  "mode": "full"}

    result = None
    findings = None
    stats = None
    lock_graph = {}
    lifecycle_graph = {}
    timing = {}
    changed = None

    if args.changed_only:
        changed = git_changed_files(REPO, paths)
        entry = cache.cached_entry(paths) if cache is not None else None
        if entry is not None and changed:
            # (an EMPTY diff takes the whole-tree path below, where
            # cache.load validates every digest itself — no staleness
            # check needed here for that case)
            # the cached graph is only trustworthy for the UNCHANGED
            # side of the tree: if files outside the git diff drifted
            # since the last full run (a pull landed commits, a file
            # appeared/vanished), their trace roots / lock edges are
            # missing from the graph and the closure would silently
            # lose context — fall back to a full run (which refreshes)
            live = cache.tree_digests(paths)
            skip = set(changed)
            if {k: v for k, v in live.items() if k not in skip} != \
                    {k: v for k, v in (entry.get("files") or {}).items()
                     if k not in skip}:
                entry = None
        if changed is None or entry is None:
            why = ("git unavailable" if changed is None
                   else "cached import graph missing or stale vs the "
                        "unchanged tree (full run refreshes it)")
            cache_info["mode"] = f"full (changed-only fallback: {why})"
            changed = None
        elif not changed:
            # empty diff: there is no changed-file subset to gate, so
            # the verdict is the WHOLE tree's — served from the cache
            # when it matches (milliseconds), re-analyzed (and the
            # cache refreshed) when the committed tree drifted. The
            # old behavior ("nothing uncommitted" = instant OK) let a
            # committed-but-never-linted violation pass a gate run on
            # a clean checkout.
            cache_info.update(mode="changed-only (empty diff: "
                                   "whole-tree verdict)", changed=[])
            changed = None
        else:
            # cached import graph for the unchanged side of the tree,
            # OVERLAID with the changed files' freshly parsed imports —
            # a dependency edge the edit itself just added must pull
            # its target into the lint scope
            imports = dict(entry.get("imports") or {})
            imports.update(cache.fresh_imports(
                changed, list(entry.get("files") or ())))
            scope = LintCache.closure(changed, imports)
            cache_info.update(mode="changed-only", changed=changed,
                              closure_files=len(scope))
            result = analyze(REPO, scope)
            # only findings IN the changed files gate; context files were
            # linted for cross-file resolution, not for reporting
            keep = set(changed)
            findings = [f for f in result.findings if f.path in keep]
            stats = result.stats()
            lock_graph = result.lock_graph
            lifecycle_graph = result.lifecycle_graph
            timing = result.timing

    if findings is None:
        digests = cache.tree_digests(paths) if cache is not None else {}
        got = cache.load(paths, digests) if cache is not None else None
        if got is not None:
            cache_info["hit"] = True
            findings = LintCache.findings_from(got)
            stats = got.get("stats", {})
            lock_graph = got.get("lock_graph", {})
            lifecycle_graph = got.get("lifecycle_graph", {})
            timing = {"total_ms": round((time.monotonic() - t0) * 1e3, 3),
                      "cached_run": got.get("timing", {})}
        else:
            result = analyze(REPO, paths)
            findings = result.findings
            stats = result.stats()
            lock_graph = result.lock_graph
            lifecycle_graph = result.lifecycle_graph
            timing = result.timing
            if cache is not None:
                cache.store(paths, digests, findings, stats, lock_graph,
                            result.project_imports(), timing,
                            lifecycle_graph=lifecycle_graph)
    elapsed = time.monotonic() - t0

    if args.update_baseline:
        target = baseline_path or DEFAULT_BASELINE
        keep = [f for f in findings if f.rule != "R0"]
        save_baseline(target, keep)
        r0 = [f for f in findings if f.rule == "R0"]
        print(f"tpu_lint: baseline updated: {target} "
              f"({len(keep)} finding(s) accepted)")
        for f in r0:
            print(f.render())
        return 1 if r0 else 0

    baseline = {}
    if baseline_path and not args.no_baseline:
        baseline = load_baseline(baseline_path)
    new, stale = diff_baseline(findings, baseline)
    if changed is not None:
        stale = []      # a partial view cannot judge staleness

    if args.sarif:
        sarif = to_sarif(findings, {f.key() for f in new}, RULE_DOCS)
        if args.sarif == "-":
            print(json.dumps(sarif, indent=1))
        else:
            with open(args.sarif, "w", encoding="utf-8") as fh:
                json.dump(sarif, fh, indent=1)
                fh.write("\n")

    if args.as_json:
        _emit_json({
            "schema_version": SCHEMA_VERSION,
            "stats": stats,
            "elapsed_s": round(elapsed, 3),
            "baseline": baseline_path if baseline else None,
            "cache": cache_info,
            "timing": timing,
            "lock_graph": lock_graph,
            "lifecycle_graph": lifecycle_graph,
            "findings": [f.as_dict() for f in findings],
            "new_findings": [f.as_dict() for f in new],
            "stale_baseline_keys": stale,
        })
        return 1 if new else 0

    if stats:
        mode = ""
        if cache_info["hit"]:
            mode = " [cache hit]"
        elif changed is not None:
            mode = (f" [changed-only: {len(changed)} changed, "
                    f"{cache_info.get('closure_files', 0)} in closure]")
        print(f"tpu_lint: {stats.get('files', 0)} files, "
              f"{stats.get('trace_roots', 0)} trace roots, "
              f"{stats.get('trace_reachable', 0)} trace-reachable fns, "
              f"{stats.get('thread_roots', 0)} thread roots, "
              f"{stats.get('locks', 0)} locks "
              f"({elapsed:.2f}s){mode}")
    if baseline:
        accepted = len(findings) - len(new)
        print(f"tpu_lint: {len(findings)} finding(s); "
              f"{accepted} baselined, {len(new)} NEW")
    else:
        print(f"tpu_lint: {len(findings)} finding(s)")
    shown = new if baseline else findings
    for f in shown:
        print(f.render())
    for k in stale:
        print(f"stale baseline entry (consider --update-baseline): {k}")
    if new:
        print(f"\nFAIL: {len(new)} new finding(s) — fix them, or "
              f"suppress with `# tpu-lint: disable=R<n>(reason)`, or "
              f"(last resort) re-accept with --update-baseline",
              file=sys.stderr)
        return 1
    print("OK: no new findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
