#!/usr/bin/env python
"""tpu_lint — trace-discipline static analyzer for the TPU-native stack.

Catches, before runtime: host syncs in trace-reachable/hot code (R1),
retrace hazards (R2), donation-after-use (R3), PRNG key reuse (R4), and
unguarded shared state in threaded classes (R5). Pure-AST: no jax import,
no backend, whole-repo runs in seconds.

    python tools/tpu_lint.py                          # paddle_tpu + tools
    python tools/tpu_lint.py paddle_tpu/serving       # a subtree
    python tools/tpu_lint.py --baseline .tpu_lint_baseline.json
    python tools/tpu_lint.py --baseline ... --update-baseline
    python tools/tpu_lint.py --json                   # machine-readable
    python tools/tpu_lint.py --list-rules

Exit codes: 0 = clean (every finding suppressed or baselined);
1 = NEW findings (beyond the baseline); 2 = usage error.

Suppression (reason REQUIRED — a bare disable is rule R0 and fails)::

    x = flag.item()   # tpu-lint: disable=R1(one-time init readback)
    # tpu-lint: disable-file=R5(single-threaded CLI tool)

Baseline workflow: triage every finding — fix it or suppress it with a
reason; only then accept the residue with ``--update-baseline``. The
checked-in ``.tpu_lint_baseline.json`` makes pre-existing accepted
findings pass while any NEW finding fails the build (first stage of
``tools/robustness_gate.py``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_PATHS = ("paddle_tpu", "tools")
DEFAULT_BASELINE = os.path.join(REPO, ".tpu_lint_baseline.json")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: paddle_tpu tools)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; accepted findings pass, new "
                         "findings fail (default: .tpu_lint_baseline.json "
                         "when it exists)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0 (R0 policy findings still fail)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore any baseline")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import (analyze, diff_baseline, load_baseline,
                                     save_baseline, RULE_DOCS)

    if args.list_rules:
        for rule, doc in sorted(RULE_DOCS.items()):
            print(f"{rule}  {doc}")
        return 0

    paths = list(args.paths) or list(DEFAULT_PATHS)
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(REPO, p)
        if not os.path.exists(full):
            print(f"tpu_lint: no such path: {p}", file=sys.stderr)
            return 2

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline \
            and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    t0 = time.monotonic()
    result = analyze(REPO, paths)
    elapsed = time.monotonic() - t0

    if args.update_baseline:
        if args.paths:
            # a subtree run sees a subset of the findings — rewriting the
            # whole-repo baseline from it would silently erase every
            # accepted entry outside the subtree and fail the next gate
            print("tpu_lint: --update-baseline only works on the default "
                  "scope (paddle_tpu + tools); drop the explicit paths",
                  file=sys.stderr)
            return 2
        target = baseline_path or DEFAULT_BASELINE
        keep = [f for f in result.findings if f.rule != "R0"]
        save_baseline(target, keep)
        r0 = [f for f in result.findings if f.rule == "R0"]
        print(f"tpu_lint: baseline updated: {target} "
              f"({len(keep)} finding(s) accepted)")
        for f in r0:
            print(f.render())
        return 1 if r0 else 0

    baseline = {}
    if baseline_path and not args.no_baseline:
        baseline = load_baseline(baseline_path)
    new, stale = diff_baseline(result.findings, baseline)

    if args.as_json:
        print(json.dumps({
            "stats": result.stats(),
            "elapsed_s": round(elapsed, 3),
            "baseline": baseline_path if baseline else None,
            "findings": [f.as_dict() for f in result.findings],
            "new_findings": [f.as_dict() for f in new],
            "stale_baseline_keys": stale,
        }, indent=1))
        return 1 if new else 0

    stats = result.stats()
    print(f"tpu_lint: {stats['files']} files, "
          f"{stats['trace_roots']} trace roots, "
          f"{stats['trace_reachable']} trace-reachable fns, "
          f"{stats['thread_roots']} thread roots "
          f"({elapsed:.2f}s)")
    if baseline:
        accepted = len(result.findings) - len(new)
        print(f"tpu_lint: {len(result.findings)} finding(s); "
              f"{accepted} baselined, {len(new)} NEW")
    else:
        print(f"tpu_lint: {len(result.findings)} finding(s)")
    shown = new if baseline else result.findings
    for f in shown:
        print(f.render())
    for k in stale:
        print(f"stale baseline entry (consider --update-baseline): {k}")
    if new:
        print(f"\nFAIL: {len(new)} new finding(s) — fix them, or "
              f"suppress with `# tpu-lint: disable=R<n>(reason)`, or "
              f"(last resort) re-accept with --update-baseline",
              file=sys.stderr)
        return 1
    print("OK: no new findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
