#!/usr/bin/env python
"""tpu_lint — trace-discipline static analyzer for the TPU-native stack.

Catches, before runtime: host syncs in trace-reachable/hot code (R1),
retrace hazards (R2), donation-after-use (R3), PRNG key reuse (R4),
unguarded shared state in threaded classes (R5), lock-order cycles and
non-reentrant re-entry (R6), blocking work under held locks (R7), and
mesh-axis/sharding discipline (R8). Pure-AST: no jax import, no backend.

    python tools/tpu_lint.py                          # paddle_tpu + tools
    python tools/tpu_lint.py paddle_tpu/serving       # a subtree
    python tools/tpu_lint.py --changed-only           # pre-commit: git
    python tools/tpu_lint.py --baseline .tpu_lint_baseline.json
    python tools/tpu_lint.py --baseline ... --update-baseline
    python tools/tpu_lint.py --json                   # machine-readable
    python tools/tpu_lint.py --list-rules

Incremental engine: full runs persist a content-hash result cache under
``.tpu_lint_cache/`` — when nothing changed, the next whole-repo run is
served from the cache in milliseconds; any edit re-analyzes (and
refreshes). ``--changed-only`` asks git for the changed files and lints
just their one-hop import closure — the sub-second pre-commit path (it
falls back to a full run when no cache exists yet). ``--no-cache``
disables both. ``--json`` carries ``schema_version``, a ``timing`` block
(per-file parse/lint ms, per-rule totals), the R6 ``lock_graph`` (lock
nodes, acquisition sites, held→acquired order edges), and a ``cache``
block (hit/miss, mode, changed files).

Exit codes: 0 = clean (every finding suppressed or baselined);
1 = NEW findings (beyond the baseline); 2 = usage error.

Suppression (reason REQUIRED — a bare disable is rule R0 and fails)::

    x = flag.item()   # tpu-lint: disable=R1(one-time init readback)
    # tpu-lint: disable-file=R5(single-threaded CLI tool)

Baseline workflow: triage every finding — fix it or suppress it with a
reason; only then accept the residue with ``--update-baseline``. The
checked-in ``.tpu_lint_baseline.json`` makes pre-existing accepted
findings pass while any NEW finding fails the build (first stage of
``tools/robustness_gate.py``).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_PATHS = ("paddle_tpu", "tools")
DEFAULT_BASELINE = os.path.join(REPO, ".tpu_lint_baseline.json")
SCHEMA_VERSION = 2


def _emit_json(payload: dict) -> None:
    print(json.dumps(payload, indent=1))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="*", default=None,
                    help="files/dirs to lint (default: paddle_tpu tools)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output (schema_version, "
                         "timing, lock_graph, cache blocks)")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON; accepted findings pass, new "
                         "findings fail (default: .tpu_lint_baseline.json "
                         "when it exists)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline from the current findings "
                         "and exit 0 (R0 policy findings still fail)")
    ap.add_argument("--list-rules", action="store_true")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, ignore any baseline")
    ap.add_argument("--changed-only", action="store_true",
                    help="lint only git-changed files (plus their one-"
                         "hop import closure for context) — the "
                         "pre-commit path; falls back to a full run "
                         "when no cache exists")
    ap.add_argument("--no-cache", action="store_true",
                    help="disable the .tpu_lint_cache/ incremental "
                         "engine (always analyze from scratch)")
    ap.add_argument("--cache-dir", default=None,
                    help="cache directory (default: "
                         "<repo>/.tpu_lint_cache)")
    args = ap.parse_args(argv)

    from paddle_tpu.analysis import (analyze, diff_baseline, load_baseline,
                                     save_baseline, RULE_DOCS)
    from paddle_tpu.analysis.cache import LintCache, git_changed_files

    if args.list_rules:
        for rule, doc in sorted(RULE_DOCS.items()):
            print(f"{rule}  {doc}")
        return 0

    paths = list(args.paths) or list(DEFAULT_PATHS)
    for p in paths:
        full = p if os.path.isabs(p) else os.path.join(REPO, p)
        if not os.path.exists(full):
            print(f"tpu_lint: no such path: {p}", file=sys.stderr)
            return 2
    if args.update_baseline and args.paths:
        # a subtree run sees a subset of the findings — rewriting the
        # whole-repo baseline from it would silently erase every
        # accepted entry outside the subtree and fail the next gate
        print("tpu_lint: --update-baseline only works on the default "
              "scope (paddle_tpu + tools); drop the explicit paths",
              file=sys.stderr)
        return 2
    if args.update_baseline and args.changed_only:
        print("tpu_lint: --update-baseline needs the full view; drop "
              "--changed-only", file=sys.stderr)
        return 2

    baseline_path = args.baseline
    if baseline_path is None and not args.no_baseline \
            and os.path.exists(DEFAULT_BASELINE):
        baseline_path = DEFAULT_BASELINE

    cache = None if args.no_cache else LintCache(REPO, args.cache_dir)
    t0 = time.monotonic()
    cache_info = {"enabled": cache is not None, "hit": False,
                  "mode": "full"}

    result = None
    findings = None
    stats = None
    lock_graph = {}
    timing = {}
    changed = None

    if args.changed_only:
        changed = git_changed_files(REPO, paths)
        entry = cache.cached_entry(paths) if cache is not None else None
        if entry is not None and changed:
            # (an EMPTY diff short-circuits below without this check —
            # "nothing uncommitted" is a clean pre-commit answer no
            # matter how stale the cache is)
            # the cached graph is only trustworthy for the UNCHANGED
            # side of the tree: if files outside the git diff drifted
            # since the last full run (a pull landed commits, a file
            # appeared/vanished), their trace roots / lock edges are
            # missing from the graph and the closure would silently
            # lose context — fall back to a full run (which refreshes)
            live = cache.tree_digests(paths)
            skip = set(changed)
            if {k: v for k, v in live.items() if k not in skip} != \
                    {k: v for k, v in (entry.get("files") or {}).items()
                     if k not in skip}:
                entry = None
        if changed is None or entry is None:
            why = ("git unavailable" if changed is None
                   else "cached import graph missing or stale vs the "
                        "unchanged tree (full run refreshes it)")
            cache_info["mode"] = f"full (changed-only fallback: {why})"
            changed = None
        elif not changed:
            elapsed = time.monotonic() - t0
            cache_info.update(mode="changed-only", changed=[])
            if args.as_json:
                _emit_json({"schema_version": SCHEMA_VERSION,
                            "stats": {}, "elapsed_s": round(elapsed, 3),
                            "baseline": baseline_path, "cache": cache_info,
                            "timing": {"total_ms":
                                       round(elapsed * 1e3, 3)},
                            "lock_graph": {}, "findings": [],
                            "new_findings": [],
                            "stale_baseline_keys": []})
            else:
                print(f"tpu_lint: no changed files under "
                      f"{' '.join(paths)} ({elapsed:.2f}s)")
                print("OK: no new findings")
            return 0
        else:
            # cached import graph for the unchanged side of the tree,
            # OVERLAID with the changed files' freshly parsed imports —
            # a dependency edge the edit itself just added must pull
            # its target into the lint scope
            imports = dict(entry.get("imports") or {})
            imports.update(cache.fresh_imports(
                changed, list(entry.get("files") or ())))
            scope = LintCache.closure(changed, imports)
            cache_info.update(mode="changed-only", changed=changed,
                              closure_files=len(scope))
            result = analyze(REPO, scope)
            # only findings IN the changed files gate; context files were
            # linted for cross-file resolution, not for reporting
            keep = set(changed)
            findings = [f for f in result.findings if f.path in keep]
            stats = result.stats()
            lock_graph = result.lock_graph
            timing = result.timing

    if findings is None:
        digests = cache.tree_digests(paths) if cache is not None else {}
        got = cache.load(paths, digests) if cache is not None else None
        if got is not None:
            cache_info["hit"] = True
            findings = LintCache.findings_from(got)
            stats = got.get("stats", {})
            lock_graph = got.get("lock_graph", {})
            timing = {"total_ms": round((time.monotonic() - t0) * 1e3, 3),
                      "cached_run": got.get("timing", {})}
        else:
            result = analyze(REPO, paths)
            findings = result.findings
            stats = result.stats()
            lock_graph = result.lock_graph
            timing = result.timing
            if cache is not None:
                cache.store(paths, digests, findings, stats, lock_graph,
                            result.project_imports(), timing)
    elapsed = time.monotonic() - t0

    if args.update_baseline:
        target = baseline_path or DEFAULT_BASELINE
        keep = [f for f in findings if f.rule != "R0"]
        save_baseline(target, keep)
        r0 = [f for f in findings if f.rule == "R0"]
        print(f"tpu_lint: baseline updated: {target} "
              f"({len(keep)} finding(s) accepted)")
        for f in r0:
            print(f.render())
        return 1 if r0 else 0

    baseline = {}
    if baseline_path and not args.no_baseline:
        baseline = load_baseline(baseline_path)
    new, stale = diff_baseline(findings, baseline)
    if changed is not None:
        stale = []      # a partial view cannot judge staleness

    if args.as_json:
        _emit_json({
            "schema_version": SCHEMA_VERSION,
            "stats": stats,
            "elapsed_s": round(elapsed, 3),
            "baseline": baseline_path if baseline else None,
            "cache": cache_info,
            "timing": timing,
            "lock_graph": lock_graph,
            "findings": [f.as_dict() for f in findings],
            "new_findings": [f.as_dict() for f in new],
            "stale_baseline_keys": stale,
        })
        return 1 if new else 0

    if stats:
        mode = ""
        if cache_info["hit"]:
            mode = " [cache hit]"
        elif changed is not None:
            mode = (f" [changed-only: {len(changed)} changed, "
                    f"{cache_info.get('closure_files', 0)} in closure]")
        print(f"tpu_lint: {stats.get('files', 0)} files, "
              f"{stats.get('trace_roots', 0)} trace roots, "
              f"{stats.get('trace_reachable', 0)} trace-reachable fns, "
              f"{stats.get('thread_roots', 0)} thread roots, "
              f"{stats.get('locks', 0)} locks "
              f"({elapsed:.2f}s){mode}")
    if baseline:
        accepted = len(findings) - len(new)
        print(f"tpu_lint: {len(findings)} finding(s); "
              f"{accepted} baselined, {len(new)} NEW")
    else:
        print(f"tpu_lint: {len(findings)} finding(s)")
    shown = new if baseline else findings
    for f in shown:
        print(f.render())
    for k in stale:
        print(f"stale baseline entry (consider --update-baseline): {k}")
    if new:
        print(f"\nFAIL: {len(new)} new finding(s) — fix them, or "
              f"suppress with `# tpu-lint: disable=R<n>(reason)`, or "
              f"(last resort) re-accept with --update-baseline",
              file=sys.stderr)
        return 1
    print("OK: no new findings")
    return 0


if __name__ == "__main__":
    sys.exit(main())
