"""Real-TPU flash-kernel validation (dropout needs the TPU PRNG, which has
no CPU/interpret lowering — this complements tests/test_flash_attention.py).

Run: python -m tools.flash_check
"""
import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.kernels import flash_attention as fa


def _rand(shape, seed):
    return jnp.asarray(np.random.default_rng(seed).standard_normal(shape),
                       jnp.float32)


def main():
    assert jax.default_backend() == "tpu", jax.default_backend()
    B, H, L, D = 2, 4, 1024, 64
    q, k, v = _rand((B, H, L, D), 0), _rand((B, H, L, D), 1), _rand((B, H, L, D), 2)

    # fwd/bwd parity vs reference
    o = fa.flash_attention_bhld(q, k, v, causal=True)
    ref = fa.reference_attention_bhld(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(o - ref)))
    print("fwd max err", err)
    assert err < 2e-5, err

    g = jax.grad(lambda *a: jnp.sum(fa.flash_attention_bhld(*a, causal=True) ** 2),
                 argnums=(0, 1, 2))(q, k, v)
    gr = jax.grad(lambda *a: jnp.sum(fa.reference_attention_bhld(*a, causal=True) ** 2),
                  argnums=(0, 1, 2))(q, k, v)
    for a, b, n in zip(g, gr, "qkv"):
        e = float(jnp.max(jnp.abs(a - b)))
        print(f"d{n} max err", e)
        assert e < 5e-4, (n, e)

    # bias path
    bias = 0.5 * _rand((1, 1, L, L), 3)
    o = fa.flash_attention_bhld(q, k, v, causal=True, bias=bias)
    ref = fa.reference_attention_bhld(q, k, v, causal=True, bias=bias)
    e = float(jnp.max(jnp.abs(o - ref)))
    print("bias fwd max err", e)
    assert e < 2e-5, e

    # dropout: mean preserved (upscale_in_train), deterministic per seed,
    # different across seeds, zero-fraction ~ p
    p_drop = 0.2
    o1 = fa.flash_attention_bhld(q, k, v, causal=True, dropout_p=p_drop, seed=7)
    o2 = fa.flash_attention_bhld(q, k, v, causal=True, dropout_p=p_drop, seed=7)
    o3 = fa.flash_attention_bhld(q, k, v, causal=True, dropout_p=p_drop, seed=8)
    assert float(jnp.max(jnp.abs(o1 - o2))) == 0.0, "dropout not deterministic per seed"
    assert float(jnp.max(jnp.abs(o1 - o3))) > 0.0, "dropout ignores seed"
    rel = abs(float(o1.mean()) - float(o.mean() if False else ref.mean()))
    print("dropout mean |drop - ref|:", rel, "(ref mean", float(ref.mean()), ")")
    # dropout bwd runs and is finite
    gd = jax.grad(lambda q: jnp.sum(fa.flash_attention_bhld(
        q, k, v, causal=True, dropout_p=p_drop, seed=7) ** 2))(q)
    assert bool(jnp.isfinite(gd).all())
    print("dropout bwd finite OK")

    # traced seed: no retrace across seeds inside jit
    @jax.jit
    def step(q, seed):
        return fa.flash_attention_bhld(q, k, v, causal=True, dropout_p=p_drop,
                                       seed=seed).sum()

    s1 = step(q, jnp.int32(1))
    s2 = step(q, jnp.int32(2))
    assert float(s1) != float(s2)
    print("traced-seed jit OK; all flash TPU checks passed")


if __name__ == "__main__":
    main()
