#!/usr/bin/env python
"""Chaos soak: a short GPT pretrain under injected NaN batches, step
stalls, and a mid-training SIGKILL — asserting the self-healing layer
(``Model.fit(recovery=...)``, ``framework/supervisor.py``) recovers to the
SAME answer as an undisturbed run.

Three child runs (each a fresh interpreter, like ``tools/fault_sweep.py``):

1. **baseline** — no faults; records the final eval loss.
2. **chaos #1** — a seeded FaultPlan poisons 2 consecutive batches with NaN
   (``drop`` @ ``train.data`` → the step's NaN seam), stalls one step past
   the hang watchdog's ``step_timeout`` (``delay`` @ ``train.step``), and
   kills the process cold at the 3rd checkpoint attempt (``crash`` @
   ``train.ckpt``, as hard as SIGKILL). The run must die with CRASH_EXIT
   after logging >=1 anomaly, >=1 rollback and >=1 hang detection to its
   event log.
3. **chaos #2** — a clean restart against the same checkpoint root resumes
   from the last published snapshot + data cursor and runs to completion.

Pass criteria (exit 0 iff all hold):

- chaos final eval loss within ``--tol`` (default 1%) of the baseline;
- every injected fault observed (anomaly/rollback/hang events + the kill);
- no steady-state recompiles: each child enters ``retrace_guard(0)`` after
  warmup, so a rollback/replay or resume that retraced the step would have
  failed the child outright.

**Elastic scenario** (``--elastic``): the shrink/grow-on-preemption proof.
Four child runs against ONE checkpoint root, each a fresh interpreter with
its own simulated device count:

1. **baseline** — 8 devices (dp4 x mp2), uninterrupted; records the final
   eval loss.
2. **elastic #1** — 8 devices, killed cold (``crash`` @ ``train.ckpt``)
   mid-training: the "preemption notice never arrived" case.
3. **elastic #2 (shrink)** — only 4 devices survive: the child rebuilds a
   dp2 x mp2 mesh via ``elastic_mesh.reshaped_mesh``, reshard-restores the
   newest complete checkpoint (must log ``elastic reshard``), trains on,
   and is killed again.
4. **elastic #3 (grow)** — capacity returns (8 devices): reshard back up,
   run to completion. Final eval loss must match the baseline within
   ``--tol`` — training effectively never stopped.

Usage::

    python tools/chaos_soak.py            # full soak
    python tools/chaos_soak.py --quick    # CI-sized (robustness_gate)
    python tools/chaos_soak.py --elastic --quick   # shrink/grow scenario
"""
from __future__ import annotations

import argparse
import json
import math
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.distributed.resilience import CRASH_EXIT, FaultPlan  # noqa: E402

SEQ = 32
BATCH = 4

# elastic scenario: a dp x mp2 teacher-fit MLP, global batch constant
# across resizes (divisible by every dp degree the job can shrink to)
ELASTIC_DIM = 16
ELASTIC_BATCH = 8


def _config(quick: bool):
    """(docs, epochs): enough steps to reach the random-token plateau, so
    the 1% tolerance compares converged runs, not transients."""
    return (64, 2) if quick else (64, 4)


# --------------------------------------------------------------------- child
def run_child(args) -> int:
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import profiler
    from paddle_tpu.framework import compile_cache
    from paddle_tpu.framework.supervisor import RecoveryPolicy
    from paddle_tpu.hapi import Model
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.optimizer import AdamW

    n_docs, epochs = _config(args.quick)
    pt.seed(args.seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=SEQ, hidden_dropout_prob=0.0,
                    attention_dropout_prob=0.0, use_flash_attention=False)
    model = Model(GPTForCausalLM(cfg), labels=[])  # forward(ids, labels)->loss
    model.prepare(AdamW(learning_rate=1e-3))

    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, (n_docs, SEQ)).astype(np.int32)
    train = pt.io.TensorDataset([ids, ids])
    eval_rng = np.random.default_rng(args.seed + 1)
    eval_ids = eval_rng.integers(0, cfg.vocab_size,
                                 (4, BATCH, SEQ)).astype(np.int32)

    events_path = os.path.join(args.workdir, "events.jsonl")

    class EventLog(pt.hapi.Callback):
        """Crash-surviving record of what the supervisor observed (the
        killed incarnation cannot write a result file)."""

        def __init__(self):
            super().__init__()
            self._fh = open(events_path, "a")
            self._hangs = 0

        def _emit(self, event, **kw):
            self._fh.write(json.dumps({"event": event, "pid": os.getpid(),
                                       **kw}) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())

        def on_train_anomaly(self, logs=None):
            self._emit("anomaly", **(logs or {}))

        def on_rollback(self, logs=None):
            info = dict(logs or {})
            info.pop("cursor", None)  # not JSON-serializable
            self._emit("rollback", **info)

        def on_preemption(self, logs=None):
            self._emit("preemption", **(logs or {}))

        def on_train_batch_end(self, step, logs=None):
            hangs = profiler.counter_values().get("train.hang", 0)
            if hangs > self._hangs:
                self._emit("hang", count=hangs)
                self._hangs = hangs

    class GuardAfterWarmup(pt.hapi.Callback):
        """retrace_guard(0) once the step program is traced: any recompile
        caused by rollback/replay/resume fails the child loudly."""

        def __init__(self, warmup=3):
            super().__init__()
            self.warmup = warmup
            self._cm = None

        def on_train_batch_end(self, step, logs=None):
            if self._cm is None and step + 1 >= self.warmup:
                self._cm = compile_cache.retrace_guard(
                    0, label="chaos-steady")
                self._cm.__enter__()

        def release(self):
            if self._cm is not None:
                self._cm.__exit__(None, None, None)
                self._cm = None

    guard = GuardAfterWarmup()
    policy = RecoveryPolicy(
        checkpoint_dir=os.path.join(args.workdir, "ckpt"),
        save_interval_steps=5, check_interval=2, max_consecutive=2,
        skip_window=2, step_timeout=0.5, hang_action="warn",
        preemption=True, grace_seconds=20.0, async_save=False)
    import warnings

    t0 = time.monotonic()
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            hist = model.fit(train, batch_size=BATCH, epochs=epochs,
                             shuffle=False, verbose=0,
                             callbacks=[EventLog(), guard],
                             recovery=policy)
    finally:
        guard.release()   # EvalStep below compiles legitimately

    eval_losses = [float(np.asarray(model.predict_batch((b, b))))
                   for b in eval_ids]
    step = model._train_step
    result = {
        "final_eval_loss": float(np.mean(eval_losses)),
        "train_loss": float(hist["loss"][-1]),
        "step_compiles": step.cache_stats()["compiles"],
        "counters": profiler.counter_values(),
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    out = os.path.join(args.workdir, "result.json")
    with open(out + ".tmp", "w") as f:
        json.dump(result, f, indent=1)
    os.replace(out + ".tmp", out)
    print(json.dumps(result))
    return 0


# ------------------------------------------------------------- elastic child
def run_elastic_child(args) -> int:
    """One incarnation of the elastic trainer.

    Builds the mesh for THIS device count from the newest checkpoint's
    recorded topology (``elastic_mesh.reshaped_mesh``), reshard-restores
    through the supervisor, and trains to ``--total-steps`` with periodic
    checkpoints — where the fault plan's ``train.ckpt`` crash kills the
    process cold. The data stream is a pure function of the global step,
    so every incarnation (any topology) sees the same batches: final loss
    is comparable across baseline and shrink/grow sequences.
    """
    import numpy as np

    import jax

    import paddle_tpu as pt
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F
    from paddle_tpu.distributed import elastic_mesh
    from paddle_tpu.distributed.checkpoint import last_load_stats
    from paddle_tpu.distributed.parallel.mp_layers import (
        ColumnParallelLinear, RowParallelLinear)
    from paddle_tpu.distributed.shard import DistributedTrainStep
    from paddle_tpu.framework.supervisor import (RecoveryPolicy,
                                                 TrainingSupervisor)
    from paddle_tpu.optimizer import AdamW

    assert len(jax.devices()) == args.devices, \
        f"expected {args.devices} simulated devices, got {len(jax.devices())}"
    root = os.path.join(args.workdir, "ckpt")
    # topology-agnostic bootstrap: the recorded mesh reshaped onto the
    # live devices; a fresh start falls back to dp x mp2 over whatever
    # capacity exists. First launch, resume, shrink and grow all take
    # this same line.
    mesh = elastic_mesh.reshaped_mesh(root, default_axes={"dp": -1, "mp": 2})
    per_replica = elastic_mesh.rescale_batch(ELASTIC_BATCH, dict(mesh.shape))

    pt.seed(args.seed)
    model = nn.Sequential(
        ColumnParallelLinear(ELASTIC_DIM, 4 * ELASTIC_DIM,
                             gather_output=False),
        nn.ReLU(),
        RowParallelLinear(4 * ELASTIC_DIM, ELASTIC_DIM,
                          input_is_parallel=True))
    step = DistributedTrainStep(
        model, AdamW(learning_rate=1e-2),
        loss_fn=lambda out, b: F.mse_loss(out, b[1]))

    rng = np.random.default_rng(args.seed)
    w_true = rng.standard_normal(
        (ELASTIC_DIM, ELASTIC_DIM)).astype(np.float32)

    def batch_at(i: int):
        r = np.random.default_rng(args.seed * 100003 + i)
        x = r.standard_normal((ELASTIC_BATCH, ELASTIC_DIM)).astype(np.float32)
        return x, x @ w_true

    policy = RecoveryPolicy(checkpoint_dir=root, save_interval_steps=4,
                            keep_max=4, async_save=False, preemption=False)
    sup = TrainingSupervisor(step, policy)
    losses = []
    with sup:
        sup.restore()
        start = int(step._count)
        # crash-surviving record of this incarnation (a killed child
        # cannot write its result file)
        with open(os.path.join(args.workdir, "incarnations.jsonl"),
                  "a") as f:
            f.write(json.dumps({
                "pid": os.getpid(), "devices": args.devices,
                "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
                "start_step": start, "per_replica_batch": per_replica,
                "restore": last_load_stats()}) + "\n")
            f.flush()
            os.fsync(f.fileno())
        print(f"[elastic-child] devices={args.devices} "
              f"mesh={dict(mesh.shape)} per_replica_batch={per_replica} "
              f"start_step={start}", flush=True)
        for i in range(start, args.total_steps):
            losses.append(float(np.asarray(step(batch_at(i)))))
            sup.maybe_save()
    result = {
        # mean over the final plateau steps: every run (baseline or
        # shrink/grow sequence) computes these on the SAME batches
        "final_eval_loss": float(np.mean(losses[-4:])),
        "start_step": start,
        "end_step": int(step._count),
        "mesh": {k: int(v) for k, v in dict(mesh.shape).items()},
    }
    out = os.path.join(args.workdir, "result.json")
    with open(out + ".tmp", "w") as f:
        json.dump(result, f, indent=1)
    os.replace(out + ".tmp", out)
    print(json.dumps(result))
    return 0


# ------------------------------------------------------------------- harness
def _fault_plan(seed: int) -> FaultPlan:
    return FaultPlan([
        # two CONSECUTIVE NaN batches -> skip_step escalates to rollback
        {"site": "train.data", "kind": "drop", "times": 2, "after": 5},
        # one stall past step_timeout=0.5 -> hang watchdog detection
        {"site": "train.step", "kind": "delay", "delay": 1.2, "after": 9,
         "times": 1},
        # SIGKILL-hard death at the 3rd checkpoint attempt
        {"site": "train.ckpt", "kind": "crash", "times": 1, "after": 2},
    ], seed=seed)


def _spawn(workdir: str, args, plan: FaultPlan | None):
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if plan is not None:
        env["PT_FAULT_PLAN"] = plan.to_json()
    else:
        env.pop("PT_FAULT_PLAN", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--workdir", workdir, "--seed", str(args.seed)]
    if args.quick:
        cmd.append("--quick")
    return subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True, timeout=900)


def _kill_plan(seed: int) -> FaultPlan:
    """Die cold (as hard as SIGKILL) at the 3rd checkpoint attempt — no
    preemption notice, no final snapshot: the restore must fall back to
    the last PUBLISHED checkpoint."""
    return FaultPlan([{"site": "train.ckpt", "kind": "crash", "times": 1,
                       "after": 2}], seed=seed)


def _spawn_elastic(workdir: str, args, devices: int, plan: FaultPlan | None):
    env = dict(os.environ, PYTHONPATH=REPO)
    # forced (not setdefault): the scenario IS a simulated N-device CPU
    # mesh, and the device-count flag only applies to the host platform
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count={devices}"
                        ).strip()
    if plan is not None:
        env["PT_FAULT_PLAN"] = plan.to_json()
    else:
        env.pop("PT_FAULT_PLAN", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--elastic-child",
           "--workdir", workdir, "--seed", str(args.seed),
           "--devices", str(devices), "--total-steps",
           str(args.total_steps)]
    if args.quick:
        cmd.append("--quick")
    return subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True, timeout=900)


def _incarnations(workdir: str) -> list:
    path = os.path.join(workdir, "incarnations.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def run_elastic(args) -> int:
    """The shrink/grow-on-preemption proof (see module docstring)."""
    failures = []
    with tempfile.TemporaryDirectory(prefix="chaos_elastic_") as root:
        base_dir = os.path.join(root, "baseline")
        el_dir = os.path.join(root, "elastic")
        os.makedirs(base_dir)
        os.makedirs(el_dir)

        print("[chaos_soak] elastic baseline (8 devices, uninterrupted)...",
              flush=True)
        p = _spawn_elastic(base_dir, args, 8, plan=None)
        if p.returncode != 0:
            print(p.stdout[-2000:])
            print("[chaos_soak] FAIL: elastic baseline failed")
            return 1
        baseline = json.load(open(os.path.join(base_dir, "result.json")))
        print(f"[chaos_soak] baseline loss "
              f"{baseline['final_eval_loss']:.5f} "
              f"mesh={baseline['mesh']}", flush=True)

        print("[chaos_soak] elastic #1 (8 devices, killed mid-run)...",
              flush=True)
        p1 = _spawn_elastic(el_dir, args, 8, plan=_kill_plan(args.seed))
        if p1.returncode != CRASH_EXIT:
            failures.append(f"elastic #1: expected CRASH_EXIT {CRASH_EXIT},"
                            f" got {p1.returncode}: {p1.stdout[-500:]}")

        print("[chaos_soak] elastic #2 (shrink: 4 devices survive)...",
              flush=True)
        p2 = _spawn_elastic(el_dir, args, 4, plan=_kill_plan(args.seed))
        if p2.returncode != CRASH_EXIT:
            failures.append(f"elastic #2: expected CRASH_EXIT {CRASH_EXIT},"
                            f" got {p2.returncode}: {p2.stdout[-500:]}")
        if "elastic reshard" not in p2.stdout:
            failures.append("elastic #2: no 'elastic reshard' logged — the "
                            "shrunk incarnation did not reshard-restore")

        print("[chaos_soak] elastic #3 (grow: back to 8 devices)...",
              flush=True)
        p3 = _spawn_elastic(el_dir, args, 8, plan=None)
        if p3.returncode != 0:
            failures.append(f"elastic #3: grow run failed "
                            f"rc={p3.returncode}: {p3.stdout[-800:]}")
        elif "elastic reshard" not in p3.stdout:
            failures.append("elastic #3: no 'elastic reshard' logged — the "
                            "regrown incarnation did not reshard-restore")

        incs = _incarnations(el_dir)
        if len(incs) == 3:
            shrunk_dp = incs[1]["mesh"].get("dp")
            shrunk_mp = incs[1]["mesh"].get("mp")
            # a missing axis key is itself the anomaly — record it, don't
            # TypeError out of the gate harness
            if (shrunk_dp is None or shrunk_mp is None
                    or shrunk_dp * shrunk_mp != 4):
                failures.append(
                    f"elastic #2 did not shrink to 4 devices: "
                    f"mesh={incs[1]['mesh']}")
            if incs[1]["mesh"].get("mp") != incs[0]["mesh"].get("mp"):
                failures.append("elastic resize changed the frozen mp axis")
            # progress must carry ACROSS topologies: each incarnation
            # resumes from checkpoints the previous one published
            if not (0 < incs[1]["start_step"] <= incs[2]["start_step"]):
                failures.append(
                    f"no cross-topology progress: start steps "
                    f"{[i['start_step'] for i in incs]}")
        else:
            failures.append(f"expected 3 elastic incarnations, saw "
                            f"{len(incs)}")

        result_path = os.path.join(el_dir, "result.json")
        if os.path.exists(result_path):
            final = json.load(open(result_path))
            base_loss = baseline["final_eval_loss"]
            rel = abs(final["final_eval_loss"] - base_loss) / abs(base_loss)
            print(f"[chaos_soak] elastic loss {final['final_eval_loss']:.5f}"
                  f" vs baseline {base_loss:.5f} (rel diff {rel * 100:.2f}%,"
                  f" tol {args.tol * 100:.0f}%)", flush=True)
            # NaN (e.g. an incarnation that resumed at/past total_steps and
            # trained zero steps) must fail CLOSED: `NaN > tol` is False
            if not math.isfinite(rel) or rel > args.tol:
                failures.append(
                    f"final loss diverged across shrink/grow: "
                    f"{final['final_eval_loss']} vs {base_loss} "
                    f"(rel {rel:.4f} > tol {args.tol})")
        elif not failures:
            failures.append("elastic #3: no result.json")

    if failures:
        print("[chaos_soak] FAIL (elastic)")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("[chaos_soak] PASS (elastic): trained through kill -> shrink to "
          "4 devices -> regrow to 8 with loss parity")
    return 0


def _events(workdir: str) -> list:
    path = os.path.join(workdir, "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized soak (fewer steps)")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--tol", type=float, default=0.01,
                    help="relative final-loss tolerance vs the clean run")
    ap.add_argument("--elastic", action="store_true",
                    help="shrink/grow-on-preemption scenario")
    ap.add_argument("--child", action="store_true", help="internal")
    ap.add_argument("--elastic-child", action="store_true", help="internal")
    ap.add_argument("--workdir", default=None, help="internal")
    ap.add_argument("--devices", type=int, default=8, help="internal")
    ap.add_argument("--total-steps", type=int, default=None,
                    help="elastic scenario optimizer-step budget")
    args = ap.parse_args()
    if args.total_steps is None:
        args.total_steps = 24 if args.quick else 48
    if args.child:
        return run_child(args)
    if args.elastic_child:
        return run_elastic_child(args)
    if args.elastic:
        return run_elastic(args)

    failures = []
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as root:
        base_dir = os.path.join(root, "baseline")
        chaos_dir = os.path.join(root, "chaos")
        os.makedirs(base_dir)
        os.makedirs(chaos_dir)
        ns = argparse.Namespace(**vars(args))

        print("[chaos_soak] baseline run...", flush=True)
        p = _spawn(base_dir, ns, plan=None)
        if p.returncode != 0:
            print(p.stdout[-2000:])
            print("[chaos_soak] FAIL: baseline run failed")
            return 1
        baseline = json.load(open(os.path.join(base_dir, "result.json")))
        print(f"[chaos_soak] baseline eval loss "
              f"{baseline['final_eval_loss']:.4f} "
              f"({baseline['elapsed_s']}s)", flush=True)

        print("[chaos_soak] chaos run #1 (NaN x2, stall x1, kill x1)...",
              flush=True)
        p1 = _spawn(chaos_dir, ns, plan=_fault_plan(args.seed))
        if p1.returncode != CRASH_EXIT:
            failures.append(
                f"chaos #1: expected CRASH_EXIT {CRASH_EXIT}, got "
                f"{p1.returncode}: {p1.stdout[-500:]}")
        events = _events(chaos_dir)
        kinds = {e["event"] for e in events}
        for want in ("anomaly", "rollback", "hang"):
            if want not in kinds:
                failures.append(f"chaos #1: no {want!r} event logged "
                                f"(got {sorted(kinds)})")

        print("[chaos_soak] chaos run #2 (clean restart, resume)...",
              flush=True)
        p2 = _spawn(chaos_dir, ns, plan=None)
        if p2.returncode != 0:
            failures.append(f"chaos #2: restart failed rc={p2.returncode}: "
                            f"{p2.stdout[-500:]}")
        result_path = os.path.join(chaos_dir, "result.json")
        chaos = None
        if os.path.exists(result_path):
            chaos = json.load(open(result_path))
        elif not failures:
            failures.append("chaos #2: no result.json")

        if chaos is not None:
            base_loss = baseline["final_eval_loss"]
            rel = abs(chaos["final_eval_loss"] - base_loss) / abs(base_loss)
            print(f"[chaos_soak] chaos eval loss "
                  f"{chaos['final_eval_loss']:.4f} vs baseline "
                  f"{base_loss:.4f} (rel diff {rel * 100:.2f}%, "
                  f"tol {args.tol * 100:.0f}%)", flush=True)
            if rel > args.tol:
                failures.append(
                    f"final eval loss diverged: {chaos['final_eval_loss']}"
                    f" vs {base_loss} (rel {rel:.4f} > tol {args.tol})")
            # one specialization of the checked step per incarnation; the
            # in-run guard already failed the child on mid-run retraces
            if chaos["step_compiles"] > 2:
                failures.append(
                    f"steady-state recompiles: {chaos['step_compiles']} "
                    f"train-step compiles in the resumed run")

    if failures:
        print("[chaos_soak] FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("[chaos_soak] PASS: recovered from NaN/stall/kill to within "
          "tolerance, no steady-state recompiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
