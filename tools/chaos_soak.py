#!/usr/bin/env python
"""Chaos soak: a short GPT pretrain under injected NaN batches, step
stalls, and a mid-training SIGKILL — asserting the self-healing layer
(``Model.fit(recovery=...)``, ``framework/supervisor.py``) recovers to the
SAME answer as an undisturbed run.

Three child runs (each a fresh interpreter, like ``tools/fault_sweep.py``):

1. **baseline** — no faults; records the final eval loss.
2. **chaos #1** — a seeded FaultPlan poisons 2 consecutive batches with NaN
   (``drop`` @ ``train.data`` → the step's NaN seam), stalls one step past
   the hang watchdog's ``step_timeout`` (``delay`` @ ``train.step``), and
   kills the process cold at the 3rd checkpoint attempt (``crash`` @
   ``train.ckpt``, as hard as SIGKILL). The run must die with CRASH_EXIT
   after logging >=1 anomaly, >=1 rollback and >=1 hang detection to its
   event log.
3. **chaos #2** — a clean restart against the same checkpoint root resumes
   from the last published snapshot + data cursor and runs to completion.

Pass criteria (exit 0 iff all hold):

- chaos final eval loss within ``--tol`` (default 1%) of the baseline;
- every injected fault observed (anomaly/rollback/hang events + the kill);
- no steady-state recompiles: each child enters ``retrace_guard(0)`` after
  warmup, so a rollback/replay or resume that retraced the step would have
  failed the child outright.

Usage::

    python tools/chaos_soak.py            # full soak
    python tools/chaos_soak.py --quick    # CI-sized (robustness_gate)
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from paddle_tpu.distributed.resilience import CRASH_EXIT, FaultPlan  # noqa: E402

SEQ = 32
BATCH = 4


def _config(quick: bool):
    """(docs, epochs): enough steps to reach the random-token plateau, so
    the 1% tolerance compares converged runs, not transients."""
    return (64, 2) if quick else (64, 4)


# --------------------------------------------------------------------- child
def run_child(args) -> int:
    import numpy as np

    import paddle_tpu as pt
    from paddle_tpu import profiler
    from paddle_tpu.framework import compile_cache
    from paddle_tpu.framework.supervisor import RecoveryPolicy
    from paddle_tpu.hapi import Model
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.optimizer import AdamW

    n_docs, epochs = _config(args.quick)
    pt.seed(args.seed)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2, num_heads=2,
                    max_position_embeddings=SEQ, hidden_dropout_prob=0.0,
                    attention_dropout_prob=0.0, use_flash_attention=False)
    model = Model(GPTForCausalLM(cfg), labels=[])  # forward(ids, labels)->loss
    model.prepare(AdamW(learning_rate=1e-3))

    rng = np.random.default_rng(args.seed)
    ids = rng.integers(0, cfg.vocab_size, (n_docs, SEQ)).astype(np.int32)
    train = pt.io.TensorDataset([ids, ids])
    eval_rng = np.random.default_rng(args.seed + 1)
    eval_ids = eval_rng.integers(0, cfg.vocab_size,
                                 (4, BATCH, SEQ)).astype(np.int32)

    events_path = os.path.join(args.workdir, "events.jsonl")

    class EventLog(pt.hapi.Callback):
        """Crash-surviving record of what the supervisor observed (the
        killed incarnation cannot write a result file)."""

        def __init__(self):
            super().__init__()
            self._fh = open(events_path, "a")
            self._hangs = 0

        def _emit(self, event, **kw):
            self._fh.write(json.dumps({"event": event, "pid": os.getpid(),
                                       **kw}) + "\n")
            self._fh.flush()
            os.fsync(self._fh.fileno())

        def on_train_anomaly(self, logs=None):
            self._emit("anomaly", **(logs or {}))

        def on_rollback(self, logs=None):
            info = dict(logs or {})
            info.pop("cursor", None)  # not JSON-serializable
            self._emit("rollback", **info)

        def on_preemption(self, logs=None):
            self._emit("preemption", **(logs or {}))

        def on_train_batch_end(self, step, logs=None):
            hangs = profiler.counter_values().get("train.hang", 0)
            if hangs > self._hangs:
                self._emit("hang", count=hangs)
                self._hangs = hangs

    class GuardAfterWarmup(pt.hapi.Callback):
        """retrace_guard(0) once the step program is traced: any recompile
        caused by rollback/replay/resume fails the child loudly."""

        def __init__(self, warmup=3):
            super().__init__()
            self.warmup = warmup
            self._cm = None

        def on_train_batch_end(self, step, logs=None):
            if self._cm is None and step + 1 >= self.warmup:
                self._cm = compile_cache.retrace_guard(
                    0, label="chaos-steady")
                self._cm.__enter__()

        def release(self):
            if self._cm is not None:
                self._cm.__exit__(None, None, None)
                self._cm = None

    guard = GuardAfterWarmup()
    policy = RecoveryPolicy(
        checkpoint_dir=os.path.join(args.workdir, "ckpt"),
        save_interval_steps=5, check_interval=2, max_consecutive=2,
        skip_window=2, step_timeout=0.5, hang_action="warn",
        preemption=True, grace_seconds=20.0, async_save=False)
    import warnings

    t0 = time.monotonic()
    try:
        with warnings.catch_warnings():
            warnings.simplefilter("ignore", RuntimeWarning)
            hist = model.fit(train, batch_size=BATCH, epochs=epochs,
                             shuffle=False, verbose=0,
                             callbacks=[EventLog(), guard],
                             recovery=policy)
    finally:
        guard.release()   # EvalStep below compiles legitimately

    eval_losses = [float(np.asarray(model.predict_batch((b, b))))
                   for b in eval_ids]
    step = model._train_step
    result = {
        "final_eval_loss": float(np.mean(eval_losses)),
        "train_loss": float(hist["loss"][-1]),
        "step_compiles": step.cache_stats()["compiles"],
        "counters": profiler.counter_values(),
        "elapsed_s": round(time.monotonic() - t0, 1),
    }
    out = os.path.join(args.workdir, "result.json")
    with open(out + ".tmp", "w") as f:
        json.dump(result, f, indent=1)
    os.replace(out + ".tmp", out)
    print(json.dumps(result))
    return 0


# ------------------------------------------------------------------- harness
def _fault_plan(seed: int) -> FaultPlan:
    return FaultPlan([
        # two CONSECUTIVE NaN batches -> skip_step escalates to rollback
        {"site": "train.data", "kind": "drop", "times": 2, "after": 5},
        # one stall past step_timeout=0.5 -> hang watchdog detection
        {"site": "train.step", "kind": "delay", "delay": 1.2, "after": 9,
         "times": 1},
        # SIGKILL-hard death at the 3rd checkpoint attempt
        {"site": "train.ckpt", "kind": "crash", "times": 1, "after": 2},
    ], seed=seed)


def _spawn(workdir: str, args, plan: FaultPlan | None):
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if plan is not None:
        env["PT_FAULT_PLAN"] = plan.to_json()
    else:
        env.pop("PT_FAULT_PLAN", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--workdir", workdir, "--seed", str(args.seed)]
    if args.quick:
        cmd.append("--quick")
    return subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True, timeout=900)


def _events(workdir: str) -> list:
    path = os.path.join(workdir, "events.jsonl")
    if not os.path.exists(path):
        return []
    with open(path) as f:
        return [json.loads(line) for line in f if line.strip()]


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="CI-sized soak (fewer steps)")
    ap.add_argument("--seed", type=int, default=1234)
    ap.add_argument("--tol", type=float, default=0.01,
                    help="relative final-loss tolerance vs the clean run")
    ap.add_argument("--child", action="store_true", help="internal")
    ap.add_argument("--workdir", default=None, help="internal")
    args = ap.parse_args()
    if args.child:
        return run_child(args)

    failures = []
    with tempfile.TemporaryDirectory(prefix="chaos_soak_") as root:
        base_dir = os.path.join(root, "baseline")
        chaos_dir = os.path.join(root, "chaos")
        os.makedirs(base_dir)
        os.makedirs(chaos_dir)
        ns = argparse.Namespace(**vars(args))

        print("[chaos_soak] baseline run...", flush=True)
        p = _spawn(base_dir, ns, plan=None)
        if p.returncode != 0:
            print(p.stdout[-2000:])
            print("[chaos_soak] FAIL: baseline run failed")
            return 1
        baseline = json.load(open(os.path.join(base_dir, "result.json")))
        print(f"[chaos_soak] baseline eval loss "
              f"{baseline['final_eval_loss']:.4f} "
              f"({baseline['elapsed_s']}s)", flush=True)

        print("[chaos_soak] chaos run #1 (NaN x2, stall x1, kill x1)...",
              flush=True)
        p1 = _spawn(chaos_dir, ns, plan=_fault_plan(args.seed))
        if p1.returncode != CRASH_EXIT:
            failures.append(
                f"chaos #1: expected CRASH_EXIT {CRASH_EXIT}, got "
                f"{p1.returncode}: {p1.stdout[-500:]}")
        events = _events(chaos_dir)
        kinds = {e["event"] for e in events}
        for want in ("anomaly", "rollback", "hang"):
            if want not in kinds:
                failures.append(f"chaos #1: no {want!r} event logged "
                                f"(got {sorted(kinds)})")

        print("[chaos_soak] chaos run #2 (clean restart, resume)...",
              flush=True)
        p2 = _spawn(chaos_dir, ns, plan=None)
        if p2.returncode != 0:
            failures.append(f"chaos #2: restart failed rc={p2.returncode}: "
                            f"{p2.stdout[-500:]}")
        result_path = os.path.join(chaos_dir, "result.json")
        chaos = None
        if os.path.exists(result_path):
            chaos = json.load(open(result_path))
        elif not failures:
            failures.append("chaos #2: no result.json")

        if chaos is not None:
            base_loss = baseline["final_eval_loss"]
            rel = abs(chaos["final_eval_loss"] - base_loss) / abs(base_loss)
            print(f"[chaos_soak] chaos eval loss "
                  f"{chaos['final_eval_loss']:.4f} vs baseline "
                  f"{base_loss:.4f} (rel diff {rel * 100:.2f}%, "
                  f"tol {args.tol * 100:.0f}%)", flush=True)
            if rel > args.tol:
                failures.append(
                    f"final eval loss diverged: {chaos['final_eval_loss']}"
                    f" vs {base_loss} (rel {rel:.4f} > tol {args.tol})")
            # one specialization of the checked step per incarnation; the
            # in-run guard already failed the child on mid-run retraces
            if chaos["step_compiles"] > 2:
                failures.append(
                    f"steady-state recompiles: {chaos['step_compiles']} "
                    f"train-step compiles in the resumed run")

    if failures:
        print("[chaos_soak] FAIL")
        for f in failures:
            print(f"  - {f}")
        return 1
    print("[chaos_soak] PASS: recovered from NaN/stall/kill to within "
          "tolerance, no steady-state recompiles")
    return 0


if __name__ == "__main__":
    sys.exit(main())
