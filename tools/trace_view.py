#!/usr/bin/env python
"""Merge flight-recorder / trace dumps into one chrome://tracing JSON,
keyed by correlation id.

A fleet request's telemetry is scattered: the router's span buffer in
one process, each replica's spans (and crash-time flight dumps) in
others. This CLI reads any mix of

- flight-recorder dumps (``{"format": "flight_recorder", "spans": [...],
  "events": [...]}`` — what ``observability.flight.dump()`` writes),
- raw span lists (``[{"name", "corr", "t0", "t1", "tags"}, ...]`` — what
  ``observability.tracing.spans()`` serializes to),
- chrome traces (``{"traceEvents": [...]}`` — what
  ``export_chrome_trace`` writes),

and merges every span into ONE chrome trace where each correlation id is
a single named lane, regardless of which process recorded which piece.
Wall-clock timestamps make the cross-process merge line up.

Flight dumps are hostname-prefixed (``flight_<host>_<pid>_...``), so
many hosts can share one dump dir (NFS); ``--list`` groups its summary
by recording host when more than one contributed.

    python tools/trace_view.py flight_records/*.json -o merged.json
    python tools/trace_view.py --list flight_records/*.json
    python tools/trace_view.py --corr req-1f03ab-000004 dumps/*.json \\
        -o one_request.json

Exit codes: 0 ok; 2 no spans found / unreadable input.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional, Tuple


def _spans_from_chrome(obj: dict, label: str) -> List[dict]:
    out = []
    for ev in obj.get("traceEvents", []):
        if ev.get("ph") not in ("X", "i"):
            continue
        t0 = float(ev.get("ts", 0.0)) / 1e6
        t1 = t0 + float(ev.get("dur", 0.0)) / 1e6
        args = dict(ev.get("args") or {})
        corr = args.pop("correlation_id", None)
        out.append({"name": ev.get("name", "?"), "corr": corr,
                    "t0": t0, "t1": t1, "tags": args, "src": label})
    return out


def _events_as_spans(events: List[dict], label: str) -> List[dict]:
    """Flight-recorder ring events become instant spans so a dump's
    engine_reset/compile markers land on the merged timeline too."""
    out = []
    for ev in events:
        if not isinstance(ev, dict) or "t" not in ev:
            continue
        tags = {k: v for k, v in ev.items()
                if k not in ("t", "kind", "corr")
                and isinstance(v, (str, int, float, bool))}
        out.append({"name": f"event:{ev.get('kind', '?')}",
                    "corr": ev.get("corr"), "t0": float(ev["t"]),
                    "t1": float(ev["t"]), "tags": tags, "src": label})
    return out


def load_spans(path: str) -> Tuple[List[dict], str]:
    """(spans, kind) from one input file; raises on unreadable input."""
    with open(path) as f:
        obj = json.load(f)
    label = os.path.basename(path)
    if isinstance(obj, dict) and obj.get("format") == "flight_recorder":
        label = f"{label}:pid{obj.get('pid', '?')}"
        host = obj.get("host")
        spans = []
        for rec in obj.get("spans", []):
            rec = dict(rec)
            rec["src"] = label
            if host:
                rec.setdefault("host", host)
            spans.append(rec)
        for rec in _events_as_spans(obj.get("events", []), label):
            if host:
                rec.setdefault("host", host)
            spans.append(rec)
        return spans, "flight"
    if isinstance(obj, dict) and "traceEvents" in obj:
        return _spans_from_chrome(obj, label), "chrome"
    if isinstance(obj, list):
        out = []
        for rec in obj:
            if isinstance(rec, dict) and "t0" in rec and "t1" in rec:
                rec = dict(rec)
                rec["src"] = label
                out.append(rec)
        return out, "spans"
    raise ValueError(f"{path}: not a flight dump, span list, or "
                     f"chrome trace")


def _migrated_corrs(spans: List[dict]) -> set:
    """Correlation ids whose KV blocks moved between replicas: the
    prefill side records ``kv_migrate:send``, the decode side
    ``kv_migrate:recv``, under the SAME corr id — seeing both halves
    (usually from different hosts' dumps) marks the request migrated."""
    sends, recvs = set(), set()
    for s in spans:
        c = s.get("corr")
        if c is None:
            continue
        if s.get("name") == "kv_migrate:send":
            sends.add(c)
        elif s.get("name") == "kv_migrate:recv":
            recvs.add(c)
    return sends & recvs


def merge_chrome(spans: List[dict], corr: Optional[str] = None) -> dict:
    """One merged chrome trace: pid 1 = the merged view, one tid lane
    per correlation id (sorted by first-span time so lanes read in
    arrival order), lane 0 for uncorrelated spans. A migrated request
    (kv_migrate:send + recv under one corr) keeps a SINGLE lane even
    though its halves were recorded on different hosts — the lane name
    carries a ``[migrated]`` marker."""
    spans = [s for s in spans
             if corr is None or (s.get("corr") or "").find(corr) >= 0]
    first_seen = {}
    for s in sorted(spans, key=lambda s: s["t0"]):
        c = s.get("corr")
        if c is not None and c not in first_seen:
            first_seen[c] = s["t0"]
    lanes = {c: i + 1 for i, c in enumerate(
        sorted(first_seen, key=first_seen.get))}
    events = [{"ph": "M", "name": "process_name", "pid": 1, "tid": 0,
               "args": {"name": "merged fleet trace"}},
              {"ph": "M", "name": "thread_name", "pid": 1, "tid": 0,
               "args": {"name": "untraced"}}]
    migrated = _migrated_corrs(spans)
    for c, tid in lanes.items():
        lane_name = f"{c} [migrated]" if c in migrated else c
        events.append({"ph": "M", "name": "thread_name", "pid": 1,
                       "tid": tid, "args": {"name": lane_name}})
        events.append({"ph": "M", "name": "thread_sort_index", "pid": 1,
                       "tid": tid, "args": {"sort_index": tid}})
    for s in spans:
        tid = lanes.get(s.get("corr"), 0)
        args = dict(s.get("tags") or {})
        if s.get("corr") is not None:
            args["correlation_id"] = s["corr"]
        if s.get("src"):
            args["source"] = s["src"]
        if s.get("host"):
            args["host"] = s["host"]
        t0, t1 = float(s["t0"]), float(s["t1"])
        ev = {"name": s.get("name", "?"), "pid": 1, "tid": tid,
              "ts": t0 * 1e6, "args": args}
        if t1 > t0:
            ev.update(ph="X", dur=(t1 - t0) * 1e6)
        else:
            ev.update(ph="i", s="t")
        events.append(ev)
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def list_correlations(spans: List[dict]) -> List[dict]:
    migrated = _migrated_corrs(spans)
    by_corr = {}
    for s in spans:
        c = s.get("corr")
        if c is None:
            continue
        e = by_corr.setdefault(c, {"corr": c, "spans": 0,
                                   "t0": s["t0"], "t1": s["t1"],
                                   "names": [], "sources": set(),
                                   "hosts": set()})
        e["spans"] += 1
        e["t0"] = min(e["t0"], s["t0"])
        e["t1"] = max(e["t1"], s["t1"])
        if s.get("name") not in e["names"]:
            e["names"].append(s.get("name"))
        if s.get("src"):
            e["sources"].add(s["src"])
        if s.get("host"):
            e["hosts"].add(s["host"])
    out = []
    for e in sorted(by_corr.values(), key=lambda e: e["t0"]):
        e["duration_ms"] = round((e["t1"] - e["t0"]) * 1e3, 3)
        e["sources"] = sorted(e["sources"])
        e["hosts"] = sorted(e["hosts"])
        e["migrated"] = e["corr"] in migrated
        out.append(e)
    return out


def group_by_host(spans: List[dict]) -> dict:
    """``{host: sorted source labels}`` — dumps from many hosts sharing
    one flight dir (NFS) group under their recording host; spans with
    no host annotation book under ``"local"``."""
    by_host: dict = {}
    for s in spans:
        h = s.get("host") or "local"
        by_host.setdefault(h, set()).add(s.get("src") or "?")
    return {h: sorted(srcs) for h, srcs in sorted(by_host.items())}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("inputs", nargs="+",
                    help="flight dumps / span lists / chrome traces")
    ap.add_argument("-o", "--output", default=None,
                    help="merged chrome-trace JSON path")
    ap.add_argument("--corr", default=None,
                    help="keep only correlation ids containing this "
                         "substring")
    ap.add_argument("--list", action="store_true",
                    help="print one line per correlation id instead of "
                         "writing a trace")
    args = ap.parse_args(argv)

    spans: List[dict] = []
    for path in args.inputs:
        try:
            got, kind = load_spans(path)
        except Exception as e:
            print(f"trace_view: {path}: {type(e).__name__}: {e}",
                  file=sys.stderr)
            return 2
        print(f"[trace_view] {path}: {len(got)} span(s) ({kind})",
              file=sys.stderr)
        spans.extend(got)
    if not spans:
        print("trace_view: no spans in any input", file=sys.stderr)
        return 2

    if args.list:
        groups = group_by_host(spans)
        if len(groups) > 1:
            # multi-host flight dir (hostname-prefixed dumps): lead with
            # a per-host roll-up so an operator sees which machines
            # contributed; '#' lines keep per-corr output line-JSON
            for host, sources in groups.items():
                print(f"# host {host}: {len(sources)} source(s): "
                      f"{', '.join(sources)}")
        for e in list_correlations(spans):
            if args.corr and args.corr not in e["corr"]:
                continue
            print(json.dumps(e))
        return 0

    trace = merge_chrome(spans, corr=args.corr)
    n = sum(1 for ev in trace["traceEvents"] if ev["ph"] in ("X", "i"))
    if args.output:
        with open(args.output, "w") as f:
            json.dump(trace, f)
        print(f"[trace_view] wrote {args.output}: {n} event(s), "
              f"{len({e['tid'] for e in trace['traceEvents']}) - 1} "
              f"lane(s) — open in chrome://tracing", file=sys.stderr)
    else:
        print(json.dumps(trace))
    return 0 if n else 2


if __name__ == "__main__":
    sys.exit(main())
