"""Latency-percentile load bench for the serving stack — solo or fleet.

Open-loop Poisson load (arrivals don't wait for completions — the honest
way to measure a server: closed-loop generators self-throttle and hide
queueing collapse) against ``paddle_tpu.serving``, reporting the serving
numbers that matter and the compile discipline. Prints ONE JSON line:

    {"metric": "gpt_serve_requests_per_sec", "value": N, "unit": "req/s",
     "extra": {"goodput": ..., "ttft_p50_ms": ..., "ttft_p99_ms": ...,
               "inter_token_p50_ms": ..., "inter_token_p99_ms": ...,
               "tokens_per_sec": ..., "slot_occupancy": ...,
               "cache_hit_rate": ..., "steady_state_recompiles": ...}}

Defaults reproduce the PR 4 single-replica bench byte-for-byte (the
``gpt_serve_requests_per_sec`` breadth metric ``bench.py`` probes).
Fleet knobs:

- ``--replicas N`` puts a load-aware ``ReplicaRouter`` in front of N
  ``InferenceServer`` replicas (prefix-affinity + occupancy placement);
- ``--prefix-cache-mb M`` attaches a paged prefix/KV block pool to every
  replica (``--block-tokens`` sets the page size);
- ``--prefix-tokens P`` switches the trace generator prefix-heavy: a
  ``--prefix-frac`` share of requests open with the SAME P-token system
  prefix (the millions-of-users shape), the rest stay uniform random;
- ``--crash-replica`` hard-kills one replica mid-window (no drain) —
  the router must requeue its requests onto survivors with no recompile
  and, for the ``--verify K`` seeded-greedy probes, no token divergence
  vs a solo ``generate`` (the fleet robustness gate).

Multi-tenant LoRA knobs:

- ``--adapters N`` registers N synthetic tenants (rank ``--adapter-rank``
  LoRA adapters on the attention+MLP projections) in a per-replica
  ``AdapterStore``; an ``--adapter-frac`` share of requests carries a
  tenant id drawn Zipf-style (skewed popularity — the realistic shape);
- ``--max-loaded`` caps device-resident adapters per replica (default:
  all N), so a smaller value exercises LRU load/evict churn under load —
  which must stay recompile-free;
- ``--verify`` probes with a tenant id are checked token-exact against a
  solo ``generate`` with that adapter's weights loaded.

The JSON gains a ``per_adapter`` block (offered/completed/tokens/TTFT
p50 per tenant) plus registry load/evict totals, and an ``slo_report``
block: per-tenant availability + multi-window burn rates over the
measured window against the ``--slo-ttft`` / ``--slo-availability``
targets (``observability.slo``).

Warmup touches every prefill bucket on every replica first; the
measured window must then hold at ``#buckets + 1`` programs per replica
— ANY steady-state recompile exits non-zero (the serving analogue of
``tools/retrace_report.py``), as does a verify mismatch or an
unrecovered crash casualty.

    python tools/serve_bench.py                  # CPU-safe tiny config
    python tools/serve_bench.py --check          # quick CI/bench probe
    python tools/serve_bench.py --preset serving --slots 8 --rate 4
    python tools/serve_bench.py --replicas 2 --prefix-cache-mb 8 \\
        --prefix-tokens 24 --crash-replica --verify 3
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _pct(values, p):
    if not values:
        return 0.0
    return float(np.percentile(np.asarray(values, np.float64), p))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=("gpt", "llama"), default="gpt")
    ap.add_argument("--preset", choices=("tiny", "small", "serving"), default="tiny")
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--rate", type=float, default=2.0,
                    help="offered load, requests/s (Poisson arrivals)")
    ap.add_argument("--requests", type=int, default=16,
                    help="measured requests after warmup")
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--buckets", type=int, nargs="+", default=(16, 32))
    ap.add_argument("--max-queue-depth", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-request completion wait cap (s)")
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request queue-wait SLO (s): requests that "
                         "cannot start in time expire and count against "
                         "goodput — the number queueing collapse "
                         "actually destroys")
    ap.add_argument("--check", action="store_true",
                    help="small fixed workload for CI / bench.py probing")
    # ---- fleet knobs ----
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--prefix-cache-mb", type=float, default=0.0,
                    help="per-replica paged KV block pool budget (0=off)")
    ap.add_argument("--block-tokens", type=int, default=8,
                    help="prefix-cache page size in tokens")
    ap.add_argument("--prefix-tokens", type=int, default=0,
                    help="shared system-prefix length for the "
                         "prefix-heavy trace (0=uniform random trace)")
    ap.add_argument("--prefix-frac", type=float, default=0.9,
                    help="share of requests carrying the shared prefix")
    ap.add_argument("--affinity-weight", type=float, default=0.75)
    ap.add_argument("--crash-replica", action="store_true",
                    help="hard-kill one replica mid-window (router must "
                         "reroute with no recompiles / no divergence)")
    ap.add_argument("--verify", type=int, default=0,
                    help="seeded-greedy probes checked token-exact "
                         "against a solo generate after the window")
    # ---- multi-tenant LoRA knobs ----
    ap.add_argument("--adapters", type=int, default=0,
                    help="register N synthetic LoRA tenants per replica "
                         "(0 = base-only trace)")
    ap.add_argument("--adapter-frac", type=float, default=0.7,
                    help="share of requests carrying a tenant id "
                         "(Zipf-skewed popularity over --adapters)")
    ap.add_argument("--adapter-rank", type=int, default=4)
    ap.add_argument("--max-loaded", type=int, default=0,
                    help="device-resident adapters per replica (0 = all "
                         "of --adapters; smaller exercises LRU churn)")
    # ---- SLO report knobs ----
    ap.add_argument("--slo-ttft", type=float, default=0.5,
                    help="per-tenant TTFT target (s) for the slo_report "
                         "block (window-mean judged)")
    ap.add_argument("--slo-availability", type=float, default=0.99,
                    help="per-tenant availability target for the "
                         "slo_report burn rates")
    args = ap.parse_args(argv)
    if args.check:
        args.requests = min(args.requests, 8)
        args.rate = min(args.rate, 4.0)
        args.new_tokens = min(args.new_tokens, 10)
    if args.replicas < 1:
        ap.error("--replicas must be >= 1")
    if args.crash_replica and args.replicas < 2:
        ap.error("--crash-replica needs --replicas >= 2 (someone must "
                 "survive)")

    import jax

    from decode_bench import build_model
    from paddle_tpu.framework import compile_cache
    from paddle_tpu.serving import (InferenceServer, LatencyHistogram,
                                    QueueFull, ReplicaRouter)

    model, cfg = build_model(args.model, args.preset)
    prefix_pad = args.prefix_tokens + args.block_tokens
    max_length = min(cfg.max_position_embeddings,
                     max(args.buckets) + args.new_tokens + 8
                     + (prefix_pad if args.prefix_tokens else 0))
    if args.prefix_tokens and (args.prefix_tokens + args.block_tokens
                               + args.new_tokens > max_length):
        ap.error(
            f"--prefix-tokens {args.prefix_tokens} + --block-tokens "
            f"{args.block_tokens} + --new-tokens {args.new_tokens} "
            f"exceeds the model's cache length {max_length} "
            f"(max_position_embeddings={cfg.max_position_embeddings}); "
            f"shrink the prefix or pick a larger preset")
    if args.prefix_tokens and (args.prefix_tokens + args.block_tokens
                               > max(args.buckets)):
        # a cold shared-prefix prompt would overflow the top declared
        # bucket into the ladder — a legitimate warmup compile the
        # #buckets+1 budget check would then (correctly) reject
        ap.error(
            f"--prefix-tokens {args.prefix_tokens} + --block-tokens "
            f"{args.block_tokens} overflows the largest prefill bucket "
            f"{max(args.buckets)}; declare a bucket that fits the cold "
            f"prefix prompt (e.g. --buckets {min(args.buckets)} "
            f"{args.prefix_tokens + args.block_tokens})")
    prefix_cache = (int(args.prefix_cache_mb * (1 << 20))
                    if args.prefix_cache_mb > 0 else None)

    # ---- multi-tenant LoRA: N synthetic adapters, one store per replica
    tenant_names, tenant_trees, stores = [], {}, []
    if args.adapters > 0:
        from paddle_tpu.lora import (AdapterStore, LoraConfig, apply_lora,
                                     lora_state)

        lcfg = LoraConfig(rank=args.adapter_rank, alpha=2.0 * args.adapter_rank)
        apply_lora(model, lcfg)
        zero = lora_state(model)
        arng = np.random.default_rng(args.seed + 777)
        tenant_names = [f"tenant{k}" for k in range(args.adapters)]
        for name in tenant_names:
            tenant_trees[name] = {
                k: arng.normal(0.0, 0.02, v.shape).astype(np.float32)
                for k, v in zero.items()}
        max_loaded = args.max_loaded or args.adapters
        for _ in range(args.replicas):
            store = AdapterStore(model, lcfg, max_loaded=max_loaded)
            for name in tenant_names:
                store.register(name, tenant_trees[name])
            stores.append(store)
        # Zipf-ish popularity: a few hot tenants, a long cool tail
        zipf_w = np.array([1.0 / (k + 1) ** 1.1
                           for k in range(args.adapters)])
        zipf_w /= zipf_w.sum()
    servers = [
        InferenceServer(
            model, slots=args.slots, max_length=max_length,
            prefill_buckets=args.buckets,
            max_queue_depth=args.max_queue_depth,
            prefix_cache=(dict(max_bytes=prefix_cache,
                               block_tokens=args.block_tokens)
                          if prefix_cache else None),
            adapter_store=stores[i] if stores else None)
        for i in range(args.replicas)]
    fleet = args.replicas > 1
    router = None
    if fleet:
        router = ReplicaRouter(affinity_weight=args.affinity_weight)
        names = [router.add_replica(s, f"r{i}")
                 for i, s in enumerate(servers)]
    srv = servers[0]
    rng = np.random.default_rng(args.seed)
    lens = sorted(b - 2 for b in srv.engine.prefill_buckets)

    def prompt(n):
        return rng.integers(0, cfg.vocab_size, (int(n),)).astype(np.int32)

    shared_prefix = (prompt(args.prefix_tokens)
                     if args.prefix_tokens else None)

    def trace_prompt(i):
        """The measured trace: prefix-heavy when --prefix-tokens is
        set, PR 4's uniform-random lengths otherwise."""
        if shared_prefix is not None and rng.random() < args.prefix_frac:
            sfx = prompt(int(rng.integers(2, args.block_tokens + 1)))
            return np.concatenate([shared_prefix, sfx])
        return prompt(int(rng.integers(4, max(lens) + 1)))

    def trace_tenant(i):
        """Per-request tenant id: an --adapter-frac share of requests
        carries one, drawn Zipf-style over the registered adapters."""
        if not tenant_names or rng.random() >= args.adapter_frac:
            return None
        return tenant_names[int(rng.choice(args.adapters, p=zipf_w))]

    # ---- warmup: touch every bucket + the decode program, per replica ----
    t_warm = time.perf_counter()
    for s in servers:
        for L in lens:
            s.submit(prompt(L), max_new_tokens=4).result(
                timeout=args.timeout)
        s.submit(prompt(lens[0]), max_new_tokens=4, do_sample=True,
                 temperature=0.9, top_p=0.9, seed=1).result(
                     timeout=args.timeout)
        if shared_prefix is not None:
            # the suffix bucket a prefix hit lands in must be warm too
            s.submit(np.concatenate([shared_prefix, prompt(4)]),
                     max_new_tokens=4).result(timeout=args.timeout)
    warmup_s = time.perf_counter() - t_warm
    compiles_before = compile_cache.cache_stats()["compiles"]
    for s in servers:
        s.metrics.reset()

    # SLO burn-rate evaluation over the measured window: baseline
    # ingest here, final ingest after the window; the report block
    # rides the JSON (per-tenant availability + burn vs the --slo-*
    # targets). dump_on_burn off — a bench judging a historical window
    # must not write crash artifacts.
    from paddle_tpu.observability.slo import SloPolicy, SloTracker

    slo = SloTracker(
        SloPolicy(target_ttft_s=args.slo_ttft,
                  target_availability=args.slo_availability,
                  fast_window_s=60.0, slow_window_s=1800.0),
        dump_on_burn=False)

    def slo_snapshot():
        return router.snapshot() if fleet else srv.snapshot()

    slo.ingest(slo_snapshot())

    def submit(i, p, **kw):
        if fleet:
            return router.submit(p, **kw)
        return srv.submit(p, **kw)

    # ---- measured open-loop window ----
    interarrival = rng.exponential(1.0 / max(args.rate, 1e-6),
                                   args.requests)
    crash_at = args.requests // 2 if args.crash_replica else None
    crashed_replica = None
    # verify probes ride just below the crash point so the ones most
    # likely to be in flight when the replica dies are token-checked
    verify_idx = (set(range(max(0, crash_at - args.verify), crash_at))
                  if crash_at is not None
                  else set(range(args.verify)))
    verify_solo = {}
    tenant_of = {}
    handles, rejected = [], 0
    t0 = time.perf_counter()
    for i in range(args.requests):
        target = t0 + float(interarrival[:i + 1].sum())
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        if crash_at is not None and i == crash_at:
            # hard kill, no drain: queued + in-flight requests must be
            # rerouted by the router, not lost
            crashed_replica = names[-1]
            servers[-1].shutdown(drain=False, timeout=60.0)
        p = trace_prompt(i)
        tid = trace_tenant(i)
        tenant_of[i] = tid
        verify = i in verify_idx
        kw = dict(max_new_tokens=args.new_tokens, seed=args.seed + i,
                  deadline=args.deadline, adapter_id=tid)
        if verify:
            # correctness probes must not expire on the SLO — a queue-wait
            # miss would masquerade as token divergence
            kw["deadline"] = None
            verify_solo[i] = (p, tid)   # greedy + seeded: reproducible
        else:
            kw.update(do_sample=bool(i % 2), temperature=0.8, top_p=0.95)
        try:
            handles.append((i, submit(i, p, **kw)))
        except QueueFull:
            rejected += 1  # open loop: a reject is goodput lost, not a wait
    completed, failed, expired = 0, 0, 0
    results = {}
    for i, h in handles:
        try:
            results[i] = h.result(timeout=args.timeout)
            completed += 1
        except TimeoutError:
            if args.deadline is not None:
                expired += 1   # queue-wait SLO miss — goodput lost, not a bug
            else:
                failed += 1    # no SLO in play: a hung handle IS a lost
                               # request (the --crash-replica gate must see it)
        except Exception:
            failed += 1
    elapsed = time.perf_counter() - t0
    compiles_after = compile_cache.cache_stats()["compiles"]
    steady = compiles_after - compiles_before

    # ---- verify: seeded-greedy fleet streams == solo generate ----
    # divergence is judged only on probes that COMPLETED — a probe shed
    # by backpressure or lost to the crash is a capacity/loss event
    # (already visible in rejected/failed, and failed trips the crash
    # gate), not nondeterminism
    verify_failures = 0
    verify_compared = 0
    if verify_solo and stores:
        from paddle_tpu.lora import clear_adapter, set_adapter
    for i, (p, tid) in verify_solo.items():
        got = results.get(i)
        if got is None:
            continue
        verify_compared += 1
        if stores:
            # the tenant's solo reference runs with ITS adapter loaded
            # into the model's own leaves (engines hold their snapshot)
            if tid is None:
                clear_adapter(model)
            else:
                set_adapter(model, tenant_trees[tid])
        solo = model.generate(
            p[None], max_new_tokens=args.new_tokens,
            max_length=max_length, prefill_buckets=tuple(args.buckets))[0]
        if not np.array_equal(np.asarray(got), solo):
            verify_failures += 1
    if verify_solo and stores:
        clear_adapter(model)
    # the solo engine above compiles its own programs; they are not
    # serving-loop recompiles
    live = [s for i, s in enumerate(servers)
            if not (crashed_replica is not None and i == len(servers) - 1)]
    snaps = [s.snapshot() for s in live]
    slo.ingest(slo_snapshot())
    slo_report = slo.report()
    # unified-registry scrape while every live server's collectors are
    # still registered: occupancy, hit-rate and compile counters land in
    # the BENCH artifact alongside the throughput numbers (the SLO
    # ingest above lands its burn gauges first)
    from paddle_tpu.observability import default_registry

    metrics_snap = default_registry().snapshot()
    for s in live:
        s.shutdown(drain=True, timeout=60.0)

    # ---- report ----
    ttfts = [h.ttft_s for _, h in handles
             if getattr(h, "ttft_s", None) is not None]
    inter = LatencyHistogram.merge(
        [s.metrics.inter_token for s in live]).summary()
    queue_wait = LatencyHistogram.merge(
        [s.metrics.queue_wait for s in live]).summary()
    hit = sum(sn["prefix_hit_tokens"] for sn in snaps)
    miss = sum(sn["prefix_miss_tokens"] for sn in snaps)
    tokens_emitted = sum(sn["tokens_emitted"] for sn in snaps)
    per_replica_compiles = [s.engine.cache_stats() for s in live]
    budget = len(srv.engine.prefill_buckets) + 1
    over_budget = [
        i for i, cc in enumerate(per_replica_compiles)
        if cc["prefill"]["compiles"] + cc["decode"]["compiles"] > budget]
    occ = (sum(sn["slot_occupancy"] for sn in snaps) / len(snaps)
           if snaps else 0.0)

    per_adapter = {}
    if stores:
        # offered/completed per tenant from the trace bookkeeping,
        # merged with the servers' per_adapter metric blocks
        for i, tid in tenant_of.items():
            name = tid or "base"
            e = per_adapter.setdefault(
                name, {"offered": 0, "completed": 0, "tokens": 0,
                       "ttft_p50_ms": 0.0})
            e["offered"] += 1
            if i in results:
                e["completed"] += 1
        for sn in snaps:
            for name, m in sn.get("per_adapter", {}).items():
                e = per_adapter.setdefault(
                    name, {"offered": 0, "completed": 0, "tokens": 0,
                           "ttft_p50_ms": 0.0})
                e["tokens"] += m["tokens"]
                e["ttft_p50_ms"] = max(e["ttft_p50_ms"], m["ttft_p50_ms"])
        adapter_loads = sum(st.stats()["loads"] for st in stores)
        adapter_evictions = sum(st.stats()["evictions"] for st in stores)

    record = {
        "metric": f"{args.model}_serve_requests_per_sec",
        "value": round(completed / max(elapsed, 1e-9), 3),
        "unit": "req/s",
        "extra": {
            "goodput": round(completed / max(args.requests, 1), 4),
            "offered_requests": args.requests,
            "completed": completed,
            "rejected": rejected,
            "expired": expired,
            "failed": failed,
            "deadline_s": args.deadline,
            "offered_rate_per_sec": args.rate,
            "elapsed_s": round(elapsed, 3),
            "tokens_per_sec": round(tokens_emitted / max(elapsed, 1e-9), 2),
            "ttft_p50_ms": round(_pct(ttfts, 50) * 1e3, 3),
            "ttft_p99_ms": round(_pct(ttfts, 99) * 1e3, 3),
            "inter_token_p50_ms": inter["p50_ms"],
            "inter_token_p99_ms": inter["p99_ms"],
            "queue_wait_p99_ms": queue_wait["p99_ms"],
            "slot_occupancy": round(occ, 4),
            "slots": args.slots,
            "new_tokens": args.new_tokens,
            "replicas": args.replicas,
            "live_replicas": len(live),
            "prefix_cache_mb": args.prefix_cache_mb,
            "prefix_tokens": args.prefix_tokens,
            "cache_hit_rate": round(hit / (hit + miss), 4)
            if (hit + miss) else 0.0,
            "prefix_hit_tokens": hit,
            "prefix_miss_tokens": miss,
            "prefill_compiles": sum(
                cc["prefill"]["compiles"] for cc in per_replica_compiles),
            "decode_compiles": sum(
                cc["decode"]["compiles"] for cc in per_replica_compiles),
            "compile_budget_per_replica": budget,
            "steady_state_recompiles": steady,
            "warmup_s": round(warmup_s, 2),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "preset": args.preset,
            "check": bool(args.check),
            "metrics": metrics_snap,
            "slo_report": slo_report,
            **({"crashed_replica": crashed_replica,
                "rerouted": router.snapshot()["requests_rerouted"]}
               if crashed_replica is not None else {}),
            **({"verified": len(verify_solo),
                "verify_compared": verify_compared,
                "verify_failures": verify_failures}
               if args.verify else {}),
            **({"adapters": args.adapters,
                "adapter_frac": args.adapter_frac,
                "adapter_rank": args.adapter_rank,
                "max_loaded": args.max_loaded or args.adapters,
                "adapter_loads": adapter_loads,
                "adapter_evictions": adapter_evictions,
                "per_adapter": per_adapter}
               if stores else {}),
        },
    }
    print(json.dumps(record))
    rc = 0
    if steady:
        print(f"FAIL: {steady} recompile(s) during the measured window — "
              f"the serving loop is not shape-stable (see "
              f"compile_cache.cache_stats() signatures)", file=sys.stderr)
        rc = 1
    if over_budget:
        print(f"FAIL: replica(s) {over_budget} exceeded the "
              f"#buckets+1={budget} compile budget", file=sys.stderr)
        rc = 1
    if verify_failures:
        print(f"FAIL: {verify_failures}/{verify_compared} completed "
              f"seeded-greedy probes diverged from solo generate "
              f"(placement/reroute changed tokens)", file=sys.stderr)
        rc = 1
    if args.crash_replica and failed:
        print(f"FAIL: {failed} request(s) lost to the replica crash — "
              f"the router did not requeue them onto survivors",
              file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
