"""Latency-percentile load bench for the serving stack — solo or fleet.

Open-loop Poisson load (arrivals don't wait for completions — the honest
way to measure a server: closed-loop generators self-throttle and hide
queueing collapse) against ``paddle_tpu.serving``, reporting the serving
numbers that matter and the compile discipline. Prints ONE JSON line:

    {"metric": "gpt_serve_requests_per_sec", "value": N, "unit": "req/s",
     "extra": {"goodput": ..., "ttft_p50_ms": ..., "ttft_p99_ms": ...,
               "inter_token_p50_ms": ..., "inter_token_p99_ms": ...,
               "tokens_per_sec": ..., "slot_occupancy": ...,
               "cache_hit_rate": ..., "steady_state_recompiles": ...}}

Defaults reproduce the PR 4 single-replica bench byte-for-byte (the
``gpt_serve_requests_per_sec`` breadth metric ``bench.py`` probes).
Fleet knobs:

- ``--replicas N`` puts a load-aware ``ReplicaRouter`` in front of N
  ``InferenceServer`` replicas (prefix-affinity + occupancy placement);
- ``--prefix-cache-mb M`` attaches a paged prefix/KV block pool to every
  replica (``--block-tokens`` sets the page size);
- ``--prefix-tokens P`` switches the trace generator prefix-heavy: a
  ``--prefix-frac`` share of requests open with the SAME P-token system
  prefix (the millions-of-users shape), the rest stay uniform random;
- ``--crash-replica`` hard-kills one replica mid-window (no drain) —
  the router must requeue its requests onto survivors with no recompile
  and, for the ``--verify K`` seeded-greedy probes, no token divergence
  vs a solo ``generate`` (the fleet robustness gate).

Multi-tenant LoRA knobs:

- ``--adapters N`` registers N synthetic tenants (rank ``--adapter-rank``
  LoRA adapters on the attention+MLP projections) in a per-replica
  ``AdapterStore``; an ``--adapter-frac`` share of requests carries a
  tenant id drawn Zipf-style (skewed popularity — the realistic shape);
- ``--max-loaded`` caps device-resident adapters per replica (default:
  all N), so a smaller value exercises LRU load/evict churn under load —
  which must stay recompile-free;
- ``--verify`` probes with a tenant id are checked token-exact against a
  solo ``generate`` with that adapter's weights loaded.

The JSON gains a ``per_adapter`` block (offered/completed/tokens/TTFT
p50 per tenant) plus registry load/evict totals, and an ``slo_report``
block: per-tenant availability + multi-window burn rates over the
measured window against the ``--slo-ttft`` / ``--slo-availability``
targets (``observability.slo``).

Warmup touches every prefill bucket on every replica first; the
measured window must then hold at ``#buckets + 1`` programs per replica
— ANY steady-state recompile exits non-zero (the serving analogue of
``tools/retrace_report.py``), as does a verify mismatch or an
unrecovered crash casualty.

    python tools/serve_bench.py                  # CPU-safe tiny config
    python tools/serve_bench.py --check          # quick CI/bench probe
    python tools/serve_bench.py --preset serving --slots 8 --rate 4
    python tools/serve_bench.py --replicas 2 --prefix-cache-mb 8 \\
        --prefix-tokens 24 --crash-replica --verify 3
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def _pct(values, p):
    if not values:
        return 0.0
    return float(np.percentile(np.asarray(values, np.float64), p))


def _emit(record, json_out=None):
    """Print the one-line JSON record; mirror it to ``--json-out`` so
    the robustness gate can diff it against a checked-in baseline."""
    line = json.dumps(record)
    print(line)
    if json_out:
        with open(json_out, "w") as f:
            f.write(line + "\n")


def _kv_logit_error(model, prompt, steps, max_length):
    """Max relative logit error of an int8-quantized KV cache against
    full precision, over a teacher-forced decode (same token sequence
    through both caches, so every step compares like with like).
    Prefill attends over the un-quantized fresh block, so the error
    budget is spent exactly where the quantized path reads the cache:
    the decode steps."""
    import jax.numpy as jnp

    from paddle_tpu.models.generation import init_cache
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     param_state)

    was_training = model.training
    model.eval()
    try:
        params, buffers = param_state(model), buffer_state(model)
        ids = jnp.asarray(prompt[None].astype(np.int32))
        seqs = {}
        for name, kv in (("full", None), ("int8", "int8")):
            cache = init_cache(model, 1, max_length, kv_dtype=kv)
            (lg, cache), _ = functional_call(
                model, params, buffers, ids, cache=cache,
                position_offset=0)
            per_step = [np.asarray(lg[:, -1], np.float32)]
            pos = int(prompt.shape[0])
            for s in range(steps):
                if name == "full":
                    tok = int(np.argmax(per_step[-1]))
                    seqs.setdefault("toks", []).append(tok)
                else:
                    tok = seqs["toks"][s]   # teacher-forced: same tokens
                (lg, cache), _ = functional_call(
                    model, params, buffers,
                    jnp.full((1, 1), tok, jnp.int32), cache=cache,
                    position_offset=pos + s)
                per_step.append(np.asarray(lg[:, -1], np.float32))
            seqs[name] = np.concatenate(per_step, axis=0)
    finally:
        if was_training:
            model.train()
    ref, quant = seqs["full"], seqs["int8"]
    scale = max(float(np.max(np.abs(ref))), 1e-9)
    return float(np.max(np.abs(ref - quant))) / scale


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=("gpt", "llama"), default="gpt")
    ap.add_argument("--preset", choices=("tiny", "small", "serving"), default="tiny")
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--rate", type=float, default=2.0,
                    help="offered load, requests/s (Poisson arrivals)")
    ap.add_argument("--requests", type=int, default=16,
                    help="measured requests after warmup")
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--buckets", type=int, nargs="+", default=(16, 32))
    ap.add_argument("--max-queue-depth", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-request completion wait cap (s)")
    ap.add_argument("--deadline", type=float, default=None,
                    help="per-request queue-wait SLO (s): requests that "
                         "cannot start in time expire and count against "
                         "goodput — the number queueing collapse "
                         "actually destroys")
    ap.add_argument("--check", action="store_true",
                    help="small fixed workload for CI / bench.py probing")
    ap.add_argument("--kv-dtype", choices=("none", "int8"), default="none",
                    help="KV-cache storage dtype for every replica "
                         "(int8 = quantized slots + pool blocks)")
    ap.add_argument("--kv-logit-tol", type=float, default=0.05,
                    help="max relative logit error (vs full-precision "
                         "KV) the quantized --verify gate accepts")
    # ---- fleet knobs ----
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument("--prefix-cache-mb", type=float, default=0.0,
                    help="per-replica paged KV block pool budget (0=off)")
    ap.add_argument("--block-tokens", type=int, default=8,
                    help="prefix-cache page size in tokens")
    ap.add_argument("--prefix-tokens", type=int, default=0,
                    help="shared system-prefix length for the "
                         "prefix-heavy trace (0=uniform random trace)")
    ap.add_argument("--prefix-frac", type=float, default=0.9,
                    help="share of requests carrying the shared prefix")
    ap.add_argument("--affinity-weight", type=float, default=0.75)
    ap.add_argument("--crash-replica", action="store_true",
                    help="hard-kill one replica mid-window (router must "
                         "reroute with no recompiles / no divergence)")
    ap.add_argument("--verify", type=int, default=0,
                    help="seeded-greedy probes checked token-exact "
                         "against a solo generate after the window")
    # ---- multi-tenant LoRA knobs ----
    ap.add_argument("--adapters", type=int, default=0,
                    help="register N synthetic LoRA tenants per replica "
                         "(0 = base-only trace)")
    ap.add_argument("--adapter-frac", type=float, default=0.7,
                    help="share of requests carrying a tenant id "
                         "(Zipf-skewed popularity over --adapters)")
    ap.add_argument("--adapter-rank", type=int, default=4)
    ap.add_argument("--max-loaded", type=int, default=0,
                    help="device-resident adapters per replica (0 = all "
                         "of --adapters; smaller exercises LRU churn)")
    # ---- SLO report knobs ----
    ap.add_argument("--slo-ttft", type=float, default=0.5,
                    help="per-tenant TTFT target (s) for the slo_report "
                         "block (window-mean judged)")
    ap.add_argument("--slo-availability", type=float, default=0.99,
                    help="per-tenant availability target for the "
                         "slo_report burn rates")
    # ---- adversarial fairness trace (SLO control loop, PR 16) ----
    ap.add_argument("--fairness", action="store_true",
                    help="adversarial SLO-control-loop trace: one "
                         "abusive tenant at 10x rate (token-bucket "
                         "throttled), a traffic spike that must force "
                         "a REAL burn-driven scale-out (child replica "
                         "over rpc), protected tenants' fast-window "
                         "burn must never edge-trigger, zero requests "
                         "lost across the scale events")
    ap.add_argument("--child-replica", action="store_true",
                    help="internal: host one replica for a --fairness "
                         "parent (rpc rank 1)")
    ap.add_argument("--endpoint", default=None,
                    help="internal: rpc master endpoint for "
                         "--child-replica")
    # ---- disaggregated prefill/decode fleet (PR 19) ----
    ap.add_argument("--disagg", action="store_true",
                    help="disaggregated fleet: dedicated prefill "
                         "replicas fill KV blocks and migrate them to "
                         "decode replicas over rpc (serving.disagg); "
                         "measures cold vs warm replica boot through "
                         "the persistent compile cache, per-pool "
                         "occupancy/goodput, and migration overhead")
    ap.add_argument("--prefill-ratio", type=float, default=0.5,
                    help="share of --replicas dedicated to the prefill "
                         "pool in --disagg mode (at least one replica "
                         "per pool; the PR 16 autoscaler scales each "
                         "pool on its own burn signal)")
    ap.add_argument("--json-out", default=None, metavar="PATH",
                    help="also write the one-line JSON record to PATH "
                         "(the regression-gate input)")
    ap.add_argument("--disagg-child", choices=("prefill", "decode"),
                    default=None,
                    help="internal: host one disagg replica of this "
                         "role for a --disagg parent")
    ap.add_argument("--rpc-name", default=None,
                    help="internal: rpc worker name for --disagg-child")
    ap.add_argument("--rank", type=int, default=None,
                    help="internal: rpc rank for --disagg-child")
    ap.add_argument("--world", type=int, default=None,
                    help="internal: rpc world size for --disagg-child")
    ap.add_argument("--wait-file", default=None,
                    help="internal: defer the model build until this "
                         "file exists (the warm-boot release gate)")
    args = ap.parse_args(argv)
    if args.child_replica:
        return _child_replica_main(args)
    if args.disagg_child:
        return _disagg_child_main(args)
    if args.fairness:
        return _fairness_main(args)
    if args.disagg:
        return _disagg_main(args)
    if args.check:
        args.requests = min(args.requests, 8)
        args.rate = min(args.rate, 4.0)
        args.new_tokens = min(args.new_tokens, 10)
    if args.replicas < 1:
        ap.error("--replicas must be >= 1")
    if args.crash_replica and args.replicas < 2:
        ap.error("--crash-replica needs --replicas >= 2 (someone must "
                 "survive)")

    import jax

    from decode_bench import build_model
    from paddle_tpu.framework import compile_cache
    from paddle_tpu.serving import (InferenceServer, LatencyHistogram,
                                    QueueFull, ReplicaRouter)

    model, cfg = build_model(args.model, args.preset)
    prefix_pad = args.prefix_tokens + args.block_tokens
    max_length = min(cfg.max_position_embeddings,
                     max(args.buckets) + args.new_tokens + 8
                     + (prefix_pad if args.prefix_tokens else 0))
    if args.prefix_tokens and (args.prefix_tokens + args.block_tokens
                               + args.new_tokens > max_length):
        ap.error(
            f"--prefix-tokens {args.prefix_tokens} + --block-tokens "
            f"{args.block_tokens} + --new-tokens {args.new_tokens} "
            f"exceeds the model's cache length {max_length} "
            f"(max_position_embeddings={cfg.max_position_embeddings}); "
            f"shrink the prefix or pick a larger preset")
    if args.prefix_tokens and (args.prefix_tokens + args.block_tokens
                               > max(args.buckets)):
        # a cold shared-prefix prompt would overflow the top declared
        # bucket into the ladder — a legitimate warmup compile the
        # #buckets+1 budget check would then (correctly) reject
        ap.error(
            f"--prefix-tokens {args.prefix_tokens} + --block-tokens "
            f"{args.block_tokens} overflows the largest prefill bucket "
            f"{max(args.buckets)}; declare a bucket that fits the cold "
            f"prefix prompt (e.g. --buckets {min(args.buckets)} "
            f"{args.prefix_tokens + args.block_tokens})")
    prefix_cache = (int(args.prefix_cache_mb * (1 << 20))
                    if args.prefix_cache_mb > 0 else None)

    # ---- multi-tenant LoRA: N synthetic adapters, one store per replica
    tenant_names, tenant_trees, stores = [], {}, []
    if args.adapters > 0:
        from paddle_tpu.lora import (AdapterStore, LoraConfig, apply_lora,
                                     lora_state)

        lcfg = LoraConfig(rank=args.adapter_rank, alpha=2.0 * args.adapter_rank)
        apply_lora(model, lcfg)
        zero = lora_state(model)
        arng = np.random.default_rng(args.seed + 777)
        tenant_names = [f"tenant{k}" for k in range(args.adapters)]
        for name in tenant_names:
            tenant_trees[name] = {
                k: arng.normal(0.0, 0.02, v.shape).astype(np.float32)
                for k, v in zero.items()}
        max_loaded = args.max_loaded or args.adapters
        for _ in range(args.replicas):
            store = AdapterStore(model, lcfg, max_loaded=max_loaded)
            for name in tenant_names:
                store.register(name, tenant_trees[name])
            stores.append(store)
        # Zipf-ish popularity: a few hot tenants, a long cool tail
        zipf_w = np.array([1.0 / (k + 1) ** 1.1
                           for k in range(args.adapters)])
        zipf_w /= zipf_w.sum()
    kv_dtype = None if args.kv_dtype == "none" else args.kv_dtype
    servers = [
        InferenceServer(
            model, slots=args.slots, max_length=max_length,
            prefill_buckets=args.buckets,
            max_queue_depth=args.max_queue_depth,
            prefix_cache=(dict(max_bytes=prefix_cache,
                               block_tokens=args.block_tokens)
                          if prefix_cache else None),
            adapter_store=stores[i] if stores else None,
            kv_dtype=kv_dtype)
        for i in range(args.replicas)]
    fleet = args.replicas > 1
    router = None
    if fleet:
        router = ReplicaRouter(affinity_weight=args.affinity_weight)
        names = [router.add_replica(s, f"r{i}")
                 for i, s in enumerate(servers)]
    srv = servers[0]
    rng = np.random.default_rng(args.seed)
    lens = sorted(b - 2 for b in srv.engine.prefill_buckets)

    def prompt(n):
        return rng.integers(0, cfg.vocab_size, (int(n),)).astype(np.int32)

    shared_prefix = (prompt(args.prefix_tokens)
                     if args.prefix_tokens else None)

    def trace_prompt(i):
        """The measured trace: prefix-heavy when --prefix-tokens is
        set, PR 4's uniform-random lengths otherwise."""
        if shared_prefix is not None and rng.random() < args.prefix_frac:
            sfx = prompt(int(rng.integers(2, args.block_tokens + 1)))
            return np.concatenate([shared_prefix, sfx])
        return prompt(int(rng.integers(4, max(lens) + 1)))

    def trace_tenant(i):
        """Per-request tenant id: an --adapter-frac share of requests
        carries one, drawn Zipf-style over the registered adapters."""
        if not tenant_names or rng.random() >= args.adapter_frac:
            return None
        return tenant_names[int(rng.choice(args.adapters, p=zipf_w))]

    # ---- warmup: touch every bucket + the decode program, per replica ----
    t_warm = time.perf_counter()
    for s in servers:
        for L in lens:
            s.submit(prompt(L), max_new_tokens=4).result(
                timeout=args.timeout)
        s.submit(prompt(lens[0]), max_new_tokens=4, do_sample=True,
                 temperature=0.9, top_p=0.9, seed=1).result(
                     timeout=args.timeout)
        if shared_prefix is not None:
            # the suffix bucket a prefix hit lands in must be warm too
            s.submit(np.concatenate([shared_prefix, prompt(4)]),
                     max_new_tokens=4).result(timeout=args.timeout)
    warmup_s = time.perf_counter() - t_warm
    compiles_before = compile_cache.cache_stats()["compiles"]
    for s in servers:
        s.metrics.reset()

    # SLO burn-rate evaluation over the measured window: baseline
    # ingest here, final ingest after the window; the report block
    # rides the JSON (per-tenant availability + burn vs the --slo-*
    # targets). dump_on_burn off — a bench judging a historical window
    # must not write crash artifacts.
    from paddle_tpu.observability.slo import SloPolicy, SloTracker

    slo = SloTracker(
        SloPolicy(target_ttft_s=args.slo_ttft,
                  target_availability=args.slo_availability,
                  fast_window_s=60.0, slow_window_s=1800.0),
        dump_on_burn=False)

    def slo_snapshot():
        return router.snapshot() if fleet else srv.snapshot()

    slo.ingest(slo_snapshot())

    def submit(i, p, **kw):
        if fleet:
            return router.submit(p, **kw)
        return srv.submit(p, **kw)

    # ---- measured open-loop window ----
    interarrival = rng.exponential(1.0 / max(args.rate, 1e-6),
                                   args.requests)
    crash_at = args.requests // 2 if args.crash_replica else None
    crashed_replica = None
    # verify probes ride just below the crash point so the ones most
    # likely to be in flight when the replica dies are token-checked
    verify_idx = (set(range(max(0, crash_at - args.verify), crash_at))
                  if crash_at is not None
                  else set(range(args.verify)))
    verify_solo = {}
    tenant_of = {}
    handles, rejected = [], 0
    t0 = time.perf_counter()
    for i in range(args.requests):
        target = t0 + float(interarrival[:i + 1].sum())
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        if crash_at is not None and i == crash_at:
            # hard kill, no drain: queued + in-flight requests must be
            # rerouted by the router, not lost
            crashed_replica = names[-1]
            servers[-1].shutdown(drain=False, timeout=60.0)
        p = trace_prompt(i)
        tid = trace_tenant(i)
        tenant_of[i] = tid
        verify = i in verify_idx
        kw = dict(max_new_tokens=args.new_tokens, seed=args.seed + i,
                  deadline=args.deadline, adapter_id=tid)
        if verify:
            # correctness probes must not expire on the SLO — a queue-wait
            # miss would masquerade as token divergence
            kw["deadline"] = None
            verify_solo[i] = (p, tid)   # greedy + seeded: reproducible
        else:
            kw.update(do_sample=bool(i % 2), temperature=0.8, top_p=0.95)
        try:
            handles.append((i, submit(i, p, **kw)))
        except QueueFull:
            rejected += 1  # open loop: a reject is goodput lost, not a wait
    completed, failed, expired = 0, 0, 0
    results = {}
    for i, h in handles:
        try:
            results[i] = h.result(timeout=args.timeout)
            completed += 1
        except TimeoutError:
            if args.deadline is not None:
                expired += 1   # queue-wait SLO miss — goodput lost, not a bug
            else:
                failed += 1    # no SLO in play: a hung handle IS a lost
                               # request (the --crash-replica gate must see it)
        except Exception:
            failed += 1
    elapsed = time.perf_counter() - t0
    compiles_after = compile_cache.cache_stats()["compiles"]
    steady = compiles_after - compiles_before

    # ---- verify: seeded-greedy fleet streams == solo generate ----
    # divergence is judged only on probes that COMPLETED — a probe shed
    # by backpressure or lost to the crash is a capacity/loss event
    # (already visible in rejected/failed, and failed trips the crash
    # gate), not nondeterminism
    verify_failures = 0
    verify_compared = 0
    if verify_solo and stores:
        from paddle_tpu.lora import clear_adapter, set_adapter
    for i, (p, tid) in verify_solo.items():
        got = results.get(i)
        if got is None:
            continue
        verify_compared += 1
        if stores:
            # the tenant's solo reference runs with ITS adapter loaded
            # into the model's own leaves (engines hold their snapshot)
            if tid is None:
                clear_adapter(model)
            else:
                set_adapter(model, tenant_trees[tid])
        # the solo reference runs with the SAME kv storage dtype, so the
        # served stream stays token-EXACT even when quantized (fidelity
        # of quantization itself is the separate logit-error gate below)
        solo = model.generate(
            p[None], max_new_tokens=args.new_tokens,
            max_length=max_length, prefill_buckets=tuple(args.buckets),
            kv_dtype=kv_dtype)[0]
        if not np.array_equal(np.asarray(got), solo):
            verify_failures += 1
    if verify_solo and stores:
        clear_adapter(model)
    # quantized fidelity gate: the token-parity probes above prove the
    # served stream matches solo-with-int8; this bounds how far the
    # int8 cache's LOGITS drift from full precision (the bitwise gate's
    # replacement for a lossy representation)
    kv_logit_err = None
    if kv_dtype is not None and args.verify:
        probe = prompt(lens[0])
        kv_logit_err = _kv_logit_error(model, probe,
                                       steps=min(args.new_tokens, 8),
                                       max_length=max_length)
    # the solo engine above compiles its own programs; they are not
    # serving-loop recompiles
    live = [s for i, s in enumerate(servers)
            if not (crashed_replica is not None and i == len(servers) - 1)]
    snaps = [s.snapshot() for s in live]
    slo.ingest(slo_snapshot())
    slo_report = slo.report()
    # unified-registry scrape while every live server's collectors are
    # still registered: occupancy, hit-rate and compile counters land in
    # the BENCH artifact alongside the throughput numbers (the SLO
    # ingest above lands its burn gauges first)
    from paddle_tpu.observability import default_registry

    metrics_snap = default_registry().snapshot()
    for s in live:
        s.shutdown(drain=True, timeout=60.0)

    # ---- report ----
    ttfts = [h.ttft_s for _, h in handles
             if getattr(h, "ttft_s", None) is not None]
    inter = LatencyHistogram.merge(
        [s.metrics.inter_token for s in live]).summary()
    queue_wait = LatencyHistogram.merge(
        [s.metrics.queue_wait for s in live]).summary()
    hit = sum(sn["prefix_hit_tokens"] for sn in snaps)
    miss = sum(sn["prefix_miss_tokens"] for sn in snaps)
    tokens_emitted = sum(sn["tokens_emitted"] for sn in snaps)
    per_replica_compiles = [s.engine.cache_stats() for s in live]
    budget = len(srv.engine.prefill_buckets) + 1
    over_budget = [
        i for i, cc in enumerate(per_replica_compiles)
        if cc["prefill"]["compiles"] + cc["decode"]["compiles"] > budget]
    occ = (sum(sn["slot_occupancy"] for sn in snaps) / len(snaps)
           if snaps else 0.0)

    per_adapter = {}
    if stores:
        # offered/completed per tenant from the trace bookkeeping,
        # merged with the servers' per_adapter metric blocks
        for i, tid in tenant_of.items():
            name = tid or "base"
            e = per_adapter.setdefault(
                name, {"offered": 0, "completed": 0, "tokens": 0,
                       "ttft_p50_ms": 0.0})
            e["offered"] += 1
            if i in results:
                e["completed"] += 1
        for sn in snaps:
            for name, m in sn.get("per_adapter", {}).items():
                e = per_adapter.setdefault(
                    name, {"offered": 0, "completed": 0, "tokens": 0,
                           "ttft_p50_ms": 0.0})
                e["tokens"] += m["tokens"]
                e["ttft_p50_ms"] = max(e["ttft_p50_ms"], m["ttft_p50_ms"])
        adapter_loads = sum(st.stats()["loads"] for st in stores)
        adapter_evictions = sum(st.stats()["evictions"] for st in stores)

    record = {
        "metric": f"{args.model}_serve_requests_per_sec",
        "value": round(completed / max(elapsed, 1e-9), 3),
        "unit": "req/s",
        "extra": {
            "goodput": round(completed / max(args.requests, 1), 4),
            "offered_requests": args.requests,
            "completed": completed,
            "rejected": rejected,
            "expired": expired,
            "failed": failed,
            "deadline_s": args.deadline,
            "offered_rate_per_sec": args.rate,
            "elapsed_s": round(elapsed, 3),
            "tokens_per_sec": round(tokens_emitted / max(elapsed, 1e-9), 2),
            "ttft_p50_ms": round(_pct(ttfts, 50) * 1e3, 3),
            "ttft_p99_ms": round(_pct(ttfts, 99) * 1e3, 3),
            "inter_token_p50_ms": inter["p50_ms"],
            "inter_token_p99_ms": inter["p99_ms"],
            "queue_wait_p99_ms": queue_wait["p99_ms"],
            "slot_occupancy": round(occ, 4),
            "slots": args.slots,
            "new_tokens": args.new_tokens,
            "replicas": args.replicas,
            "live_replicas": len(live),
            "prefix_cache_mb": args.prefix_cache_mb,
            "prefix_tokens": args.prefix_tokens,
            "cache_hit_rate": round(hit / (hit + miss), 4)
            if (hit + miss) else 0.0,
            "prefix_hit_tokens": hit,
            "prefix_miss_tokens": miss,
            "prefill_compiles": sum(
                cc["prefill"]["compiles"] for cc in per_replica_compiles),
            "decode_compiles": sum(
                cc["decode"]["compiles"] for cc in per_replica_compiles),
            "compile_budget_per_replica": budget,
            "steady_state_recompiles": steady,
            "warmup_s": round(warmup_s, 2),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "preset": args.preset,
            "check": bool(args.check),
            "kv_dtype": args.kv_dtype,
            **({"kv_logit_err": round(kv_logit_err, 6),
                "kv_logit_tol": args.kv_logit_tol}
               if kv_logit_err is not None else {}),
            "metrics": metrics_snap,
            "slo_report": slo_report,
            **({"crashed_replica": crashed_replica,
                "rerouted": router.snapshot()["requests_rerouted"]}
               if crashed_replica is not None else {}),
            **({"verified": len(verify_solo),
                "verify_compared": verify_compared,
                "verify_failures": verify_failures}
               if args.verify else {}),
            **({"adapters": args.adapters,
                "adapter_frac": args.adapter_frac,
                "adapter_rank": args.adapter_rank,
                "max_loaded": args.max_loaded or args.adapters,
                "adapter_loads": adapter_loads,
                "adapter_evictions": adapter_evictions,
                "per_adapter": per_adapter}
               if stores else {}),
        },
    }
    _emit(record, args.json_out)
    rc = 0
    if steady:
        print(f"FAIL: {steady} recompile(s) during the measured window — "
              f"the serving loop is not shape-stable (see "
              f"compile_cache.cache_stats() signatures)", file=sys.stderr)
        rc = 1
    if over_budget:
        print(f"FAIL: replica(s) {over_budget} exceeded the "
              f"#buckets+1={budget} compile budget", file=sys.stderr)
        rc = 1
    if verify_failures:
        print(f"FAIL: {verify_failures}/{verify_compared} completed "
              f"seeded-greedy probes diverged from solo generate "
              f"(placement/reroute changed tokens)", file=sys.stderr)
        rc = 1
    if kv_logit_err is not None and kv_logit_err > args.kv_logit_tol:
        print(f"FAIL: int8 KV cache drifts logits by "
              f"{kv_logit_err:.4f} (rel) > tol {args.kv_logit_tol} — "
              f"quantization error is out of bounds", file=sys.stderr)
        rc = 1
    if args.crash_replica and failed:
        print(f"FAIL: {failed} request(s) lost to the replica crash — "
              f"the router did not requeue them onto survivors",
              file=sys.stderr)
        rc = 1
    return rc


# --------------------------------------------------------------------
# Adversarial fairness trace: the SLO control loop end to end.
#
# Topology: rank 0 (this process) runs the router over one local
# replica; the burn-driven scale-out spawns rank 1 ("auto-r1") as a
# CHILD serve_bench process hosting a second replica over the rpc
# fabric (remote.host_server). Four tenants: "alice"/"bob" (protected,
# unthrottled), "abuser" (10x offered rate, token-bucket limited), and
# "spike" (a mid-run burst with a tight queue-wait deadline whose
# expiries burn the slow window — the legitimate overload signal the
# autoscaler must answer). Gates: the scale-out really happened and
# was triggered by the spike/fleet burn (NEVER the abuser — rate-limit
# rejects book no tenant failures, so abuse can't buy capacity), the
# protected tenants' fast window never edge-triggered, zero requests
# were lost (failed == 0) across the scale event, and the #buckets+1
# compile budget held on BOTH replicas, the cold-started one included.

_FAIR_TENANTS = ("alice", "bob", "abuser", "spike")
_FAIR_PROTECTED = ("alice", "bob")
_FAIR_ABUSER_RATE = 1.0      # admitted req/s the abuser is entitled to
_FAIR_SPIKE_N = 48           # spike burst depth (~16 service times on
                             # default slots: the tail MUST miss the
                             # ~2-service-time deadline on any machine)


def _fair_geometry(args):
    return dict(slots=args.slots, prefill_buckets=tuple(args.buckets),
                max_queue_depth=args.max_queue_depth,
                tenant_limits={"abuser": (_FAIR_ABUSER_RATE, 2.0)},
                fair_queueing=True)


def _fair_server(args, model):
    """One replica with the PR 16 admission knobs on: per-tenant DRR
    fair queueing + the abuser's token bucket, plus the shared adapter
    registry (per-tenant metrics need adapter-id traffic)."""
    from paddle_tpu.lora import (AdapterStore, LoraConfig, apply_lora,
                                 lora_state)
    from paddle_tpu.serving import InferenceServer

    lcfg = LoraConfig(rank=2, alpha=4.0)
    apply_lora(model, lcfg)
    zero = lora_state(model)
    arng = np.random.default_rng(args.seed + 777)   # same seed both
    store = AdapterStore(model, lcfg,                # ranks: same trees
                         max_loaded=len(_FAIR_TENANTS))
    for name in _FAIR_TENANTS:
        store.register(name, {
            k: arng.normal(0.0, 0.02, v.shape).astype(np.float32)
            for k, v in zero.items()})
    cfg_max_len = max(args.buckets) + args.new_tokens + 8
    srv = InferenceServer(model, max_length=cfg_max_len,
                          adapter_store=store, **_fair_geometry(args))
    return srv


def _fair_warm(srv, args, rng, vocab):
    """Touch every prefill bucket + the decode program (greedy trace:
    the budget must close at #buckets+1)."""
    for b in srv.engine.prefill_buckets:
        p = rng.integers(0, vocab, (b - 2,)).astype(np.int32)
        srv.submit(p, max_new_tokens=4).result(timeout=args.timeout)


def _child_replica_main(args) -> int:
    """Rank 1 of the fairness drill: host one warmed replica and serve
    until the parent signals stop. Spawned mid-run by the autoscaler —
    everything from here to the first served token is the cold-start
    window the parent reports as ``cold_start_ttft_s``."""
    from decode_bench import build_model
    from paddle_tpu.distributed import rpc
    from paddle_tpu.serving import remote

    rpc.init_rpc(name="auto-r1", rank=1, world_size=2,
                 master_endpoint=args.endpoint)
    model, cfg = build_model(args.model, args.preset)
    srv = _fair_server(args, model)
    # warm BEFORE hosting: wait_ready green means placeable at full
    # speed, and the measured window stays recompile-free on this
    # replica too
    _fair_warm(srv, args, np.random.default_rng(args.seed + 1),
               cfg.vocab_size)
    remote.host_server(srv, name="default")
    remote.wait_for_stop(timeout=900.0)
    try:
        srv.shutdown(drain=False, timeout=20.0)
    except Exception:
        pass
    rpc.shutdown(timeout=6.0)
    return 0


def _fairness_main(args) -> int:
    import socket
    import subprocess

    import jax

    from decode_bench import build_model
    from paddle_tpu.distributed import rpc
    from paddle_tpu.framework import compile_cache
    from paddle_tpu.observability.slo import SloPolicy
    from paddle_tpu.serving import (Autoscaler, ProcessReplicaSpawner,
                                    QueueFull, RateLimited,
                                    ReplicaRouter)
    from paddle_tpu.serving import remote as remote_mod

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        endpoint = f"127.0.0.1:{s.getsockname()[1]}"

    model, cfg = build_model(args.model, args.preset)
    local = _fair_server(args, model)
    policy = SloPolicy(
        # generous TTFT target: badness in this trace is AVAILABILITY
        # (spike expiries), so the burn evidence is machine-speed-proof
        target_ttft_s=30.0, target_availability=0.99,
        fast_window_s=15.0, slow_window_s=180.0)
    router = ReplicaRouter(slo_policy=policy)
    router.add_replica(local, "r0")

    child_argv = [
        sys.executable, os.path.abspath(__file__),
        "--child-replica", "--endpoint", endpoint,
        "--model", args.model, "--preset", args.preset,
        "--slots", str(args.slots),
        "--new-tokens", str(args.new_tokens),
        "--buckets", *[str(b) for b in args.buckets],
        "--max-queue-depth", str(args.max_queue_depth),
        "--seed", str(args.seed)]
    spawner = ProcessReplicaSpawner(
        child_argv, "auto-r1",
        init=lambda: rpc.init_rpc(name="bench", rank=0, world_size=2,
                                  master_endpoint=endpoint),
        rpc_timeout=30.0, connect_deadline=2.0, ready_timeout=600.0,
        env=dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu"))
    cold = {}
    rng = np.random.default_rng(args.seed)
    lens = sorted(b - 2 for b in local.engine.prefill_buckets)

    def prompt():
        n = int(rng.integers(4, max(lens) + 1))
        return rng.integers(0, cfg.vocab_size, (n,)).astype(np.int32)

    def spawn(name):
        """The autoscaler's actuator, wrapped to time the warm-boot
        window: child process start -> rpc rendezvous -> model build +
        bucket warmup -> host_server -> first served token."""
        t0 = time.perf_counter()
        replica = spawner(name)
        t_ready = time.perf_counter()
        h = replica.submit(prompt=prompt(), max_new_tokens=4)
        h.result(timeout=args.timeout)
        cold["cold_start_ttft_s"] = round(
            (t_ready - t0) + (h.ttft_s or 0.0), 3)
        cold["probe_ttft_s"] = round(h.ttft_s or 0.0, 4)
        return replica

    auto = Autoscaler(
        router, spawn, min_replicas=1, max_replicas=2,
        sustain_ticks=2, cooldown_s=300.0, replica_prefix="auto-r")

    _fair_warm(local, args, rng, cfg.vocab_size)
    # one timed service round-trip calibrates the spike's queue-wait
    # deadline to THIS machine (~2 service times): the 48-deep burst
    # tail then misses it whatever the absolute hardware speed, so the
    # burn evidence is deterministic, not host-dependent
    t_cal = time.perf_counter()
    local.submit(prompt(), max_new_tokens=args.new_tokens).result(
        timeout=args.timeout)
    spike_deadline = max(0.05, 2.0 * (time.perf_counter() - t_cal))
    local.metrics.reset()
    compiles_before = compile_cache.cache_stats()["compiles"]

    # ---- the trace: per-tenant Poisson arrivals + one spike burst ----
    protected_rate = 1.5
    events = []        # (t, tenant, deadline)
    for name, rate, t_end in (("alice", protected_rate, 16.0),
                              ("bob", protected_rate, 16.0),
                              ("abuser", 10 * protected_rate, 8.0)):
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / rate))
            if t >= t_end:
                break
            events.append((t, name, None))
    spike_at = 6.0
    for k in range(_FAIR_SPIKE_N):   # the legitimate overload: a burst
        events.append((spike_at + 0.01 * k, "spike",   # too big for one
                       spike_deadline))               # replica to hold
    events.sort()

    handles, rate_limited, rejected = [], 0, 0
    protected_breached, abuser_breached = [], []
    trigger = None
    tick_every, next_tick = 1.0, 1.0
    t0 = time.perf_counter()
    for t_at, tenant, deadline in events:
        now = time.perf_counter() - t0
        if t_at > now:
            time.sleep(t_at - now)
        while time.perf_counter() - t0 >= next_tick:
            d = auto.tick()
            if d is not None and d["action"] == "scale_out":
                trigger = d
            rep = router.slo_report() or {}
            for name, ten in rep.get("tenants", {}).items():
                if name in _FAIR_PROTECTED and (ten["fast_breached"]
                                                or ten["alerting"]):
                    protected_breached.append(name)
                if name == "abuser" and (ten["fast_breached"]
                                         or ten["slow_breached"]):
                    abuser_breached.append(ten)
            next_tick += tick_every
        try:
            handles.append((tenant, deadline, router.submit(
                prompt(), max_new_tokens=args.new_tokens,
                adapter_id=tenant, deadline=deadline,
                seed=args.seed)))
        except RateLimited:
            rate_limited += 1        # retryable fast-fail by design
        except QueueFull:
            rejected += 1
    # a few ticks past the window so a just-sustained burn still fires
    for _ in range(4):
        if auto.scale_outs:
            break
        time.sleep(tick_every)
        d = auto.tick()
        if d is not None and d["action"] == "scale_out":
            trigger = d

    completed, expired, failed = 0, 0, 0
    per_tenant = {n: {"offered": 0, "completed": 0, "expired": 0}
                  for n in _FAIR_TENANTS}
    for tenant, deadline, h in handles:
        per_tenant[tenant]["offered"] += 1
        try:
            h.result(timeout=args.timeout)
            completed += 1
            per_tenant[tenant]["completed"] += 1
        except TimeoutError:
            if deadline is not None:
                expired += 1         # spike deadline lapsed: SLO miss,
                per_tenant[tenant]["expired"] += 1   # not a lost request
            else:
                failed += 1          # no deadline in play: a hung
                                     # handle IS a lost request
        except Exception:
            failed += 1              # THIS is a lost request
    # post-scale traffic: the grown fleet must serve cleanly too
    post = {"offered": 0, "completed": 0}
    for k in range(8):
        post["offered"] += 1
        try:
            router.submit(prompt(), max_new_tokens=args.new_tokens,
                          adapter_id=_FAIR_PROTECTED[k % 2],
                          seed=args.seed).result(timeout=args.timeout)
            post["completed"] += 1
        except Exception:
            failed += 1
    steady = compile_cache.cache_stats()["compiles"] - compiles_before
    auto.tick()
    slo_final = router.slo_report() or {}
    for name, ten in slo_final.get("tenants", {}).items():
        if name in _FAIR_PROTECTED and (ten["fast_breached"]
                                        or ten["alerting"]):
            protected_breached.append(name)
    statz = router.statusz()

    # ---- per-replica compile budget, spawned replica included ----
    budget = len(local.engine.prefill_buckets) + 1
    budgets = {}
    cc = local.engine.cache_stats()
    budgets["r0"] = cc["prefill"]["compiles"] + cc["decode"]["compiles"]
    remote_snap = None
    for rep_name, state in router.replicas().items():
        if rep_name == "r0" or state == "dead":
            continue
        try:
            remote_snap = router._replicas[rep_name].server.snapshot()
            ccr = remote_snap.get("compile_stats", {})
            budgets[rep_name] = (ccr.get("prefill", {}).get("compiles", 0)
                                 + ccr.get("decode", {}).get("compiles", 0))
        except Exception:
            budgets[rep_name] = -1
    over_budget = {n: c for n, c in budgets.items()
                   if c > budget or c < 0}

    # ---- teardown: stop the child host, then the local plane ----
    child_rcs = []
    if spawner.procs:
        try:
            rpc.rpc_sync("auto-r1", remote_mod._host_request_stop,
                         timeout=10.0, connect_deadline=2.0)
        except Exception:
            pass
    local.shutdown(drain=True, timeout=60.0)
    if spawner._init_done:
        try:
            rpc.shutdown(timeout=8.0)
        except Exception:
            pass
    for proc in spawner.procs:
        try:
            child_rcs.append(proc.wait(timeout=120))
        except Exception:
            proc.kill()
            child_rcs.append(-1)

    record = {
        "metric": f"{args.model}_serve_fairness_goodput",
        "value": round(
            sum(per_tenant[n]["completed"] for n in _FAIR_PROTECTED)
            / max(1, sum(per_tenant[n]["offered"]
                         for n in _FAIR_PROTECTED)), 4),
        "unit": "goodput",
        "extra": {
            "completed": completed, "expired": expired, "failed": failed,
            "rate_limited_at_submit": rate_limited,
            "rate_limited_counter":
                local.metrics.snapshot()["requests_rate_limited"],
            "rejected": rejected,
            "spike_deadline_s": round(spike_deadline, 4),
            "per_tenant": per_tenant,
            "post_scale": post,
            "scale_outs": auto.scale_outs,
            "scale_decision": trigger,
            **cold,
            "compile_budget_per_replica": budget,
            "per_replica_compiles": budgets,
            "steady_state_recompiles": steady,
            "protected_fast_breaches": sorted(set(protected_breached)),
            "abuser_breaches": len(abuser_breached),
            "slo_tenants": {
                n: {"burn_fast": t["burn_fast"],
                    "burn_slow": t["burn_slow"],
                    "alerting": t["alerting"]}
                for n, t in slo_final.get("tenants", {}).items()},
            "autoscaler": statz.get("autoscaler"),
            "child_rcs": child_rcs,
            "backend": jax.default_backend(),
        },
    }
    _emit(record, args.json_out)
    rc = 0
    if not auto.scale_outs or trigger is None:
        print("FAIL: the spike never forced a scale-out — the SLO "
              "control loop did not close", file=sys.stderr)
        rc = 1
    elif trigger.get("tenant") not in ("spike", "__fleet__"):
        print(f"FAIL: scale-out was triggered by "
              f"{trigger.get('tenant')!r} — an abusive/protected "
              f"tenant bought fleet capacity", file=sys.stderr)
        rc = 1
    if protected_breached:
        print(f"FAIL: protected tenant(s) "
              f"{sorted(set(protected_breached))} edge-triggered a "
              f"fast-window burn — fairness did not hold under the "
              f"abuser", file=sys.stderr)
        rc = 1
    if abuser_breached:
        print(f"FAIL: the abuser's burn windows breached "
              f"({len(abuser_breached)} ticks) — rate-limit rejects "
              f"leaked into its SLO accounting", file=sys.stderr)
        rc = 1
    if failed:
        print(f"FAIL: {failed} request(s) lost across the scale "
              f"events", file=sys.stderr)
        rc = 1
    if rate_limited == 0:
        print("FAIL: the 10x abuser was never rate-limited",
              file=sys.stderr)
        rc = 1
    if over_budget:
        print(f"FAIL: compile budget ({budget}) exceeded: "
              f"{over_budget}", file=sys.stderr)
        rc = 1
    if steady:
        print(f"FAIL: {steady} local recompile(s) during the measured "
              f"window", file=sys.stderr)
        rc = 1
    if any(c != 0 for c in child_rcs):
        print(f"FAIL: child replica exit codes {child_rcs}",
              file=sys.stderr)
        rc = 1
    return rc


# --------------------------------------------------------------------
# Disaggregated prefill/decode fleet (PR 19).
#
# Topology: rank 0 (this process) runs the DisaggClient; dedicated
# prefill replicas (ranks 1..P) fill KV blocks for max_new_tokens=1
# requests and export them over rpc; decode replicas import the blocks
# into their own pool and serve the stream through the normal
# pool-admit path. Every child process points its persistent XLA
# compile cache at a shared per-role directory (serving.disagg
# .warm_boot_env): the FIRST decode replica boots cold and pays every
# compile; the deferred warm-boot replica — released mid-window by a
# wait-file touch, the scale-out moment — deserializes them and must
# boot in a fraction of the cold window (PR 16 measured ~7.4s cold).
#
# Gates: migrated-prefill streams token-identical to a solo generate
# (greedy + seeded), zero lost requests (fallback-to-local-recompute
# absorbs every failed migration leg), warm boot strictly faster than
# cold, at least one real migration, and the per-role compile budgets:
# #buckets prefill-only programs on a prefill replica (its decode
# program is never traced), #buckets+1 on a decode replica.

def _disagg_max_length(args, cfg):
    prefix_pad = args.prefix_tokens + args.block_tokens
    return min(cfg.max_position_embeddings,
               max(args.buckets) + args.new_tokens + 8
               + (prefix_pad if args.prefix_tokens else 0))


def _disagg_child_main(args) -> int:
    """One disagg replica host. Joins the rendezvous immediately (the
    fabric needs every rank), but a ``--wait-file`` child defers its
    model build + compile until the parent touches the file — the
    released-to-first-token window IS the warm-boot measurement."""
    from paddle_tpu.distributed import rpc
    from paddle_tpu.serving import remote

    rpc.init_rpc(name=args.rpc_name, rank=args.rank,
                 world_size=args.world, master_endpoint=args.endpoint)
    if args.wait_file:
        deadline = time.time() + 600.0
        while not os.path.exists(args.wait_file):
            if time.time() > deadline:
                return 3
            time.sleep(0.02)
    from decode_bench import build_model
    from paddle_tpu.serving import InferenceServer

    model, cfg = build_model(args.model, args.preset)
    srv = InferenceServer(
        model, slots=args.slots, max_length=_disagg_max_length(args, cfg),
        prefill_buckets=args.buckets,
        max_queue_depth=args.max_queue_depth,
        prefix_cache=dict(
            max_bytes=int(args.prefix_cache_mb * (1 << 20)),
            block_tokens=args.block_tokens),
        kv_dtype=None if args.kv_dtype == "none" else args.kv_dtype)
    # a prefill replica serves max_new_tokens=1 requests only — its
    # decode program is never traced, so the warmup must not trace it
    # either (#buckets programs, not #buckets+1)
    srv.engine.warmup(
        max_new_tokens=1 if args.disagg_child == "prefill" else 2)
    remote.host_server(srv, name="default")
    remote.wait_for_stop(timeout=900.0)
    try:
        srv.shutdown(drain=False, timeout=20.0)
    except Exception:
        pass
    rpc.shutdown(timeout=6.0)
    return 0


def _disagg_main(args) -> int:
    import socket
    import subprocess
    import tempfile
    import threading

    import jax

    from decode_bench import build_model
    from paddle_tpu.distributed import rpc
    from paddle_tpu.framework import compile_cache
    from paddle_tpu.serving import remote as remote_mod
    from paddle_tpu.serving.disagg import (DisaggClient, PrefixIndex,
                                           warm_boot_env)
    from paddle_tpu.serving.remote import RemoteReplica

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        endpoint = f"127.0.0.1:{s.getsockname()[1]}"

    n_total = max(2, args.replicas)
    n_prefill = max(1, min(n_total - 1,
                           int(round(args.prefill_ratio * n_total))))
    n_decode = n_total - n_prefill
    # +1: the deferred warm-boot decode replica; +1: this parent
    world = 1 + n_prefill + n_decode + 1
    if args.prefix_cache_mb <= 0:
        args.prefix_cache_mb = 8.0     # both pools need KV blocks
    if args.prefix_tokens == 0:
        # prefix-heavy by default: migration needs prompts past one
        # full block, and the cold shared-prefix prompt must still fit
        # the largest declared bucket (the main-mode invariant)
        args.prefix_tokens = max(args.buckets) - args.block_tokens
    if args.check:
        args.requests = min(args.requests, 12)
        args.rate = min(args.rate, 4.0)
        args.new_tokens = min(args.new_tokens, 10)

    work = tempfile.mkdtemp(prefix="disagg-bench-")
    # per-role cache dirs: the prefill pool must not pre-populate the
    # decode programs, or the "cold" decode boot would silently warm
    decode_cache = os.path.join(work, "cache-decode")
    prefill_cache = os.path.join(work, "cache-prefill")
    wait_file = os.path.join(work, "warm.go")

    def child_argv(role, name, rank, deferred=False):
        argv = [sys.executable, os.path.abspath(__file__),
                "--disagg-child", role, "--rpc-name", name,
                "--rank", str(rank), "--world", str(world),
                "--endpoint", endpoint,
                "--model", args.model, "--preset", args.preset,
                "--slots", str(args.slots),
                "--new-tokens", str(args.new_tokens),
                "--buckets", *[str(b) for b in args.buckets],
                "--max-queue-depth", str(args.max_queue_depth),
                "--block-tokens", str(args.block_tokens),
                "--prefix-cache-mb", str(args.prefix_cache_mb),
                "--prefix-tokens", str(args.prefix_tokens),
                "--kv-dtype", args.kv_dtype,
                "--seed", str(args.seed)]
        if deferred:
            argv += ["--wait-file", wait_file]
        return argv

    def child_env(cache_dir):
        # children serve on host CPU (a real fleet maps each to its own
        # accelerator); the warm_boot_env flags point their persistent
        # compile cache at the shared per-role directory
        return dict(os.environ, PYTHONPATH=repo, JAX_PLATFORMS="cpu",
                    **warm_boot_env(cache_dir))

    plan = []      # (role, rpc name, rank, cache dir, deferred)
    rank = 1
    for i in range(n_prefill):
        plan.append(("prefill", f"pre{i}", rank, prefill_cache, False))
        rank += 1
    for i in range(n_decode):
        plan.append(("decode", f"dec{i}", rank, decode_cache, False))
        rank += 1
    plan.append(("decode", "dec-warm", rank, decode_cache, True))

    procs = []
    t_fleet0 = time.perf_counter()
    for role, name, r, cache, deferred in plan:
        procs.append(subprocess.Popen(
            child_argv(role, name, r, deferred=deferred),
            env=child_env(cache)))
    rpc.init_rpc(name="bench", rank=0, world_size=world,
                 master_endpoint=endpoint)
    reps = {name: RemoteReplica(name, rpc_timeout=60.0,
                                connect_deadline=2.0)
            for _, name, _, _, _ in plan}
    lens = sorted(b - 2 for b in args.buckets)
    # vocab-independent probe (any model's vocab covers ids 1..97), so
    # the cold measurement needs no local model build first
    probe_prompt = ((np.arange(lens[0]) % 97) + 1).astype(np.int32)

    # ---- cold boot: fleet spawn -> first token on the cold decode ----
    if not reps["dec0"].wait_ready(timeout=600.0):
        print("FAIL: cold decode replica never hosted", file=sys.stderr)
        return 1
    h = reps["dec0"].submit(prompt=probe_prompt, max_new_tokens=4)
    h.result(timeout=args.timeout)
    cold_s = round(time.perf_counter() - t_fleet0, 3)
    for role, name, _, _, deferred in plan:
        if not deferred and not reps[name].wait_ready(timeout=600.0):
            print(f"FAIL: replica {name} never hosted", file=sys.stderr)
            return 1

    rng = np.random.default_rng(args.seed)
    model, cfg = build_model(args.model, args.preset)
    max_length = _disagg_max_length(args, cfg)

    def prompt(n):
        return rng.integers(0, cfg.vocab_size, (int(n),)).astype(np.int32)

    index = PrefixIndex()
    client = DisaggClient(
        [reps[f"pre{i}"] for i in range(n_prefill)],
        [reps[f"dec{i}"] for i in range(n_decode)],
        block_tokens=args.block_tokens, index=index,
        prefill_timeout_s=min(args.timeout, 60.0))

    shared_prefix = prompt(args.prefix_tokens)

    def trace_prompt():
        if rng.random() < args.prefix_frac:
            sfx = prompt(int(rng.integers(2, args.block_tokens + 1)))
            return np.concatenate([shared_prefix, sfx])
        return prompt(int(rng.integers(4, max(lens) + 1)))

    # ---- warm boot: released on another thread mid-window, like a
    # burn-driven scale-out; the decode pool grows when it lands ----
    warm = {}

    def release_warm():
        with open(wait_file, "w") as f:
            f.write("go\n")
        t0 = time.perf_counter()
        if not reps["dec-warm"].wait_ready(timeout=600.0):
            warm["error"] = "never hosted"
            return
        hw = reps["dec-warm"].submit(prompt=probe_prompt,
                                     max_new_tokens=4)
        hw.result(timeout=args.timeout)
        warm["warm_boot_s"] = round(time.perf_counter() - t0, 3)
        warm["t_added"] = time.perf_counter()
        client.decode.append(reps["dec-warm"])

    warm_thread = threading.Thread(target=release_warm, daemon=True)

    # ---- measured open-loop window through the DisaggClient ----
    compiles_before = compile_cache.cache_stats()["compiles"]
    interarrival = rng.exponential(1.0 / max(args.rate, 1e-6),
                                   args.requests)
    release_at = args.requests // 3
    verify_idx = set(range(min(args.verify or 2, args.requests)))
    verify_solo = {}
    handles, failed = [], 0
    ttft_pre_add, ttft_post_add = [], []
    t0 = time.perf_counter()
    for i in range(args.requests):
        target = t0 + float(interarrival[:i + 1].sum())
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        if i == release_at:
            warm_thread.start()
        if i and i % 8 == 0:
            client.scrape_index()
        # verify probes always carry the shared prefix: they must take
        # the MIGRATED path to prove token identity end to end
        p = (np.concatenate([shared_prefix,
                             prompt(int(rng.integers(2,
                                        args.block_tokens + 1)))])
             if i in verify_idx else trace_prompt())
        kw = dict(max_new_tokens=args.new_tokens, seed=args.seed + i)
        if i in verify_idx:
            verify_solo[i] = p
        else:
            kw.update(do_sample=bool(i % 2), temperature=0.8, top_p=0.95)
        handles.append((i, time.perf_counter(), client.submit(p, **kw)))
    completed, results = 0, {}
    for i, sub_t, h in handles:
        try:
            results[i] = h.result(timeout=args.timeout)
            completed += 1
            if getattr(h, "ttft_s", None) is not None:
                # p99-spike gate input: requests submitted after the
                # warm replica joined vs before
                (ttft_post_add
                 if sub_t >= warm.get("t_added", float("inf"))
                 else ttft_pre_add).append(h.ttft_s)
        except Exception:
            failed += 1
    elapsed = time.perf_counter() - t0
    warm_thread.join(timeout=600.0)
    steady = compile_cache.cache_stats()["compiles"] - compiles_before
    warm_s = warm.get("warm_boot_s")

    # ---- verify: migrated streams == cold solo generate ----
    verify_failures = 0
    for i, p in verify_solo.items():
        got = results.get(i)
        if got is None:
            continue
        solo = model.generate(
            p[None], max_new_tokens=args.new_tokens,
            max_length=max_length, prefill_buckets=tuple(args.buckets),
            kv_dtype=None if args.kv_dtype == "none" else args.kv_dtype)[0]
        if not np.array_equal(np.asarray(got), solo):
            verify_failures += 1

    # ---- per-pool blocks + per-role compile budgets ----
    pools = {"prefill": {"replicas": [], "budget": len(args.buckets)},
             "decode": {"replicas": [], "budget": len(args.buckets) + 1}}
    over_budget = {}
    for role, name, _, _, deferred in plan:
        if deferred and warm_s is None:
            continue
        try:
            sn = reps[name].snapshot()
        except Exception:
            over_budget[name] = -1
            continue
        cc = sn.get("compile_stats", {})
        compiles = (cc.get("prefill", {}).get("compiles", 0)
                    + cc.get("decode", {}).get("compiles", 0))
        pools[role]["replicas"].append({
            "name": name,
            "slot_occupancy": round(sn.get("slot_occupancy", 0.0), 4),
            "tokens_emitted": sn.get("tokens_emitted", 0),
            "completed": sn.get("requests_completed", 0),
            "prefix_hit_tokens": sn.get("prefix_hit_tokens", 0),
            "compiles": compiles})
        if compiles > pools[role]["budget"]:
            over_budget[name] = compiles
    for role, blk in pools.items():
        rs = blk["replicas"]
        blk["occupancy"] = round(
            sum(r["slot_occupancy"] for r in rs) / max(1, len(rs)), 4)
        blk["tokens_per_sec"] = round(
            sum(r["tokens_emitted"] for r in rs) / max(elapsed, 1e-9), 2)
    mig = client.statusz()
    pools["prefill"]["goodput"] = round(
        mig["migrations"] / max(1, mig["migrations"] + mig["fallbacks"]),
        4)
    pools["decode"]["goodput"] = round(
        completed / max(1, args.requests), 4)

    # ---- teardown ----
    child_rcs = []
    for _, name, _, _, deferred in plan:
        try:
            rpc.rpc_sync(name, remote_mod._host_request_stop,
                         timeout=10.0, connect_deadline=2.0)
        except Exception:
            pass
    try:
        rpc.shutdown(timeout=8.0)
    except Exception:
        pass
    for proc in procs:
        try:
            child_rcs.append(proc.wait(timeout=120))
        except Exception:
            proc.kill()
            child_rcs.append(-1)

    record = {
        "metric": f"{args.model}_serve_disagg_requests_per_sec",
        "value": round(completed / max(elapsed, 1e-9), 3),
        "unit": "req/s",
        "extra": {
            "goodput": round(completed / max(args.requests, 1), 4),
            "offered_requests": args.requests,
            "completed": completed,
            "failed": failed,
            "elapsed_s": round(elapsed, 3),
            "prefill_replicas": n_prefill,
            "decode_replicas": n_decode,
            "prefill_ratio": args.prefill_ratio,
            "cold_start_ttft_s": {
                "cold": cold_s,
                "warm": warm_s,
                "reduction_frac": (round(1.0 - warm_s / cold_s, 4)
                                   if warm_s else None)},
            "ttft_p99_pre_add_ms": round(
                _pct(ttft_pre_add, 99) * 1e3, 3),
            "ttft_p99_post_add_ms": round(
                _pct(ttft_post_add, 99) * 1e3, 3),
            "pools": pools,
            "migration": {**mig,
                          "overhead_frac": round(
                              mig["migrate_s"] / max(elapsed, 1e-9), 4)},
            "verified": len(verify_solo),
            "verify_failures": verify_failures,
            "steady_state_recompiles": steady,
            "compile_budget": {r: pools[r]["budget"] for r in pools},
            "child_rcs": child_rcs,
            "backend": jax.default_backend(),
            "preset": args.preset,
            "check": bool(args.check),
        },
    }
    _emit(record, args.json_out)
    rc = 0
    if verify_failures:
        print(f"FAIL: {verify_failures} migrated stream(s) diverged "
              f"from solo generate — block migration changed tokens",
              file=sys.stderr)
        rc = 1
    if failed:
        print(f"FAIL: {failed} request(s) lost — migration fallback "
              f"must absorb every failed leg", file=sys.stderr)
        rc = 1
    if mig["migrations"] == 0:
        print("FAIL: no migration ever succeeded — the disagg path "
              "never ran", file=sys.stderr)
        rc = 1
    if over_budget:
        print(f"FAIL: per-role compile budget exceeded: {over_budget} "
              f"(prefill={len(args.buckets)}, "
              f"decode={len(args.buckets) + 1})", file=sys.stderr)
        rc = 1
    if warm_s is None:
        print(f"FAIL: warm-boot replica never served "
              f"({warm.get('error', 'unknown')})", file=sys.stderr)
        rc = 1
    elif warm_s >= cold_s:
        print(f"FAIL: warm boot ({warm_s}s) not faster than cold "
              f"({cold_s}s) — the persistent compile cache did not "
              f"deserialize", file=sys.stderr)
        rc = 1
    if steady:
        print(f"FAIL: {steady} parent-side recompile(s) during the "
              f"measured window", file=sys.stderr)
        rc = 1
    if any(c != 0 for c in child_rcs):
        print(f"FAIL: child replica exit codes {child_rcs}",
              file=sys.stderr)
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
