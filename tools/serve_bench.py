"""Latency-percentile load bench for the continuous-batching server.

Open-loop Poisson load (arrivals don't wait for completions — the honest
way to measure a server: closed-loop generators self-throttle and hide
queueing collapse) against ``paddle_tpu.serving.InferenceServer``,
reporting the serving numbers that matter and the compile discipline.
Prints ONE JSON line:

    {"metric": "gpt_serve_requests_per_sec", "value": N, "unit": "req/s",
     "extra": {"goodput": ..., "ttft_p50_ms": ..., "ttft_p99_ms": ...,
               "inter_token_p50_ms": ..., "inter_token_p99_ms": ...,
               "tokens_per_sec": ..., "slot_occupancy": ...,
               "prefill_compiles": ..., "decode_compiles": ...,
               "steady_state_recompiles": ...}}

Warmup requests touch every prefill bucket first; the measured window
must then hold at ``#buckets + 1`` programs — ANY steady-state recompile
exits non-zero (the serving analogue of ``tools/retrace_report.py``).

    python tools/serve_bench.py                  # CPU-safe tiny config
    python tools/serve_bench.py --check          # quick CI/bench probe
    python tools/serve_bench.py --preset serving --slots 8 --rate 4
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--model", choices=("gpt", "llama"), default="gpt")
    ap.add_argument("--preset", choices=("tiny", "serving"), default="tiny")
    ap.add_argument("--slots", type=int, default=3)
    ap.add_argument("--rate", type=float, default=2.0,
                    help="offered load, requests/s (Poisson arrivals)")
    ap.add_argument("--requests", type=int, default=16,
                    help="measured requests after warmup")
    ap.add_argument("--new-tokens", type=int, default=16)
    ap.add_argument("--buckets", type=int, nargs="+", default=(16, 32))
    ap.add_argument("--max-queue-depth", type=int, default=64)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--timeout", type=float, default=600.0,
                    help="per-request completion wait cap (s)")
    ap.add_argument("--check", action="store_true",
                    help="small fixed workload for CI / bench.py probing")
    args = ap.parse_args(argv)
    if args.check:
        args.requests = min(args.requests, 8)
        args.rate = min(args.rate, 4.0)
        args.new_tokens = min(args.new_tokens, 10)

    import jax

    from decode_bench import build_model
    from paddle_tpu.framework import compile_cache
    from paddle_tpu.serving import InferenceServer, QueueFull

    model, cfg = build_model(args.model, args.preset)
    max_length = min(cfg.max_position_embeddings,
                     max(args.buckets) + args.new_tokens + 8)
    srv = InferenceServer(model, slots=args.slots, max_length=max_length,
                          prefill_buckets=args.buckets,
                          max_queue_depth=args.max_queue_depth)
    rng = np.random.default_rng(args.seed)
    lens = sorted(b - 2 for b in srv.engine.prefill_buckets)

    def prompt(n):
        return rng.integers(0, cfg.vocab_size, (int(n),)).astype(np.int32)

    # ---- warmup: touch every bucket (and the decode program) once ----
    t_warm = time.perf_counter()
    for L in lens:
        srv.submit(prompt(L), max_new_tokens=4).result(timeout=args.timeout)
    srv.submit(prompt(lens[0]), max_new_tokens=4, do_sample=True,
               temperature=0.9, top_p=0.9, seed=1).result(
                   timeout=args.timeout)
    warmup_s = time.perf_counter() - t_warm
    compiles_before = compile_cache.cache_stats()["compiles"]
    srv.metrics.reset()

    # ---- measured open-loop window ----
    interarrival = rng.exponential(1.0 / max(args.rate, 1e-6),
                                   args.requests)
    max_len = max(lens)
    handles, rejected = [], 0
    t0 = time.perf_counter()
    for i in range(args.requests):
        target = t0 + float(interarrival[:i + 1].sum())
        now = time.perf_counter()
        if target > now:
            time.sleep(target - now)
        L = int(rng.integers(4, max_len + 1))
        sampled = bool(i % 2)
        try:
            handles.append(srv.submit(
                prompt(L), max_new_tokens=args.new_tokens,
                do_sample=sampled, temperature=0.8, top_p=0.95,
                seed=args.seed + i))
        except QueueFull:
            rejected += 1  # open loop: a reject is goodput lost, not a wait
    completed = 0
    for h in handles:
        try:
            h.result(timeout=args.timeout)
            completed += 1
        except Exception:
            pass
    compiles_after = compile_cache.cache_stats()["compiles"]
    steady = compiles_after - compiles_before
    snap = srv.snapshot()
    srv.shutdown(drain=True, timeout=60.0)

    cc = snap["compile_stats"]
    record = {
        "metric": f"{args.model}_serve_requests_per_sec",
        "value": snap["requests_per_sec"],
        "unit": "req/s",
        "extra": {
            "goodput": round(completed / max(args.requests, 1), 4),
            "offered_requests": args.requests,
            "completed": completed,
            "rejected": rejected,
            "offered_rate_per_sec": args.rate,
            "tokens_per_sec": snap["tokens_per_sec"],
            "ttft_p50_ms": snap["ttft"]["p50_ms"],
            "ttft_p99_ms": snap["ttft"]["p99_ms"],
            "inter_token_p50_ms": snap["inter_token"]["p50_ms"],
            "inter_token_p99_ms": snap["inter_token"]["p99_ms"],
            "queue_wait_p99_ms": snap["queue_wait"]["p99_ms"],
            "slot_occupancy": snap["slot_occupancy"],
            "slots": args.slots,
            "new_tokens": args.new_tokens,
            "prefill_compiles": cc["prefill"]["compiles"],
            "decode_compiles": cc["decode"]["compiles"],
            "steady_state_recompiles": steady,
            "warmup_s": round(warmup_s, 2),
            "backend": jax.default_backend(),
            "device_kind": jax.devices()[0].device_kind,
            "preset": args.preset,
            "check": bool(args.check),
        },
    }
    print(json.dumps(record))
    if steady:
        print(f"FAIL: {steady} recompile(s) during the measured window — "
              f"the serving loop is not shape-stable (see "
              f"compile_cache.cache_stats() signatures)", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
