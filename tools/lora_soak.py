#!/usr/bin/env python
"""LoRA lifecycle soak: train -> die mid-save -> resume -> serve mixed.

The ``robustness_gate.py --lora`` stage. One run proves the full
multi-tenant adapter lifecycle survives the same faults the training
stack does:

1. **train** (child process): a tiny GPT adapter fine-tune through
   ``Model.fit(lora=..., recovery=...)`` — 20 optimizer steps, base
   model frozen, supervisor checkpoints every 5 steps;
2. **kill**: the first child carries a seeded ``FaultPlan`` that
   hard-exits (``os._exit``, as brutal as SIGKILL) at the SECOND
   checkpoint's publish fault point — a torn, unpublished save;
3. **resume** (second child): must restore the newest COMPLETE
   checkpoint (step 5 — the torn step-10 staging dir is invisible),
   fast-forward the data cursor, finish all 20 steps and publish the
   adapter via ``save_adapter`` (``format: "lora_adapter"`` metadata);
4. **serve** (parent): rebuild the base model, ``AdapterStore.load`` the
   trained adapter (fingerprint-checked) and run mixed base+tenant
   traffic on one continuous-batching server. The gate demands ZERO
   lost requests, ZERO steady-state recompiles, and token-identical
   seeded probes vs solo ``generate`` with the adapter loaded.

Exit non-zero on any violated invariant. ~30 s on a 2-core CPU box::

    python tools/lora_soak.py            # the full scenario
    python tools/lora_soak.py --keep     # keep the scratch dir
"""
from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

SEED = 1234
STEPS = 20          # 1 epoch x 20 batches
SAVE_EVERY = 5
RANK = 4


def _build(seed=SEED):
    import paddle_tpu as pt
    from paddle_tpu.models.gpt import GPTForCausalLM, gpt_tiny

    pt.seed(seed)
    cfg = gpt_tiny(hidden_size=64, num_layers=2, num_heads=2,
                   vocab_size=256, max_position_embeddings=64,
                   hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                   use_flash_attention=False)
    return GPTForCausalLM(cfg), cfg


def _batches(cfg, n=STEPS, batch=2, length=12):
    import numpy as np

    out = []
    for i in range(n):
        ids = np.random.default_rng(10_000 + i).integers(
            0, cfg.vocab_size, (batch, length)).astype(np.int32)
        out.append((ids, ids))
    return out


def child(args) -> int:
    """One training incarnation (crashes when the env fault plan says)."""
    import numpy as np

    from paddle_tpu import hapi
    from paddle_tpu.distributed.checkpoint import latest_checkpoint
    from paddle_tpu.framework.supervisor import RecoveryPolicy
    from paddle_tpu.lora import LoraConfig, save_adapter
    from paddle_tpu.optimizer import Adam

    model, cfg = _build()
    resumed_from = latest_checkpoint(args.ckpt_root)
    m = hapi.Model(model)
    m.prepare(optimizer=Adam(learning_rate=5e-3, parameters=[]),
              loss=lambda out, labels: model.loss(out, labels))
    m.fit(_batches(cfg), epochs=1, verbose=0,
          lora=LoraConfig(rank=RANK, alpha=2.0 * RANK),
          recovery=RecoveryPolicy(
              checkpoint_dir=args.ckpt_root,
              save_interval_steps=SAVE_EVERY, async_save=False,
              preemption=False, check_interval=1))
    step = m._train_step
    base = {k: np.asarray(v) for k, v in step.buffers.items()
            if k.endswith(".weight") or k.endswith(".bias")
            or "embeddings" in k}
    save_adapter(args.adapter_dir, model)
    print("LORA_CHILD " + json.dumps({
        "resumed_from": resumed_from,
        "final_step": step._count,
        "trainable": len(step.params),
        "frozen": len(base),
    }), flush=True)
    return 0


def _run_child(ckpt_root, adapter_dir, fault_plan=None):
    env = dict(os.environ, PYTHONPATH=REPO)
    env.setdefault("JAX_PLATFORMS", "cpu")
    if fault_plan is not None:
        env["PT_FAULT_PLAN"] = fault_plan
    else:
        env.pop("PT_FAULT_PLAN", None)
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--ckpt-root", ckpt_root, "--adapter-dir", adapter_dir]
    return subprocess.run(cmd, env=env, stdout=subprocess.PIPE,
                          stderr=subprocess.STDOUT, text=True, timeout=900)


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--ckpt-root", default=None)
    ap.add_argument("--adapter-dir", default=None)
    ap.add_argument("--keep", action="store_true",
                    help="keep the scratch directory")
    args = ap.parse_args()
    if args.child:
        return child(args)

    import numpy as np

    from paddle_tpu.distributed.resilience import CRASH_EXIT, FaultPlan

    scratch = tempfile.mkdtemp(prefix="lora_soak_")
    ckpt_root = os.path.join(scratch, "ckpt")
    adapter_dir = os.path.join(scratch, "adapter")
    failures = []
    t0 = time.monotonic()
    try:
        # ---- run 1: hard-exit at the SECOND checkpoint's publish -----
        plan = FaultPlan([{"site": "ckpt.publish", "kind": "crash",
                           "after": 1, "times": 1}], seed=SEED)
        p1 = _run_child(ckpt_root, adapter_dir,
                        fault_plan=plan.to_json())
        if p1.returncode != CRASH_EXIT:
            failures.append(
                f"run 1: expected CRASH_EXIT {CRASH_EXIT} mid-save, got "
                f"rc={p1.returncode}\n{p1.stdout[-2000:]}")
        if os.path.exists(adapter_dir):
            failures.append("run 1 published an adapter despite dying "
                            "mid-training")
        steps = sorted(d for d in os.listdir(ckpt_root)
                       if d.startswith("step_")) if \
            os.path.isdir(ckpt_root) else []
        print(f"[lora_soak] run 1 died mid-save as planned; "
              f"checkpoints on disk: {steps}", flush=True)

        # ---- run 2: resume, finish, publish the adapter --------------
        p2 = _run_child(ckpt_root, adapter_dir)
        info = {}
        for line in p2.stdout.splitlines():
            if line.startswith("LORA_CHILD "):
                info = json.loads(line[len("LORA_CHILD "):])
        if p2.returncode != 0:
            failures.append(f"run 2 rc={p2.returncode}\n"
                            f"{p2.stdout[-2000:]}")
        elif not info.get("resumed_from"):
            failures.append(
                f"run 2 did not resume from a checkpoint "
                f"(resumed_from={info.get('resumed_from')!r}) — the "
                f"SIGKILL survivor restarted from scratch\n"
                f"{p2.stdout[-1500:]}")
        elif int(info.get("final_step", 0)) < STEPS:
            failures.append(f"run 2 finished at step {info.get('final_step')}"
                            f" < {STEPS}")
        print(f"[lora_soak] run 2 resumed from "
              f"{info.get('resumed_from')} and finished step "
              f"{info.get('final_step')}", flush=True)

        if failures:
            raise SystemExit  # skip serving on a broken training phase

        # ---- serve the trained adapter mixed with base traffic -------
        from paddle_tpu.framework import compile_cache
        from paddle_tpu.lora import (AdapterStore, LoraConfig,
                                     clear_adapter, set_adapter)
        from paddle_tpu.serving import InferenceServer

        model, cfg = _build()
        store = AdapterStore(model, LoraConfig(rank=RANK, alpha=2.0 * RANK),
                             max_loaded=4)
        store.load("tenant", adapter_dir)   # fingerprint-checked
        GEO = dict(max_length=48, prefill_buckets=(16,))
        srv = InferenceServer(model, slots=2, adapter_store=store,
                              **GEO).start()

        def prompt(s, n=10):
            return np.random.default_rng(s).integers(
                0, cfg.vocab_size, (n,)).astype(np.int32)

        # warmup: the prefill bucket + decode + one sampled shape
        srv.submit(prompt(0), max_new_tokens=3).result(timeout=300)
        srv.submit(prompt(1), max_new_tokens=3, do_sample=True,
                   seed=1).result(timeout=300)
        warm = compile_cache.cache_stats()["compiles"]

        # mixed window: alternating base/tenant, greedy + seeded sampling
        handles = []
        for i in range(12):
            tid = "tenant" if i % 2 else None
            handles.append((i, tid, prompt(100 + i), srv.submit(
                prompt(100 + i), adapter_id=tid, max_new_tokens=6,
                do_sample=bool(i % 4 == 3), seed=200 + i)))
        lost = 0
        results = {}
        for i, tid, p, h in handles:
            try:
                results[i] = (tid, p, h.result(timeout=300))
            except Exception as e:
                lost += 1
                failures.append(f"request {i} (adapter={tid}) lost: {e!r}")
        steady = compile_cache.cache_stats()["compiles"] - warm
        if steady:
            failures.append(f"{steady} steady-state recompile(s) while "
                            f"serving mixed adapter traffic")
        # token parity vs solo generate (the registry round-trip must
        # serve exactly what training produced)
        from paddle_tpu.lora import load_adapter

        state, _ = load_adapter(adapter_dir, model)
        mismatches = 0
        for i in (1, 3, 4):
            if i not in results:
                continue   # its loss is already in failures above
            tid, p, got = results[i]
            if tid is None:
                clear_adapter(model)
            else:
                set_adapter(model, state)
            solo = model.generate(p[None], max_new_tokens=6,
                                  do_sample=bool(i % 4 == 3),
                                  seed=200 + i, **GEO)[0]
            if not np.array_equal(np.asarray(got), solo):
                mismatches += 1
                failures.append(
                    f"request {i} (adapter={tid}) diverged from solo "
                    f"generate: {np.asarray(got)} vs {solo}")
        clear_adapter(model)
        srv.shutdown(drain=True, timeout=60)
        print(f"[lora_soak] served {len(results)}/12 mixed requests, "
              f"{lost} lost, {steady} recompiles, "
              f"{mismatches} divergences", flush=True)
    except SystemExit:
        pass
    finally:
        if args.keep:
            print(f"[lora_soak] scratch kept at {scratch}", flush=True)
        else:
            shutil.rmtree(scratch, ignore_errors=True)

    dt = time.monotonic() - t0
    if failures:
        print(f"[lora_soak] FAIL in {dt:.0f}s:", flush=True)
        for f in failures:
            print(f"  - {f}", flush=True)
        return 1
    print(f"[lora_soak] PASS in {dt:.0f}s (train -> die mid-save -> "
          f"resume -> register -> serve mixed: zero lost, zero "
          f"recompiles, zero divergence)", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
