"""Break down the b8 bench step: fwd / fwd+bwd / full step, flash variants.

Run: python -m tools.bench_profile
"""
import time

import jax
import jax.numpy as jnp
import numpy as np

import paddle_tpu
from paddle_tpu import amp
from paddle_tpu.framework.jit import TrainStep
from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                   gpt_flops_per_token, gpt_loss_fn)
from paddle_tpu.nn.layer import buffer_state, functional_call, param_state
from paddle_tpu.optimizer import AdamW
from bench import _chip_peak_flops


def timeit(fn, *args, n=10, warmup=2):
    for _ in range(warmup):
        out = fn(*args)
    # tpu-lint: disable=R1(benchmark warmup fence — the timed region must start with nothing in flight)
    jax.tree.map(lambda x: x.block_until_ready()
                 if hasattr(x, "block_until_ready") else x, out)
    # host-read sync (block_until_ready is unreliable through the tunnel)
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(leaf).reshape(-1)[0])
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(leaf).reshape(-1)[0])
    return (time.perf_counter() - t0) / n


def main(batch=8, seq=1024, flash=True, loss_chunk=256):
    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_heads=16, max_position_embeddings=seq,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_flash_attention=flash, loss_chunk=loss_chunk,
                    dtype="bfloat16")
    paddle_tpu.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    params = jax.tree.map(lambda x: jnp.array(x, copy=True), param_state(model))
    buffers = buffer_state(model)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)

    tok = batch * seq
    fpt = gpt_flops_per_token(cfg, seq)
    peak = _chip_peak_flops()

    @jax.jit
    def fwd(p, ids):
        out, _ = functional_call(model, p, buffers, ids, ids)
        return out

    @jax.jit
    def fwdbwd(p, ids):
        def loss(p):
            out, _ = functional_call(model, p, buffers, ids, ids)
            return out

        l, g = jax.value_and_grad(loss)(p)
        return l, g

    t_f = timeit(fwd, params, ids)
    print(f"fwd          {t_f*1e3:8.2f} ms  ({tok/t_f:9.0f} tok/s, "
          f"'fwd-MFU' {tok/t_f*fpt/3*1/peak:.3f} of peak w/ 2N/tok)")
    t_fb = timeit(fwdbwd, params, ids)
    print(f"fwd+bwd      {t_fb*1e3:8.2f} ms  (MFU {tok/t_fb*fpt/peak:.4f})")

    step = TrainStep(model, opt, loss_fn=None)
    t_s = timeit(lambda b: step(b), (np.asarray(ids), np.asarray(ids)))
    print(f"full step    {t_s*1e3:8.2f} ms  (MFU {tok/t_s*fpt/peak:.4f}) "
          f"[optimizer+transfer overhead {100*(t_s-t_fb)/t_s:.1f}%]")


if __name__ == "__main__":
    import sys

    flash = "--noflash" not in sys.argv
    main(flash=flash)
