"""Break down the b8 bench step: fwd / fwd+bwd / full step, flash variants
— plus the per-step collective-overlap breakdown (``--overlap``) that
ROADMAP item 1 (overlap-scheduled distributed training) gates on.

``--overlap`` runs N instrumented train steps under the profiler's host
span recorder and splits each step's wall time into:

- **compute** — the measured fwd+bwd program time (the part overlap
  scheduling cannot shrink);
- **collective** — host spans whose names mark collective work
  (``allreduce``/``psum``/``all_gather``/... — today's serial schedule
  runs them inside the one compiled program, so this column reads 0
  until bucketed/async collectives land and register their own spans);
- **host_stall** — input-pipeline / H2D spans (``h2d_prefetch`` et al.)
  overlapping the step;
- **non_compute residual** — step wall minus all of the above
  (optimizer + dispatch + the collective time hiding inside the fused
  program). The overlap work drives THIS number toward zero per step;
  the table + JSON line make the trajectory visible per run.

Printed as a table and emitted as one bench-style JSON line
(``<model>_step_overlap_breakdown``), so ``bench_sweep``-style tooling
can archive it next to the MFU numbers.

Run: python -m tools.bench_profile            # classic fwd/bwd/step timings
     python -m tools.bench_profile --overlap  # per-step breakdown table
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def timeit(fn, *args, n=10, warmup=2):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    # tpu-lint: disable=R1(benchmark warmup fence — the timed region must start with nothing in flight)
    jax.tree.map(lambda x: x.block_until_ready()
                 if hasattr(x, "block_until_ready") else x, out)
    # host-read sync (block_until_ready is unreliable through the tunnel)
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(leaf).reshape(-1)[0])
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(leaf).reshape(-1)[0])
    return (time.perf_counter() - t0) / n


# --------------------------------------------- overlap breakdown (pure)
#: span-name classification for the breakdown — "existing profiler
#: events" in, buckets out. Collective names cover the wrappers
#: distributed/collective.py and future bucketed-allreduce spans will
#: register; host-stall covers the input pipeline's spans.
_COLLECTIVE_KEYS = ("allreduce", "all_reduce", "psum", "pmean",
                    "all_gather", "allgather", "reduce_scatter",
                    "all_to_all", "a2a", "collective", "ppermute")
_HOST_STALL_KEYS = ("h2d", "prefetch", "stall", "data_wait")


def classify_span(name: str) -> str:
    low = str(name).lower()
    if any(k in low for k in _COLLECTIVE_KEYS):
        return "collective"
    if any(k in low for k in _HOST_STALL_KEYS):
        return "host_stall"
    if low == "step":
        return "step"
    return "other"


def _overlap_s(t0, t1, w0, w1):
    """Seconds of [t0, t1] falling inside the window [w0, w1]."""
    return max(0.0, min(t1, w1) - max(t0, w0))


def overlap_breakdown(spans, compute_s=None):
    """Split each recorded ``step`` span's wall time into compute /
    collective / host_stall / residual using the other host spans that
    overlap it. ``spans`` is ``[(name, t0, t1), ...]`` (the host event
    recorder's shape); ``compute_s`` is the separately measured
    compute-only (fwd+bwd) program time attributed to every step.
    Returns ``{"steps": [per-step rows], "mean": aggregate row}``."""
    steps = sorted(((t0, t1) for name, t0, t1 in spans
                    if classify_span(name) == "step"),
                   key=lambda w: w[0])
    others = [(classify_span(name), t0, t1) for name, t0, t1 in spans
              if classify_span(name) in ("collective", "host_stall")]
    rows = []
    for i, (w0, w1) in enumerate(steps):
        wall = w1 - w0
        coll = sum(_overlap_s(t0, t1, w0, w1)
                   for kind, t0, t1 in others if kind == "collective")
        stall = sum(_overlap_s(t0, t1, w0, w1)
                    for kind, t0, t1 in others if kind == "host_stall")
        comp = min(wall, compute_s) if compute_s is not None else 0.0
        resid = max(0.0, wall - comp - coll - stall)
        rows.append({"step": i, "wall_ms": round(wall * 1e3, 3),
                     "compute_ms": round(comp * 1e3, 3),
                     "collective_ms": round(coll * 1e3, 3),
                     "host_stall_ms": round(stall * 1e3, 3),
                     "non_compute_ms": round(resid * 1e3, 3)})
    mean = {}
    if rows:
        for key in ("wall_ms", "compute_ms", "collective_ms",
                    "host_stall_ms", "non_compute_ms"):
            mean[key] = round(sum(r[key] for r in rows) / len(rows), 3)
        mean["non_compute_frac"] = round(
            (mean["collective_ms"] + mean["host_stall_ms"]
             + mean["non_compute_ms"]) / mean["wall_ms"], 4) \
            if mean["wall_ms"] else 0.0
    return {"steps": rows, "mean": mean}


def print_breakdown_table(breakdown) -> None:
    cols = ("step", "wall_ms", "compute_ms", "collective_ms",
            "host_stall_ms", "non_compute_ms")
    print("".join(f"{c:>16}" for c in cols))
    for r in breakdown["steps"]:
        print("".join(f"{r[c]:>16}" for c in cols))
    m = breakdown["mean"]
    if m:
        print("".join(f"{v:>16}" for v in
                      ("mean", m["wall_ms"], m["compute_ms"],
                       m["collective_ms"], m["host_stall_ms"],
                       m["non_compute_ms"])))
        print(f"non-compute fraction of step wall: "
              f"{m['non_compute_frac']:.1%}  (the number the overlap "
              f"scheduling work drives toward 0)")


def run_overlap(batch=4, seq=128, steps=5, flash=False):
    """The ``--overlap`` mode: instrumented steps on a small config
    (CPU-safe), classic host spans in, breakdown table + JSON out."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu import profiler
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     param_state)
    from paddle_tpu.optimizer import AdamW

    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=seq,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_flash_attention=flash)
    paddle_tpu.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4)
    params = jax.tree.map(lambda x: jnp.array(x, copy=True),
                          param_state(model))
    buffers = buffer_state(model)
    rng = np.random.default_rng(0)
    ids = np.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                     np.int32)

    @jax.jit
    def fwdbwd(p, x):
        def loss(p):
            out, _ = functional_call(model, p, buffers,
                                     jnp.asarray(x), jnp.asarray(x))
            return out

        return jax.value_and_grad(loss)(p)

    t_compute = timeit(fwdbwd, params, ids, n=max(3, steps), warmup=2)
    step = TrainStep(model, opt, loss_fn=None)
    step((ids, ids))   # compile outside the recorded window

    rec = profiler._recorder
    prev_enabled = rec.enabled
    rec.clear()
    rec.enabled = True
    try:
        for _ in range(steps):
            step((ids, ids))
        # tpu-lint: disable=R1(benchmark fence — the last step's wall time must include its device work)
        float(np.asarray(step((ids, ids))))
        with rec.lock:
            spans = list(rec.spans)
    finally:
        rec.enabled = prev_enabled
    breakdown = overlap_breakdown(spans, compute_s=t_compute)
    print_breakdown_table(breakdown)
    record = {
        "metric": "gpt_step_overlap_breakdown",
        "value": breakdown["mean"].get("non_compute_frac", 0.0),
        "unit": "frac_of_step_wall",
        "extra": {"steps": len(breakdown["steps"]),
                  **breakdown["mean"],
                  # the raw fwd+bwd program time, distinct from the
                  # per-step (wall-clamped) compute_ms mean above
                  "fwdbwd_ms": round(t_compute * 1e3, 3),
                  "batch": batch, "seq": seq,
                  "backend": jax.default_backend()},
    }
    print(json.dumps(record))
    return breakdown


def main(batch=8, seq=1024, flash=True, loss_chunk=256):
    import jax
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu import amp
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       gpt_flops_per_token, gpt_loss_fn)  # noqa: F401
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     param_state)
    from paddle_tpu.optimizer import AdamW
    from bench import _chip_peak_flops

    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_heads=16, max_position_embeddings=seq,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_flash_attention=flash, loss_chunk=loss_chunk,
                    dtype="bfloat16")
    paddle_tpu.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    params = jax.tree.map(lambda x: jnp.array(x, copy=True), param_state(model))
    buffers = buffer_state(model)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)

    tok = batch * seq
    fpt = gpt_flops_per_token(cfg, seq)
    peak = _chip_peak_flops()

    @jax.jit
    def fwd(p, ids):
        out, _ = functional_call(model, p, buffers, ids, ids)
        return out

    @jax.jit
    def fwdbwd(p, ids):
        def loss(p):
            out, _ = functional_call(model, p, buffers, ids, ids)
            return out

        l, g = jax.value_and_grad(loss)(p)
        return l, g

    t_f = timeit(fwd, params, ids)
    print(f"fwd          {t_f*1e3:8.2f} ms  ({tok/t_f:9.0f} tok/s, "
          f"'fwd-MFU' {tok/t_f*fpt/3*1/peak:.3f} of peak w/ 2N/tok)")
    t_fb = timeit(fwdbwd, params, ids)
    print(f"fwd+bwd      {t_fb*1e3:8.2f} ms  (MFU {tok/t_fb*fpt/peak:.4f})")

    step = TrainStep(model, opt, loss_fn=None)
    t_s = timeit(lambda b: step(b), (np.asarray(ids), np.asarray(ids)))
    print(f"full step    {t_s*1e3:8.2f} ms  (MFU {tok/t_s*fpt/peak:.4f}) "
          f"[optimizer+transfer overhead {100*(t_s-t_fb)/t_s:.1f}%]")


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--noflash", action="store_true")
    ap.add_argument("--overlap", action="store_true",
                    help="per-step compute/collective/host-stall "
                         "breakdown (table + JSON) instead of the b8 "
                         "timings")
    ap.add_argument("--steps", type=int, default=5)
    args = ap.parse_args()
    if args.overlap:
        # flash stays off here: the breakdown targets schedule structure,
        # not kernel choice, and the small config must stay CPU-safe
        run_overlap(steps=args.steps)
        sys.exit(0)
    main(flash=not args.noflash)
