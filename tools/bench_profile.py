"""Break down the b8 bench step: fwd / fwd+bwd / full step, flash variants
— plus the per-step collective-overlap breakdown (``--overlap``) that
ROADMAP item 1 (overlap-scheduled distributed training) gates on.

``--overlap`` runs N instrumented train steps under the profiler's host
span recorder and splits each step's wall time into:

- **compute** — the measured fwd+bwd program time (the part overlap
  scheduling cannot shrink);
- **collective** — host spans whose names mark collective work
  (``allreduce``/``psum``/``all_gather``/... — today's serial schedule
  runs them inside the one compiled program, so this column reads 0
  until bucketed/async collectives land and register their own spans);
- **host_stall** — input-pipeline / H2D spans (``h2d_prefetch`` et al.)
  overlapping the step;
- **non_compute residual** — step wall minus all of the above
  (optimizer + dispatch + the collective time hiding inside the fused
  program). The overlap work drives THIS number toward zero per step;
  the table + JSON line make the trajectory visible per run.

Printed as a table and emitted as one bench-style JSON line
(``<model>_step_overlap_breakdown``), so ``bench_sweep``-style tooling
can archive it next to the MFU numbers.

With ``--distributed`` the ``--overlap`` mode runs the REAL target of
the work — ``DistributedTrainStep`` on the multi-device mesh — twice on
the same config: once with the serial schedule (knobs off) and once
with ``overlap_grad_reduce=True`` (bucketed reverse-backward reduction
+ ZeRO weight-update sharding under ``--stage >= 1``). Each run emits
its own ``gpt_step_overlap_breakdown`` record tagged
``schedule: serial|bucketed``; per-bucket collective spans (named
``allreduce/bucketNN``, cost measured in isolation via a shard_map psum
of the bucket's payload and attributed into each step window) make the
bucketed schedule visible in the table. ``--buckets N`` sweeps bucket
count; ``--json-out`` archives the paired records + reduction factor as
one artifact for ``bench_sweep``-style diffing (and for
``robustness_gate --overlap``, which fails on a non_compute_frac
regression).

Run: python -m tools.bench_profile            # classic fwd/bwd/step timings
     python -m tools.bench_profile --overlap  # per-step breakdown table
     python -m tools.bench_profile --overlap --distributed \
         [--stage 1] [--buckets N] [--bucket-mb MB] [--json-out PATH]
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def timeit(fn, *args, n=10, warmup=2):
    import jax

    for _ in range(warmup):
        out = fn(*args)
    # tpu-lint: disable=R1(benchmark warmup fence — the timed region must start with nothing in flight)
    jax.tree.map(lambda x: x.block_until_ready()
                 if hasattr(x, "block_until_ready") else x, out)
    # host-read sync (block_until_ready is unreliable through the tunnel)
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(leaf).reshape(-1)[0])
    t0 = time.perf_counter()
    for _ in range(n):
        out = fn(*args)
    leaf = jax.tree.leaves(out)[0]
    float(np.asarray(leaf).reshape(-1)[0])
    return (time.perf_counter() - t0) / n


# --------------------------------------------- overlap breakdown (pure)
#: span-name classification for the breakdown — "existing profiler
#: events" in, buckets out. Collective names cover the wrappers
#: distributed/collective.py and future bucketed-allreduce spans will
#: register; host-stall covers the input pipeline's spans.
_COLLECTIVE_KEYS = ("allreduce", "all_reduce", "psum", "pmean",
                    "all_gather", "allgather", "reduce_scatter",
                    "all_to_all", "a2a", "collective", "ppermute")
_HOST_STALL_KEYS = ("h2d", "prefetch", "stall", "data_wait")


def classify_span(name: str) -> str:
    low = str(name).lower()
    if any(k in low for k in _COLLECTIVE_KEYS):
        return "collective"
    if any(k in low for k in _HOST_STALL_KEYS):
        return "host_stall"
    if low == "step":
        return "step"
    return "other"


def _overlap_s(t0, t1, w0, w1):
    """Seconds of [t0, t1] falling inside the window [w0, w1]."""
    return max(0.0, min(t1, w1) - max(t0, w0))


def overlap_breakdown(spans, compute_s=None):
    """Split each recorded ``step`` span's wall time into compute /
    collective / host_stall / residual using the other host spans that
    overlap it. ``spans`` is ``[(name, t0, t1), ...]`` (the host event
    recorder's shape); ``compute_s`` is the separately measured
    compute-only (fwd+bwd) program time attributed to every step.
    Returns ``{"steps": [per-step rows], "mean": aggregate row}``."""
    steps = sorted(((t0, t1) for name, t0, t1 in spans
                    if classify_span(name) == "step"),
                   key=lambda w: w[0])
    others = [(classify_span(name), t0, t1) for name, t0, t1 in spans
              if classify_span(name) in ("collective", "host_stall")]
    rows = []
    for i, (w0, w1) in enumerate(steps):
        wall = w1 - w0
        coll = sum(_overlap_s(t0, t1, w0, w1)
                   for kind, t0, t1 in others if kind == "collective")
        stall = sum(_overlap_s(t0, t1, w0, w1)
                    for kind, t0, t1 in others if kind == "host_stall")
        comp = min(wall, compute_s) if compute_s is not None else 0.0
        resid = max(0.0, wall - comp - coll - stall)
        rows.append({"step": i, "wall_ms": round(wall * 1e3, 3),
                     "compute_ms": round(comp * 1e3, 3),
                     "collective_ms": round(coll * 1e3, 3),
                     "host_stall_ms": round(stall * 1e3, 3),
                     "non_compute_ms": round(resid * 1e3, 3)})
    mean = {}
    if rows:
        for key in ("wall_ms", "compute_ms", "collective_ms",
                    "host_stall_ms", "non_compute_ms"):
            mean[key] = round(sum(r[key] for r in rows) / len(rows), 3)
        mean["non_compute_frac"] = round(
            (mean["collective_ms"] + mean["host_stall_ms"]
             + mean["non_compute_ms"]) / mean["wall_ms"], 4) \
            if mean["wall_ms"] else 0.0
    return {"steps": rows, "mean": mean}


def print_breakdown_table(breakdown) -> None:
    cols = ("step", "wall_ms", "compute_ms", "collective_ms",
            "host_stall_ms", "non_compute_ms")
    print("".join(f"{c:>16}" for c in cols))
    for r in breakdown["steps"]:
        print("".join(f"{r[c]:>16}" for c in cols))
    m = breakdown["mean"]
    if m:
        print("".join(f"{v:>16}" for v in
                      ("mean", m["wall_ms"], m["compute_ms"],
                       m["collective_ms"], m["host_stall_ms"],
                       m["non_compute_ms"])))
        print(f"non-compute fraction of step wall: "
              f"{m['non_compute_frac']:.1%}  (the number the overlap "
              f"scheduling work drives toward 0)")


def run_overlap(batch=4, seq=128, steps=5, flash=False):
    """The ``--overlap`` mode: instrumented steps on a small config
    (CPU-safe), classic host spans in, breakdown table + JSON out."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu import profiler
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     param_state)
    from paddle_tpu.optimizer import AdamW

    cfg = GPTConfig(vocab_size=512, hidden_size=64, num_layers=2,
                    num_heads=4, max_position_embeddings=seq,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_flash_attention=flash)
    paddle_tpu.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4)
    params = jax.tree.map(lambda x: jnp.array(x, copy=True),
                          param_state(model))
    buffers = buffer_state(model)
    rng = np.random.default_rng(0)
    ids = np.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                     np.int32)

    @jax.jit
    def fwdbwd(p, x):
        def loss(p):
            out, _ = functional_call(model, p, buffers,
                                     jnp.asarray(x), jnp.asarray(x))
            return out

        return jax.value_and_grad(loss)(p)

    t_compute = timeit(fwdbwd, params, ids, n=max(3, steps), warmup=2)
    step = TrainStep(model, opt, loss_fn=None)
    step((ids, ids))   # compile outside the recorded window

    rec = profiler._recorder
    prev_enabled = rec.enabled
    rec.clear()
    rec.enabled = True
    try:
        for _ in range(steps):
            step((ids, ids))
        # tpu-lint: disable=R1(benchmark fence — the last step's wall time must include its device work)
        float(np.asarray(step((ids, ids))))
        with rec.lock:
            spans = list(rec.spans)
    finally:
        rec.enabled = prev_enabled
    breakdown = overlap_breakdown(spans, compute_s=t_compute)
    print_breakdown_table(breakdown)
    record = {
        "metric": "gpt_step_overlap_breakdown",
        "value": breakdown["mean"].get("non_compute_frac", 0.0),
        "unit": "frac_of_step_wall",
        "extra": {"steps": len(breakdown["steps"]),
                  "schedule": "serial",
                  **breakdown["mean"],
                  # the raw fwd+bwd program time, distinct from the
                  # per-step (wall-clamped) compute_ms mean above
                  "fwdbwd_ms": round(t_compute * 1e3, 3),
                  "batch": batch, "seq": seq,
                  "backend": jax.default_backend()},
    }
    print(json.dumps(record))
    return breakdown


# ------------------------------------------- distributed overlap breakdown
def _measure_bucket_allreduce_ms(mesh, axis, buckets, shapes, dtypes,
                                 n=3):
    """Per-bucket collective cost, measured in ISOLATION: one compiled
    shard_map program all-reducing the bucket's grad payload over
    ``axis``. The numbers are attributed into each recorded step window
    as ``allreduce/bucketNN`` spans — a measured estimate of where the
    schedule spends its collective time, not an in-program trace (host
    callbacks inside the step would be an R1 violation and would perturb
    the thing being measured)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec
    from paddle_tpu.framework.jax_compat import shard_map

    def body(xs):
        return tuple(jax.lax.psum(x, axis) for x in xs)

    # ONE compiled callable; each bucket's payload is a different pytree
    # signature, so jit's own cache holds one executable per bucket
    spec = PartitionSpec()
    f = jax.jit(shard_map(body, mesh=mesh, in_specs=(spec,),
                          out_specs=spec))

    out = []
    for b in buckets:
        names = b["params"]
        args = tuple(jnp.zeros(shapes[p], dtypes[p]) for p in names)
        t = timeit(lambda: f(args), n=n, warmup=1)
        out.append({"bucket": b["bucket"], "bytes": b["bytes"],
                    "params": len(names), "allreduce_ms": round(t * 1e3, 3)})
    return out


def _synthesize_bucket_spans(step_windows, bucket_ms, prefix="allreduce"):
    """Lay the isolation-measured bucket costs into each step window as
    consecutive spans so :func:`overlap_breakdown` can classify them."""
    spans = []
    for (w0, w1) in step_windows:
        t = w0
        for b in bucket_ms:
            dur = b["allreduce_ms"] / 1e3
            spans.append((f"{prefix}/bucket{b['bucket']:02d}", t, t + dur))
            t += dur
    return spans


def run_overlap_distributed(batch=8, seq=128, steps=3, stage=1,
                            bucket_mb=8.0, bucket_count=None,
                            hidden=512, layers=2, vocab=4096,
                            json_out=None, serial_stage=0):
    """``--overlap --distributed``: the before/after measurement ROADMAP
    item 1 gates on. Runs the SAME model/batch config through
    ``DistributedTrainStep`` twice and emits one
    ``gpt_step_overlap_breakdown`` record per schedule plus a paired
    artifact (``--json-out``) carrying the reduction factor.

    The pairing is *pre-PR schedule vs new schedule*, not a single-knob
    ablation: ``serial`` is the defaults as they shipped before the
    overlap work (``overlap_grad_reduce=False``, ``sharding_stage=
    serial_stage`` = 0 — fused tail all-reduce, fully replicated weight
    update), and ``bucketed`` is the restructured step
    (``overlap_grad_reduce=True`` at ``--stage``, default 1 — bucketed
    reverse-backward collectives plus the ZeRO-style sharded update, so
    the weight update stops being replicated work). Pass
    ``--serial-stage`` equal to ``--stage`` for the bucketing-only
    ablation; on a single-core host mesh that delta is scheduler noise
    (overlap cannot hide latency when devices timeshare one core), which
    is exactly why the gate pins the schedule-level pairing instead.

    Compute attribution: a single-device fwd+bwd program on the batch —
    the work the schedule cannot shrink. On a multi-chip backend each
    chip holds ``batch/n``, so the local-batch program is timed; on the
    host-platform CPU mesh the virtual devices timeshare the same cores,
    so the FULL-batch program is the right serialized-compute baseline.
    """
    import jax
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu import profiler
    from paddle_tpu.distributed.mesh import init_mesh, set_mesh
    from paddle_tpu.distributed.shard import DistributedTrainStep
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     param_state)
    from paddle_tpu.optimizer import AdamW

    ndev = jax.device_count()
    cfg = GPTConfig(vocab_size=vocab, hidden_size=hidden, num_layers=layers,
                    num_heads=max(2, hidden // 64),
                    max_position_embeddings=seq,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_flash_attention=False)
    rng = np.random.default_rng(0)
    ids = np.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                     np.int32)

    # compute baseline: fwd+bwd only, one device, no collectives
    per_device = jax.default_backend() != "cpu" and ndev > 1
    local = ids[: max(1, batch // ndev)] if per_device else ids
    paddle_tpu.seed(0)
    ref_model = GPTForCausalLM(cfg)
    ref_params = jax.tree.map(lambda x: jnp.array(x, copy=True),
                              param_state(ref_model))
    ref_buffers = buffer_state(ref_model)

    @jax.jit
    def fwdbwd(p, x):
        def loss(p):
            out, _ = functional_call(ref_model, p, ref_buffers,
                                     jnp.asarray(x), jnp.asarray(x))
            return out

        return jax.value_and_grad(loss)(p)

    t_compute = timeit(fwdbwd, ref_params, local, n=max(3, steps), warmup=2)
    del ref_params

    results = {}
    for schedule in ("serial", "bucketed"):
        sched_stage = stage if schedule == "bucketed" else serial_stage
        mesh = init_mesh(sdp=ndev)
        paddle_tpu.seed(0)
        model = GPTForCausalLM(cfg)
        step = DistributedTrainStep(
            model, AdamW(learning_rate=1e-4), loss_fn=None,
            sharding_stage=sched_stage,
            overlap_grad_reduce=(schedule == "bucketed"),
            bucket_size_mb=bucket_mb, bucket_count=bucket_count)
        step((ids, ids))   # compile outside the recorded window

        rec = profiler._recorder
        prev_enabled = rec.enabled
        rec.clear()
        rec.enabled = True
        try:
            for _ in range(steps):
                step((ids, ids))
            # tpu-lint: disable=R1(benchmark fence — the last step's wall time must include its device work)
            float(np.asarray(step((ids, ids))))
            with rec.lock:
                spans = list(rec.spans)
        finally:
            rec.enabled = prev_enabled

        windows = sorted(((t0, t1) for name, t0, t1 in spans
                          if classify_span(name) == "step"),
                         key=lambda w: w[0])
        schedule_buckets = step.collective_schedule() or [
            {"bucket": 0, "bytes": sum(
                int(v.size) * int(jnp.dtype(v.dtype).itemsize)
                for v in step.params.values()),
             "params": list(step.params)}]
        shapes = {k: v.shape for k, v in step.params.items()}
        dtypes = {k: v.dtype for k, v in step.params.items()}
        bucket_ms = _measure_bucket_allreduce_ms(
            mesh, "sdp", schedule_buckets, shapes, dtypes)
        spans += _synthesize_bucket_spans(windows, bucket_ms)
        breakdown = overlap_breakdown(spans, compute_s=t_compute)
        print(f"--- schedule={schedule} stage={sched_stage} "
              f"buckets={len(schedule_buckets)} devices={ndev}")
        print_breakdown_table(breakdown)
        record = {
            "metric": "gpt_step_overlap_breakdown",
            "value": breakdown["mean"].get("non_compute_frac", 0.0),
            "unit": "frac_of_step_wall",
            "extra": {"steps": len(breakdown["steps"]),
                      "schedule": schedule,
                      "sharding_stage": sched_stage,
                      "devices": ndev,
                      **breakdown["mean"],
                      "fwdbwd_ms": round(t_compute * 1e3, 3),
                      "buckets": bucket_ms,
                      "zero_fallback_params":
                          list(step.zero_fallback_params),
                      "batch": batch, "seq": seq, "hidden": hidden,
                      "layers": layers, "vocab": vocab,
                      "backend": jax.default_backend()},
        }
        print(json.dumps(record))
        results[schedule] = record
        del step, model
        set_mesh(None)

    serial = results["serial"]["value"]
    bucketed = results["bucketed"]["value"]
    reduction = round(serial / bucketed, 3) if bucketed else float("inf")
    summary = {"config": {"batch": batch, "seq": seq, "hidden": hidden,
                          "layers": layers, "vocab": vocab, "stage": stage,
                          "serial_stage": serial_stage,
                          "steps": steps, "bucket_mb": bucket_mb,
                          "bucket_count": bucket_count},
               "serial": results["serial"],
               "bucketed": results["bucketed"],
               "non_compute_frac_reduction": reduction}
    print(f"non_compute_frac: serial={serial:.4f} bucketed={bucketed:.4f} "
          f"reduction={reduction}x")
    if json_out:
        with open(json_out, "w") as f:
            json.dump(summary, f, indent=1, sort_keys=True)
        print(f"wrote {json_out}")
    return summary


def main(batch=8, seq=1024, flash=True, loss_chunk=256):
    import jax
    import jax.numpy as jnp

    import paddle_tpu
    from paddle_tpu import amp
    from paddle_tpu.framework.jit import TrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       gpt_flops_per_token, gpt_loss_fn)  # noqa: F401
    from paddle_tpu.nn.layer import (buffer_state, functional_call,
                                     param_state)
    from paddle_tpu.optimizer import AdamW
    from bench import _chip_peak_flops

    cfg = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                    num_heads=16, max_position_embeddings=seq,
                    hidden_dropout_prob=0.0, attention_dropout_prob=0.0,
                    use_flash_attention=flash, loss_chunk=loss_chunk,
                    dtype="bfloat16")
    paddle_tpu.seed(0)
    model = GPTForCausalLM(cfg)
    opt = AdamW(learning_rate=1e-4, weight_decay=0.01)
    model, opt = amp.decorate(model, opt, level="O2", dtype="bfloat16")
    params = jax.tree.map(lambda x: jnp.array(x, copy=True), param_state(model))
    buffers = buffer_state(model)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)

    tok = batch * seq
    fpt = gpt_flops_per_token(cfg, seq)
    peak = _chip_peak_flops()

    @jax.jit
    def fwd(p, ids):
        out, _ = functional_call(model, p, buffers, ids, ids)
        return out

    @jax.jit
    def fwdbwd(p, ids):
        def loss(p):
            out, _ = functional_call(model, p, buffers, ids, ids)
            return out

        l, g = jax.value_and_grad(loss)(p)
        return l, g

    t_f = timeit(fwd, params, ids)
    print(f"fwd          {t_f*1e3:8.2f} ms  ({tok/t_f:9.0f} tok/s, "
          f"'fwd-MFU' {tok/t_f*fpt/3*1/peak:.3f} of peak w/ 2N/tok)")
    t_fb = timeit(fwdbwd, params, ids)
    print(f"fwd+bwd      {t_fb*1e3:8.2f} ms  (MFU {tok/t_fb*fpt/peak:.4f})")

    step = TrainStep(model, opt, loss_fn=None)
    t_s = timeit(lambda b: step(b), (np.asarray(ids), np.asarray(ids)))
    print(f"full step    {t_s*1e3:8.2f} ms  (MFU {tok/t_s*fpt/peak:.4f}) "
          f"[optimizer+transfer overhead {100*(t_s-t_fb)/t_s:.1f}%]")


if __name__ == "__main__":
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--noflash", action="store_true")
    ap.add_argument("--overlap", action="store_true",
                    help="per-step compute/collective/host-stall "
                         "breakdown (table + JSON) instead of the b8 "
                         "timings")
    ap.add_argument("--distributed", action="store_true",
                    help="run the breakdown through DistributedTrainStep "
                         "on the device mesh, serial vs bucketed schedule "
                         "(the before/after pair ROADMAP item 1 gates on)")
    ap.add_argument("--stage", type=int, default=1,
                    help="sharding_stage for the bucketed schedule "
                         "(default 1: ZeRO weight-update sharding "
                         "engages)")
    ap.add_argument("--serial-stage", type=int, default=0,
                    help="sharding_stage for the serial baseline "
                         "(default 0 — the pre-overlap default schedule: "
                         "fused tail all-reduce + replicated update; set "
                         "equal to --stage for a bucketing-only ablation)")
    ap.add_argument("--buckets", type=int, default=None,
                    help="bucket-count override for the bucketed "
                         "schedule (sweeps; default: size-targeted via "
                         "--bucket-mb)")
    ap.add_argument("--bucket-mb", type=float, default=8.0,
                    help="bucket size target in MB for --distributed "
                         "(default 8.0 — ~4 buckets over the default "
                         "34MB-of-grads config)")
    ap.add_argument("--json-out", default=None,
                    help="write the paired serial/bucketed records + "
                         "reduction factor as one JSON artifact")
    ap.add_argument("--steps", type=int, default=5)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--seq", type=int, default=None,
                    help="sequence length (default: 1024 for the MFU "
                         "run, 128 for --distributed)")
    args = ap.parse_args()
    if args.overlap and args.distributed:
        # the host-platform mesh needs its virtual devices BEFORE jax
        # initializes; harmless when a real multi-chip backend is up
        if "xla_force_host_platform_device_count" not in \
                os.environ.get("XLA_FLAGS", "") and \
                os.environ.get("JAX_PLATFORMS", "").startswith("cpu"):
            os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                                       + " --xla_force_host_platform_"
                                         "device_count=8")
        run_overlap_distributed(steps=args.steps, stage=args.stage,
                                batch=args.batch, seq=args.seq or 128,
                                bucket_mb=args.bucket_mb,
                                bucket_count=args.buckets,
                                json_out=args.json_out,
                                serial_stage=args.serial_stage)
        sys.exit(0)
    if args.overlap:
        # flash stays off here: the breakdown targets schedule structure,
        # not kernel choice, and the small config must stay CPU-safe
        run_overlap(steps=args.steps)
        sys.exit(0)
    main(batch=args.batch, seq=args.seq or 1024, flash=not args.noflash)
